"""Continuous batching: a request queue feeding the fused decode loop.

The serving-side counterpart of the training stack's steps-per-loop
discipline: requests of ragged lengths share ONE compiled prefill and
ONE compiled decode program — slots that are empty or whose request
already finished ride along masked (``active=False`` holds their state),
so admission and eviction never trigger a recompile.  A request's life:

    submit() → queue → slot admission (batched prefill; TTFT stops
    here — the prefill emits the first token) → fused decode windows
    (``decode_steps`` tokens per dispatch) → eviction on EOS, token
    budget, or the cache's ``max_len`` → slot freed for the next
    admission.

Because every slot's computation depends only on its own cache lane and
token (batch ops are elementwise/vmapped; the model-axis psums reduce
over devices, not slots), a request decodes the exact same tokens
whether it runs alone or interleaved with arrivals and departures — the
property the continuous-batching goldens pin.

Per-token telemetry flows through the PR 4 sink: ``serve/ttft_ms`` and
``serve/inter_token_ms`` histograms (a fused window attributes
``window/K`` to each of its tokens), ``serve/queue_depth`` gauge,
``serve/requests``/``serve/tokens`` counters, and one ``kind="serve"``
record per completed request — carrying the engine's ``kv_layout`` —
(rendered by ``tools/telemetry_report.py``, schema-gated by its
``--check``).  Paged engines additionally emit the
``serve/kv_blocks_free``/``serve/kv_blocks_used`` pool gauges on every
reservation/release; a paged run missing them fails the schema gate.
"""
from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Optional

import numpy as np

from autodist_tpu import telemetry


class OverloadedError(RuntimeError):
    """The admission queue is full: the request was *shed* (coded —
    ``serve/shed`` counter) instead of queued into unbounded latency.
    Callers back off and resubmit; a router routes to another replica."""

    code = "serve/overloaded"


# Every way a request can end.  The first three are the classic decode
# terminals; the rest are the graceful-degradation terminals (deadline
# pressure, overload shedding, engine drain, caller-side cancellation —
# the router's hedge loser) — absent entirely when no
# deadline/queue-bound/drain/cancel is in play.
FINISH_REASONS = ("eos", "max_tokens", "max_len", "deadline_exceeded",
                  "shed", "drained", "cancelled")


@dataclasses.dataclass
class Request:
    """One generation request (token ids in, token ids out)."""

    rid: str
    prompt: list
    max_new_tokens: int
    eos_id: Optional[int] = None
    submit_s: float = 0.0
    deadline_s: Optional[float] = None   # absolute (perf_counter) deadline
    # Sampling seed (engines with temperature > 0): the per-request key
    # the gumbel-max epilogue folds per emitted token, so a request
    # decodes the same stream wherever/whenever it runs (the
    # interleave-parity contract extended to sampling).  Ignored by
    # greedy engines.
    seed: int = 0
    # Distributed-trace id minted at the fleet edge (Router.submit) and
    # carried through every record/span this request touches — None for
    # untraced standalone use.
    trace_id: Optional[str] = None


@dataclasses.dataclass
class Completion:
    """A finished request's output + its latency facts."""

    rid: str
    tokens: list                 # generated ids (EOS included when hit)
    finish_reason: str           # one of FINISH_REASONS
    ttft_s: float                # submit -> first token available
    queue_wait_s: float          # submit -> slot admission
    decode_s: float              # first token -> last token
    inter_token_ms: list         # per-token latency (window/K attributed)
    # Throughput-ladder facts (all zero off the respective rungs):
    # pool blocks the request's prefix shared instead of allocating,
    # draft tokens proposed/accepted across its windows, and prefill
    # dispatches its prompt took (1 single-shot; ceil(len/C) chunked).
    prefix_hit_blocks: int = 0
    spec_proposed: int = 0
    spec_accepted: int = 0
    prefill_chunks: int = 1
    trace_id: Optional[str] = None

    @property
    def tokens_per_sec(self) -> Optional[float]:
        total = self.ttft_s + self.decode_s
        return len(self.tokens) / total if total > 0 and self.tokens \
            else None


@dataclasses.dataclass
class _Slot:
    req: Request
    tokens: list
    admitted_s: float
    first_tok_s: float
    inter_token_ms: list
    done: Optional[str] = None   # finish reason once terminal
    prefix_hit_blocks: int = 0
    spec_proposed: int = 0
    spec_accepted: int = 0
    prefill_chunks: int = 1


class ContinuousBatcher:
    """Drives a :class:`~autodist_tpu.serving.engine.ServingEngine`
    from a request queue with slot allocation and eviction."""

    def __init__(self, engine, *, max_queue: Optional[int] = None):
        """``max_queue`` bounds the admission queue: a submit beyond it
        is shed with a coded :class:`OverloadedError` (+ ``serve/shed``
        counter) instead of queueing into unbounded latency.  ``None``
        (default) keeps today's unbounded queue byte-identically."""
        self.engine = engine
        self.max_queue = max_queue
        self._queue: deque[Request] = deque()
        self._slots: list[Optional[_Slot]] = [None] * engine.num_slots
        self._ids = itertools.count()
        self._draining = False
        self.completions: dict[str, Completion] = {}

    # ------------------------------------------------------------------ #
    def submit(self, prompt, *, max_new_tokens: int = 16,
               eos_id: Optional[int] = None, rid: Optional[str] = None,
               deadline_s: Optional[float] = None, seed: int = 0,
               trace_id: Optional[str] = None) -> str:
        """Queue one request; returns its id.  Prompts must fit the
        engine's prompt bucket; a budget exceeding the cache capacity
        is accepted but the request truncates at capacity
        (``finish_reason="max_len"``).

        ``deadline_s`` (seconds from now) bounds the request's total
        latency: a request still queued — or still decoding — past its
        deadline completes with ``finish_reason="deadline_exceeded"``
        and whatever tokens it has (queued requests get none), instead
        of silently burning slot time nobody is waiting for.

        ``seed`` keys this request's sampled stream on a
        temperature > 0 engine (greedy engines ignore it).

        ``trace_id`` tags the request's records and spans with a
        distributed-trace id (defaults to the ambient trace context
        when one is active)."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        cap = getattr(self.engine, "max_prompt_tokens",
                      self.engine.prefill_len)
        if len(prompt) > cap:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the engine's "
                f"admissible {cap} (prefill_len="
                f"{self.engine.prefill_len}; chunked prefill lifts the "
                "bucket to the whole context)")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        if self._draining:
            telemetry.counter("serve/shed").inc()
            raise OverloadedError(
                f"[{OverloadedError.code}] batcher is draining; "
                "resubmit to another replica")
        if self.max_queue is not None \
                and len(self._queue) >= self.max_queue:
            telemetry.counter("serve/shed").inc()
            raise OverloadedError(
                f"[{OverloadedError.code}] admission queue full "
                f"({len(self._queue)}/{self.max_queue}); backing off "
                "and resubmitting is the caller's move")
        rid = rid if rid is not None else f"req-{next(self._ids)}"
        if trace_id is None:
            trace_id = telemetry.current_trace_id()
        now = time.perf_counter()
        self._queue.append(Request(
            rid=rid, prompt=prompt, max_new_tokens=int(max_new_tokens),
            eos_id=eos_id, submit_s=now,
            deadline_s=now + deadline_s if deadline_s is not None
            else None, seed=int(seed), trace_id=trace_id))
        telemetry.gauge("serve/queue_depth").set(len(self._queue))
        return rid

    @property
    def active_slots(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def queue_depth(self) -> int:
        """Queued-but-unadmitted requests — with :attr:`active_slots`,
        the load signal the fleet router dispatches on."""
        return len(self._queue)

    def cancel(self, rid: str) -> bool:
        """Withdraw a live request wherever it is: still queued — it
        completes ``"cancelled"`` with no tokens; in flight — its slot
        is evicted NOW (tokens decoded so far kept on the completion,
        paged blocks back on the free list immediately — a hedge
        loser's reservation must not outlive the race it lost).
        Returns False when ``rid`` is not live (already completed, or
        never submitted)."""
        for req in self._queue:
            if req.rid == rid:
                self._queue.remove(req)
                now = time.perf_counter()
                telemetry.counter("serve/cancelled").inc()
                self._finish(req, tokens=[], reason="cancelled",
                             ttft_s=now - req.submit_s,
                             queue_wait_s=now - req.submit_s,
                             decode_s=0.0, inter_token_ms=[])
                telemetry.gauge("serve/queue_depth").set(len(self._queue))
                return True
        for i, slot in enumerate(self._slots):
            if slot is not None and slot.req.rid == rid:
                if slot.done is None:
                    slot.done = "cancelled"
                    telemetry.counter("serve/cancelled").inc()
                self._evict(i)
                return True
        return False

    # ------------------------------------------------------------------ #
    def _expire_queued(self):
        """Complete queued requests already past their deadline — a
        request nobody is waiting for anymore must not win a slot over
        one somebody is.  No-op when no request carries a deadline."""
        now = time.perf_counter()
        kept: deque[Request] = deque()
        expired = False
        for req in self._queue:
            if req.deadline_s is not None and now >= req.deadline_s:
                expired = True
                telemetry.counter("serve/deadline_exceeded").inc()
                self._finish(req, tokens=[], reason="deadline_exceeded",
                             ttft_s=now - req.submit_s,
                             queue_wait_s=now - req.submit_s,
                             decode_s=0.0, inter_token_ms=[])
            else:
                kept.append(req)
        if expired:
            self._queue = kept
            telemetry.gauge("serve/queue_depth").set(len(self._queue))

    def _expire_slots(self):
        """Mark in-flight slots past their deadline terminal (tokens
        decoded so far are kept — partial output beats none at the
        deadline)."""
        now = time.perf_counter()
        for slot in self._slots:
            if slot is not None and slot.done is None \
                    and slot.req.deadline_s is not None \
                    and now >= slot.req.deadline_s:
                telemetry.counter("serve/deadline_exceeded").inc()
                slot.done = "deadline_exceeded"

    def _admit(self):
        """Fill free slots from the queue with ONE batched prefill.

        Under the paged KV layout admission gates on **free blocks, not
        slots**: a request enters only when its ``prompt + budget``
        block reservation fits the free pool (FIFO, head-of-line — a
        big request at the head waits rather than being jumped, so the
        admission order, and with it the parity contract, stays
        deterministic).  Dense engines keep the slots-only predicate
        byte-identically (``blocks_needed`` is 0)."""
        self._expire_queued()
        free = [i for i, s in enumerate(self._slots) if s is None]
        if not free or not self._queue:
            return
        B = self.engine.num_slots
        S = getattr(self.engine, "max_prompt_tokens",
                    self.engine.prefill_len)
        prompts = np.zeros((B, S), np.int32)
        p_lens = np.ones((B,), np.int32)
        admit = np.zeros((B,), bool)
        seeds = np.zeros((B,), np.int32)
        taken: list[tuple[int, Request, int]] = []
        for i in free:
            if not self._queue:
                break
            head = self._queue[0]
            # Prefix caching prices the head's prompt at its NOVEL
            # suffix: shared leading blocks are free, so an engine
            # whose pool is full of popular prefixes still admits.
            needed = self.engine.blocks_needed(len(head.prompt),
                                               head.max_new_tokens,
                                               prompt=head.prompt)
            if needed > self.engine.free_blocks:
                break   # pool-bound: the head request waits its turn
            req = self._queue.popleft()
            hits = self.engine.reserve_slot(i, len(req.prompt),
                                            req.max_new_tokens,
                                            prompt=req.prompt) or 0
            if hits:
                telemetry.counter("serve/prefix_hit_blocks").inc(hits)
            prompts[i, :len(req.prompt)] = req.prompt
            p_lens[i] = len(req.prompt)
            admit[i] = True
            seeds[i] = req.seed
            taken.append((i, req, hits))
        telemetry.gauge("serve/queue_depth").set(len(self._queue))
        if not taken:
            return
        now = time.perf_counter()
        tids = [req.trace_id for _, req, _ in taken if req.trace_id]
        try:
            with telemetry.span("serve/prefill", admitted=len(taken),
                                **({"trace_ids": tids} if tids else {})):
                toks = self.engine.prefill(prompts, p_lens, admit,
                                           seeds=seeds)
        except Exception:
            # The engine died mid-prefill (a crashed replica): the
            # reservations made above have no slot to be evicted from —
            # without this release they would strand pool blocks
            # forever in a batcher that outlives the error.  Requests
            # go back to the queue head (original order) so a
            # router-side drain/failover can re-dispatch them.
            for i, req, _hits in reversed(taken):
                self.engine.release_slot(i)
                self._queue.appendleft(req)
            telemetry.gauge("serve/queue_depth").set(len(self._queue))
            raise
        t_first = time.perf_counter()
        chunk = getattr(self.engine, "prefill_chunk", None)
        for i, req, hits in taken:
            slot = _Slot(req=req, tokens=[int(toks[i])], admitted_s=now,
                         first_tok_s=t_first, inter_token_ms=[],
                         prefix_hit_blocks=hits,
                         prefill_chunks=(-(-len(req.prompt) // chunk)
                                         if chunk else 1))
            ttft = t_first - req.submit_s
            telemetry.histogram("serve/ttft_ms").observe(ttft * 1e3)
            telemetry.counter("serve/tokens").inc()
            self._slots[i] = slot
            self._check_terminal(i)

    def _check_terminal(self, i: int):
        """Mark slot ``i`` done on EOS / token budget / cache capacity
        (truncating anything decoded past the terminal token).  Both
        caps apply BEFORE the EOS scan: an EOS landing beyond
        ``max_new_tokens`` — or beyond the cache capacity, where the
        window's clamped writes have already corrupted the last lane —
        within the same fused window must not stretch the request."""
        slot = self._slots[i]
        req = slot.req
        # tokens decoded while every prior token still fit a cache lane
        cap = max(1, self.engine.max_len - len(req.prompt))
        limit = min(req.max_new_tokens, cap)
        budgeted = slot.tokens[:limit]
        if req.eos_id is not None and req.eos_id in budgeted:
            slot.tokens = budgeted[:budgeted.index(req.eos_id) + 1]
            slot.done = "eos"
        elif len(slot.tokens) >= limit:
            slot.tokens = budgeted
            slot.done = ("max_tokens" if limit == req.max_new_tokens
                         else "max_len")

    def _finish(self, req: Request, *, tokens: list, reason: str,
                ttft_s: float, queue_wait_s: float, decode_s: float,
                inter_token_ms: list, prefix_hit_blocks: int = 0,
                spec_proposed: int = 0, spec_accepted: int = 0,
                prefill_chunks: int = 1) -> Completion:
        """The ONE completion path: record, count, and file the
        :class:`Completion` — used by slot eviction, queued-deadline
        expiry, and drain shedding alike, so every request that ever
        entered ``submit`` leaves exactly one completion + one
        ``kind="serve"`` record (no in-flight request is ever
        stranded)."""
        comp = Completion(
            rid=req.rid, tokens=list(tokens), finish_reason=reason,
            ttft_s=ttft_s, queue_wait_s=queue_wait_s, decode_s=decode_s,
            inter_token_ms=list(inter_token_ms),
            prefix_hit_blocks=int(prefix_hit_blocks),
            spec_proposed=int(spec_proposed),
            spec_accepted=int(spec_accepted),
            prefill_chunks=int(prefill_chunks),
            trace_id=req.trace_id)
        self.completions[req.rid] = comp
        telemetry.counter("serve/requests").inc()
        itl = np.asarray(comp.inter_token_ms) if comp.inter_token_ms \
            else None
        telemetry.get().record_event(
            "serve", request=req.rid,
            prompt_tokens=len(req.prompt), tokens=len(comp.tokens),
            kv_layout=getattr(self.engine, "kv_layout", "dense"),
            finish=comp.finish_reason,
            ttft_ms=comp.ttft_s * 1e3,
            queue_wait_ms=comp.queue_wait_s * 1e3,
            inter_token_p50_ms=(float(np.percentile(itl, 50))
                                if itl is not None else None),
            inter_token_p99_ms=(float(np.percentile(itl, 99))
                                if itl is not None else None),
            tokens_per_sec=comp.tokens_per_sec,
            prefix_hit_blocks=comp.prefix_hit_blocks,
            spec_proposed=comp.spec_proposed,
            spec_accepted=comp.spec_accepted,
            prefill_chunks=comp.prefill_chunks,
            **({"trace_id": req.trace_id} if req.trace_id else {}))
        return comp

    def _evict(self, i: int):
        slot = self._slots[i]
        req = slot.req
        t_end = time.perf_counter()
        self._slots[i] = None
        # Paged: the freed blocks go back on the free list immediately,
        # so the next admission round can hand them to a queued request
        # (the block-recycling edge the paged parity goldens pin).
        self.engine.release_slot(i)
        self._finish(req, tokens=slot.tokens, reason=slot.done,
                     ttft_s=slot.first_tok_s - req.submit_s,
                     queue_wait_s=slot.admitted_s - req.submit_s,
                     decode_s=t_end - slot.first_tok_s,
                     inter_token_ms=slot.inter_token_ms,
                     prefix_hit_blocks=slot.prefix_hit_blocks,
                     spec_proposed=slot.spec_proposed,
                     spec_accepted=slot.spec_accepted,
                     prefill_chunks=slot.prefill_chunks)

    def _decode_window(self):
        """One fused decode dispatch; distribute tokens, evict terminal
        slots."""
        active = np.array([s is not None and s.done is None
                           for s in self._slots], bool)
        if not active.any():
            return
        K = self.engine.decode_steps
        t0 = time.perf_counter()
        tids = [s.req.trace_id for s, a in zip(self._slots, active)
                if a and s is not None and s.req.trace_id]
        with telemetry.span("serve/decode", tokens=int(active.sum()) * K,
                            **({"trace_ids": tids} if tids else {})):
            if hasattr(self.engine, "decode_window"):
                w = self.engine.decode_window(active)
                toks, counts = w.tokens, w.counts
                proposed, accepted = w.spec_proposed, w.spec_accepted
            else:
                # Minimal engines (test doubles) expose only decode().
                toks = self.engine.decode(active)
                counts = np.where(active, K, 0)
                proposed = accepted = np.zeros_like(counts)
        dt = time.perf_counter() - t0
        per_tok_ms = dt / max(int(np.max(counts)), 1) * 1e3
        for i, slot in enumerate(self._slots):
            if slot is None or not active[i]:
                continue
            before = len(slot.tokens)
            slot.tokens.extend(int(toks[k, i])
                               for k in range(int(counts[i])))
            slot.spec_proposed += int(proposed[i])
            slot.spec_accepted += int(accepted[i])
            if proposed[i]:
                telemetry.counter("serve/spec_proposed").inc(
                    int(proposed[i]))
                telemetry.counter("serve/spec_accepted").inc(
                    int(accepted[i]))
            self._check_terminal(i)
            # Only tokens the request actually keeps count: a window's
            # over-decode past EOS/budget is discarded above, and the
            # counters/histograms must agree with the per-request
            # serve records the report aggregates.
            kept = max(0, len(slot.tokens) - before)
            slot.inter_token_ms.extend([per_tok_ms] * kept)
            for _ in range(kept):
                telemetry.histogram("serve/inter_token_ms").observe(
                    per_tok_ms)
            telemetry.counter("serve/tokens").inc(kept)

    # ------------------------------------------------------------------ #
    def step(self):
        """One scheduler round: expire deadlines, evict finished,
        admit, decode."""
        self._expire_slots()
        for i, slot in enumerate(self._slots):
            if slot is not None and slot.done is not None:
                self._evict(i)
        if not self._draining:
            self._admit()
        self._decode_window()

    def run(self) -> dict[str, Completion]:
        """Drain the queue and every in-flight request; returns
        ``{rid: Completion}`` for the requests finished DURING this
        call (a long-lived server loop calling ``run()`` per admission
        round must not re-receive old completions; the full history
        stays on :attr:`completions`)."""
        before = set(self.completions)
        while self._queue or self.active_slots:
            self.step()
        return {rid: c for rid, c in self.completions.items()
                if rid not in before}

    def drain(self, *, finish_in_flight: bool = True
              ) -> dict[str, Completion]:
        """Wind the batcher down without admitting new work — the
        explicit semantics for evicting an engine (a re-election, a
        preemption, a rolling restart): queued-but-unadmitted requests
        complete as ``"shed"`` (resubmittable elsewhere — no token was
        ever produced for them), in-flight slots either decode to their
        natural terminal (``finish_in_flight=True``) or are cut at
        their current token as ``"drained"``.  Either way NO in-flight
        slot is stranded: every submitted request ends in exactly one
        completion.  Subsequent ``submit`` calls shed with
        :class:`OverloadedError`.  Returns the completions this call
        produced."""
        before = set(self.completions)
        self._draining = True
        now = time.perf_counter()
        while self._queue:
            req = self._queue.popleft()
            telemetry.counter("serve/shed").inc()
            self._finish(req, tokens=[], reason="shed",
                         ttft_s=now - req.submit_s,
                         queue_wait_s=now - req.submit_s,
                         decode_s=0.0, inter_token_ms=[])
        telemetry.gauge("serve/queue_depth").set(0)
        if finish_in_flight:
            while self.active_slots:
                self.step()
        else:
            for i, slot in enumerate(self._slots):
                if slot is not None:
                    if slot.done is None:
                        slot.done = "drained"
                    self._evict(i)
        return {rid: c for rid, c in self.completions.items()
                if rid not in before}
