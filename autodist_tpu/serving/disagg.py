"""Disaggregated serving: a prefill pool and a decode pool with a
compiled KV-prefix handoff between them.

Colocated continuous batching (the :class:`ContinuousBatcher`) makes
prefill and decode fight for the same dispatch stream: one long prompt
stalls every resident request's inter-token latency for a whole prefill
(PAPERS.md: the interference DistServe/Splitwise measure).  The
disaggregated layout splits the fleet into

* a **prefill pool** — engines that only ever run the prompt pass and
  emit the first token, then give their slot back, and
* a **decode pool** — engines that only ever run the fused decode
  windows, so their inter-token cadence is never pierced by a prompt.

The request's KV prefix moves between the pools as a **handoff**: the
prefill engine's pool blocks holding positions ``[0, prompt_len)`` are
copied block-for-block into blocks the decode engine reserved, the
decode slot adopts the request's length and first token in the same
program, and the prefill slot is released.  The transfer is ONE jitted
per-block gather/scatter (``dynamic_slice`` / ``dynamic_update_slice``
along the pool's block axis, the :func:`copy_pool_block` shape, so the
model-axis head sharding passes through) — never a full-pool gather and
never a host staging:

* the compiled program is linted like an elastic reshard
  (``ADT110 no_full_gather`` at the per-device stored-shard budget of
  :func:`autodist_tpu.elastic.reshard.shard_budget`, plus
  ``ADT104 no_host_transfer``), and
* the plan is linted BEFORE compiling
  (:func:`autodist_tpu.analysis.lint_handoff`, ADT072: the per-device
  gather a handoff stages must stay under one pool shard).

Every executed handoff emits a ``kind="handoff"`` telemetry record —
route (ici/dcn), blocks, bytes moved, duration, and the **paired**
prefill/decode replica ids — schema-gated by
``tools/telemetry_report.py --check``.

The pool split itself is an election, not a guess:
:func:`elect_pool_split` ranks the ``(prefill_replicas ×
decode_replicas × tensor_parallel)`` zoo by the cost model's
``disagg_score`` (the pipeline's bottleneck stage under the traffic's
``mean_prompt_len`` / ``mean_request_len``, with the handoff priced on
the route it would ride) — prefill-heavy mixes elect bigger prefill
pools and decode-heavy mixes the reverse, pinned both ways by the unit
tests.  :func:`autodist_tpu.analysis.lint_disagg` (ADT089) gates splits
the topology cannot place before any engine is built.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from autodist_tpu import telemetry
from autodist_tpu.serving import kv_cache
from autodist_tpu.serving.batcher import (FINISH_REASONS,  # noqa: F401
                                          OverloadedError)


# --------------------------------------------------------------------------- #
# Configuration + election
# --------------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class DisaggConfig:
    """An elected (or hand-picked) pool split."""

    prefill_replicas: int
    decode_replicas: int
    tensor_parallel: int = 1
    kv_layout: str = "paged"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def elect_pool_split(trainable, resource_spec, *, candidates=None,
                     **rank_kwargs):
    """Elect the pool split for a traffic mix: rank the
    ``default_disagg_candidates`` zoo (or ``candidates``) by
    ``disagg_score`` and return ``(DisaggConfig, DecodeCost)`` for the
    winner.  Pass the traffic facts (``mean_prompt_len``,
    ``mean_request_len``, ``batch_slots``, ``max_len``) through
    ``rank_kwargs`` — they are what moves the bottleneck between the
    pools.  Raises when no candidate is feasible."""
    from autodist_tpu.simulator import rank_serving

    ranked = rank_serving(trainable, resource_spec,
                          candidates, objective="disagg", **rank_kwargs)
    for config, cost in ranked:
        if np.isfinite(cost.disagg_score):
            return DisaggConfig(
                prefill_replicas=int(config["prefill_replicas"]),
                decode_replicas=int(config["decode_replicas"]),
                tensor_parallel=int(config.get("tensor_parallel", 1)),
                kv_layout=str(config.get("kv_layout", "paged"))), cost
    raise ValueError(
        "no feasible disaggregated split for this topology/traffic — "
        "every candidate's disagg_score is infinite (check device "
        "count vs tensor_parallel, and kv_layout='paged')")


# --------------------------------------------------------------------------- #
# The handoff plan (what the ADT072 lint checks before compiling)
# --------------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class HandoffPlan:
    """One request's prefill→decode KV move, in elements and blocks —
    the planning artifact :func:`autodist_tpu.analysis.lint_handoff`
    gates (ADT072) and the ``kind="handoff"`` record serializes."""

    rid: str
    blocks: int
    bytes_moved: int              # logical k+v bytes across every layer
    per_device_gather_elems: int  # largest per-participant staging
    budget_elems: int             # one per-device stored pool shard
    prefill_replica: str
    decode_replica: str
    route: str                    # "ici" | "dcn"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class HandoffError(RuntimeError):
    """A handoff plan or its compiled program failed its lint — the
    transfer would stage more than the shard-granularity contract
    allows.  Raised BEFORE any block moves."""

    code = "serve/handoff_lint"


# --------------------------------------------------------------------------- #
# Internal request state
# --------------------------------------------------------------------------- #
@dataclasses.dataclass
class _DisaggRequest:
    rid: str
    prompt: list
    max_new_tokens: int
    eos_id: Optional[int]
    seed: int
    submit_s: float
    state: str = "queued"         # queued -> prefilled -> decode -> done
    tokens: list = dataclasses.field(default_factory=list)
    prefill_replica: str = ""
    decode_replica: str = ""
    _src_slot: int = -1
    _dst_slot: int = -1
    first_tok_s: float = 0.0
    trace_id: Optional[str] = None


@dataclasses.dataclass
class DisaggCompletion:
    """A finished request's output, tagged with BOTH replicas that
    served it (the pairing the handoff record schema pins)."""

    rid: str
    tokens: list
    finish_reason: str
    prefill_replica: str
    decode_replica: str
    ttft_s: float
    trace_id: Optional[str] = None


# --------------------------------------------------------------------------- #
# The server
# --------------------------------------------------------------------------- #
class DisaggServer:
    """Prefill/decode pools over a shared request queue.

    ``engine_factory`` builds ONE engine per replica (every pool member
    gets an identical geometry — the handoff copies blocks positionally
    between pools, so the block length, layer count, and pool shape
    must agree; validated at construction).  The split comes from
    ``config`` (a :class:`DisaggConfig`, e.g. from
    :func:`elect_pool_split`) or explicit ``prefill_replicas`` /
    ``decode_replicas`` counts; :func:`lint_disagg` gates it against
    ``resource_spec`` (ADT089) before any engine is built.

    :meth:`step` advances the pipeline one round: admit queued
    requests into prefill slots (one batched prefill per engine), hand
    finished prefixes to the decode pool (one compiled, linted transfer
    per request), then run one fused decode window per decode engine.
    :meth:`run` loops until every submitted request completes.
    """

    def __init__(self, engine_factory, *, prefill_replicas: int = None,
                 decode_replicas: int = None,
                 config: Optional[DisaggConfig] = None,
                 resource_spec=None, max_queue: Optional[int] = None,
                 name: str = "disagg"):
        if config is None:
            # explicit 0 must reach the >= 1 check below, not default
            config = DisaggConfig(
                prefill_replicas=1 if prefill_replicas is None
                else int(prefill_replicas),
                decode_replicas=1 if decode_replicas is None
                else int(decode_replicas))
        elif prefill_replicas is not None or decode_replicas is not None:
            raise ValueError("pass config= OR explicit pool counts, "
                             "not both")
        if config.prefill_replicas < 1 or config.decode_replicas < 1:
            raise ValueError("each pool needs >= 1 replica")
        from autodist_tpu.analysis import lint_disagg
        report = lint_disagg(config, resource_spec)
        if not report.ok:
            raise ValueError(report.render("disagg pool split"))
        self.config = config
        self.name = name
        self.prefill_pool = [(f"prefill-{i}", engine_factory())
                             for i in range(config.prefill_replicas)]
        self.decode_pool = [(f"decode-{i}", engine_factory())
                            for i in range(config.decode_replicas)]
        self._validate_pools()
        eng = self.decode_pool[0][1]
        L, NB, H, bl, dh = eng.cache.k.shape
        tp = int(getattr(eng, "tensor_parallel", 1) or 1)
        #: the ADT110/ADT072 budget: ONE per-device stored pool shard
        #: (shard_budget's rule applied to the k pool — heads divide
        #: over the model axis, every other dim is stored whole).
        self.budget_elems = L * NB * (H // tp) * bl * dh
        self._elem_bytes = int(jnp.dtype(eng.cache.k.dtype).itemsize)
        self.max_queue = max_queue
        self._queue: deque[_DisaggRequest] = deque()
        self._reqs: dict = {}
        self.completions: dict = {}
        self._handoff_jits: dict = {}
        self.last_handoff_report = None
        self._auto_rid = 0
        self.route = self._route(resource_spec)

    def _validate_pools(self) -> None:
        shapes = set()
        for pname, eng in self.prefill_pool + self.decode_pool:
            if eng.kv_layout != "paged":
                raise ValueError(
                    f"{pname}: the handoff rides the block table — "
                    "disaggregated pools require kv_layout='paged'")
            if getattr(eng, "speculative", None) is not None:
                raise ValueError(
                    f"{pname}: speculative decoding is not supported "
                    "in disaggregated pools — the draft's cache cannot "
                    "ride the handoff")
            shapes.add(tuple(eng.cache.k.shape))
        if len(shapes) > 1:
            raise ValueError(
                f"pool engines disagree on cache geometry: {shapes} — "
                "the handoff copies blocks positionally, so every "
                "replica needs the same factory output")

    def _route(self, resource_spec) -> str:
        """The wire the handoff rides: inside one slice's ICI when the
        whole split fits, DCN when the pools must span slices — the same
        predicate the cost model prices the handoff term with."""
        if resource_spec is None:
            return "ici"
        try:
            num_devices = resource_spec.num_devices()
        except (ValueError, RuntimeError):
            return "ici"
        num_slices = max(int(getattr(resource_spec, "num_slices", 1)
                             or 1), 1)
        per_slice = max(num_devices // num_slices, 1)
        total = (self.config.prefill_replicas
                 + self.config.decode_replicas) \
            * self.config.tensor_parallel
        return "dcn" if num_slices > 1 and total > per_slice else "ici"

    # ------------------------------------------------------------------ #
    # Submission
    # ------------------------------------------------------------------ #
    def submit(self, prompt, *, max_new_tokens: int = 16,
               eos_id: Optional[int] = None, rid: Optional[str] = None,
               seed: int = 0, trace_id: Optional[str] = None) -> str:
        """Queue one request; returns its id.  The same admission
        contract as the colocated batcher: prompts must fit the
        prefill engines' bucket, and a bounded queue sheds loudly
        (:class:`OverloadedError`) instead of buffering without
        bound.  ``trace_id`` (supplied, ambient, or minted here) tags
        the request's prefill span, ``kind="handoff"`` record, and
        decode span — the cross-pool hop stays one trace."""
        prompt = [int(t) for t in prompt]
        eng = self.prefill_pool[0][1]
        max_prompt = getattr(eng, "max_prompt_tokens", eng.prefill_len)
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > max_prompt:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the prefill "
                f"bucket ({max_prompt})")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.max_queue is not None \
                and len(self._queue) >= self.max_queue:
            raise OverloadedError(
                f"[{OverloadedError.code}] disagg queue at its bound "
                f"({self.max_queue})")
        if rid is None:
            self._auto_rid += 1
            rid = f"{self.name}-{self._auto_rid}"
        if rid in self._reqs:
            raise ValueError(f"duplicate rid {rid!r}")
        if trace_id is None:
            trace_id = telemetry.current_trace_id() \
                or telemetry.mint_trace_id()
        req = _DisaggRequest(rid=rid, prompt=prompt,
                             max_new_tokens=int(max_new_tokens),
                             eos_id=eos_id, seed=int(seed),
                             submit_s=time.perf_counter(),
                             trace_id=trace_id)
        self._reqs[rid] = req
        self._queue.append(req)
        telemetry.gauge("disagg/queue_depth").set(len(self._queue))
        return rid

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def open_requests(self) -> int:
        return sum(1 for r in self._reqs.values() if r.state != "done")

    # ------------------------------------------------------------------ #
    # The pipeline round
    # ------------------------------------------------------------------ #
    def step(self) -> None:
        """One pipeline round: admit → handoff → decode.  Each stage
        works on what the previous rounds produced, so a request takes
        (at least) three rounds end to end — and the stages of
        DIFFERENT requests overlap across rounds, which is the point."""
        self._admit_prefill()
        self._handoff_ready()
        self._decode_round()

    def run(self, max_steps: int = 10_000) -> dict:
        """Drive :meth:`step` until every submitted request completes;
        returns :attr:`completions`."""
        steps = 0
        while self.open_requests:
            if steps >= max_steps:
                raise RuntimeError(
                    f"disagg pipeline did not drain in {max_steps} "
                    f"steps ({self.open_requests} request(s) open)")
            self.step()
            steps += 1
        return self.completions

    # ---- stage 1: prefill admission ---------------------------------- #
    def _admit_prefill(self) -> None:
        """FIFO-admit queued requests into prefill slots, one batched
        prefill dispatch per engine.  A prefill slot reserves only the
        PROMPT's blocks (``max_new_tokens=0``) — generation happens in
        the other pool, against the other pool's reservation."""
        for pname, eng in self.prefill_pool:
            if not self._queue:
                return
            free = [i for i in range(eng.num_slots)
                    if not eng._slot_blocks[i]
                    and not any(r._src_slot == i
                                and r.prefill_replica == pname
                                and r.state in ("prefill", "prefilled")
                                for r in self._reqs.values())]
            if not free:
                continue
            B = eng.num_slots
            S = getattr(eng, "max_prompt_tokens", eng.prefill_len)
            prompts = np.zeros((B, S), np.int32)
            p_lens = np.ones((B,), np.int32)
            admit = np.zeros((B,), bool)
            seeds = np.zeros((B,), np.int32)
            taken = []
            for i in free:
                if not self._queue:
                    break
                head = self._queue[0]
                needed = eng.blocks_needed(len(head.prompt), 0,
                                           prompt=head.prompt)
                if needed > eng.free_blocks:
                    break      # pool-bound: the head waits (FIFO)
                req = self._queue.popleft()
                eng.reserve_slot(i, len(req.prompt), 0,
                                 prompt=req.prompt)
                prompts[i, :len(req.prompt)] = req.prompt
                p_lens[i] = len(req.prompt)
                admit[i] = True
                seeds[i] = req.seed
                req.state = "prefill"
                req.prefill_replica = pname
                req._src_slot = i
                taken.append((i, req))
            if not taken:
                continue
            tids = [req.trace_id for _, req in taken if req.trace_id]
            with telemetry.span("disagg/prefill", replica=pname,
                                admitted=len(taken),
                                **({"trace_ids": tids} if tids else {})):
                toks = eng.prefill(prompts, p_lens, admit, seeds=seeds)
            t_first = time.perf_counter()
            for i, req in taken:
                req.tokens = [int(toks[i])]
                req.first_tok_s = t_first
                req.state = "prefilled"
                telemetry.histogram("serve/ttft_ms").observe(
                    (t_first - req.submit_s) * 1e3)
        telemetry.gauge("disagg/queue_depth").set(len(self._queue))

    # ---- stage 2: the compiled KV handoff ----------------------------- #
    def _handoff_fn(self, n: int):
        """The n-block transfer as ONE jitted program: gather each
        source block (a ``dynamic_slice`` along the pool's block axis —
        the :func:`copy_pool_block` shape, head sharding passes
        through), scatter it into the destination's reserved block, and
        adopt the slot's length + current token in the same dispatch.
        Destination pools/state are donated, so XLA aliases the writes.
        Compiled ONCE per block count, and linted at build: ADT110
        (no gather result above one per-device pool shard) + ADT104
        (no host transfer) over the optimized HLO."""
        fn = self._handoff_jits.get(n)
        if fn is not None:
            return fn

        def handoff(src_k, src_v, dst_k, dst_v, lengths, tok,
                    src_ids, dst_ids, slot, p_len, first_tok):
            for i in range(n):
                kb = lax.dynamic_slice_in_dim(src_k, src_ids[i], 1,
                                              axis=1)
                vb = lax.dynamic_slice_in_dim(src_v, src_ids[i], 1,
                                              axis=1)
                dst_k = lax.dynamic_update_slice_in_dim(
                    dst_k, kb, dst_ids[i], axis=1)
                dst_v = lax.dynamic_update_slice_in_dim(
                    dst_v, vb, dst_ids[i], axis=1)
            lengths = lax.dynamic_update_slice(lengths, p_len[None],
                                               (slot,))
            tok = lax.dynamic_update_slice(tok, first_tok[None], (slot,))
            return dst_k, dst_v, lengths, tok

        fn = jax.jit(handoff, donate_argnums=(2, 3, 4, 5))
        eng = self.decode_pool[0][1]
        pool = jax.ShapeDtypeStruct(eng.cache.k.shape,
                                    eng.cache.k.dtype)
        vec = jax.ShapeDtypeStruct((eng.num_slots,), jnp.int32)
        ids = jax.ShapeDtypeStruct((n,), jnp.int32)
        scalar = jax.ShapeDtypeStruct((), jnp.int32)
        text = fn.lower(pool, pool, pool, pool, vec, vec, ids, ids,
                        scalar, scalar, scalar).compile().as_text()
        from autodist_tpu.analysis import lint_program
        from autodist_tpu.analysis.program_rules import (no_full_gather,
                                                         no_host_transfer)
        report = lint_program(
            text, [no_full_gather(self.budget_elems),
                   no_host_transfer()],
            where=f"disagg.handoff[{n} block(s)]")
        self.last_handoff_report = report
        if not report.ok:
            raise HandoffError(
                f"[{HandoffError.code}]\n"
                + report.render("compiled handoff"))
        self._handoff_jits[n] = fn
        return fn

    def _pick_decode(self, blocks_needed: int):
        """Least-loaded decode engine with a free slot and room for the
        request's full reservation (name-ordered tiebreak — the same
        determinism rule the router's ``_pick`` follows)."""
        best = None
        for pname, eng in self.decode_pool:
            free = [i for i in range(eng.num_slots)
                    if not eng._slot_blocks[i]]
            if not free or blocks_needed > eng.free_blocks:
                continue
            load = sum(1 for b in eng._slot_blocks if b)
            if best is None or (load, pname) < (best[0], best[1]):
                best = (load, pname, eng, free[0])
        return best

    def _handoff_ready(self) -> None:
        """Move every prefilled request whose decode reservation fits:
        plan → lint (ADT072) → one compiled transfer → release the
        prefill slot → one schema-gated ``kind="handoff"`` record."""
        from autodist_tpu.analysis import lint_handoff

        ready = sorted((r for r in self._reqs.values()
                        if r.state == "prefilled"),
                       key=lambda r: r.submit_s)
        for req in ready:
            p_len = len(req.prompt)
            src_name = req.prefill_replica
            src = dict(self.prefill_pool)[src_name]
            needed = self.decode_pool[0][1].blocks_needed(
                p_len, req.max_new_tokens)
            pick = self._pick_decode(needed)
            if pick is None:
                continue           # decode pool full: retry next round
            _, dst_name, dst, dst_slot = pick
            bl = dst.kv_block_len
            n = kv_cache.blocks_for(p_len, bl)
            L, NB, H, _, dh = dst.cache.k.shape
            tp = int(getattr(dst, "tensor_parallel", 1) or 1)
            plan = HandoffPlan(
                rid=req.rid, blocks=n,
                bytes_moved=2 * n * L * H * bl * dh * self._elem_bytes,
                per_device_gather_elems=n * L * (H // tp) * bl * dh,
                budget_elems=self.budget_elems,
                prefill_replica=src_name, decode_replica=dst_name,
                route=self.route)
            report = lint_handoff(plan)
            if not report.ok:
                raise HandoffError(
                    f"[{HandoffError.code}]\n"
                    + report.render("handoff plan"))
            dst.reserve_slot(dst_slot, p_len, req.max_new_tokens)
            src_ids = src._slot_blocks[req._src_slot][:n]
            dst_ids = dst._slot_blocks[dst_slot][:n]
            fn = self._handoff_fn(n)
            t0 = time.perf_counter()
            k, v, lengths, tok = fn(
                src.cache.k, src.cache.v, dst.cache.k, dst.cache.v,
                dst.cache.lengths, dst._tok,
                jnp.asarray(src_ids, jnp.int32),
                jnp.asarray(dst_ids, jnp.int32),
                jnp.int32(dst_slot), jnp.int32(p_len),
                jnp.int32(req.tokens[0]))
            jax.block_until_ready(k)
            dt_ms = (time.perf_counter() - t0) * 1e3
            dst.cache = kv_cache.PagedKVCache(
                k=k, v=v, lengths=lengths,
                block_table=dst.cache.block_table)
            dst._tok = tok
            dst._sample_seeds[dst_slot] = req.seed
            src.release_slot(req._src_slot)
            req.state = "decode"
            req.decode_replica = dst_name
            req._dst_slot = dst_slot
            telemetry.gauge("disagg/handoff_bytes").set(plan.bytes_moved)
            telemetry.counter("disagg/handoffs").inc()
            telemetry.record_event(
                "handoff", rid=req.rid, route=plan.route,
                blocks=plan.blocks, bytes_moved=plan.bytes_moved,
                per_device_gather_elems=plan.per_device_gather_elems,
                budget_elems=plan.budget_elems,
                prefill_replica=plan.prefill_replica,
                decode_replica=plan.decode_replica,
                duration_ms=dt_ms,
                **({"trace_id": req.trace_id} if req.trace_id else {}))

    # ---- stage 3: decode windows -------------------------------------- #
    def _decode_round(self) -> None:
        """One fused decode window per decode engine holding work; the
        colocated batcher's terminal rules verbatim (budget and
        capacity caps before the EOS scan)."""
        for pname, eng in self.decode_pool:
            mine = [r for r in self._reqs.values()
                    if r.state == "decode" and r.decode_replica == pname]
            if not mine:
                continue
            active = np.zeros((eng.num_slots,), bool)
            for r in mine:
                active[r._dst_slot] = True
            tids = [r.trace_id for r in mine if r.trace_id]
            with telemetry.span("disagg/decode", replica=pname,
                                active=int(active.sum()),
                                **({"trace_ids": tids} if tids else {})):
                toks = eng.decode(active)          # [K, B]
            for r in mine:
                r.tokens.extend(int(t) for t in toks[:, r._dst_slot])
                cap = max(1, eng.max_len - len(r.prompt))
                limit = min(r.max_new_tokens, cap)
                budgeted = r.tokens[:limit]
                done = None
                if r.eos_id is not None and r.eos_id in budgeted:
                    r.tokens = budgeted[:budgeted.index(r.eos_id) + 1]
                    done = "eos"
                elif len(r.tokens) >= limit:
                    r.tokens = budgeted
                    done = ("max_tokens" if limit == r.max_new_tokens
                            else "max_len")
                if done is not None:
                    eng.release_slot(r._dst_slot)
                    r.state = "done"
                    self.completions[r.rid] = DisaggCompletion(
                        rid=r.rid, tokens=list(r.tokens),
                        finish_reason=done,
                        prefill_replica=r.prefill_replica,
                        decode_replica=r.decode_replica,
                        ttft_s=r.first_tok_s - r.submit_s,
                        trace_id=r.trace_id)
                    telemetry.counter("serve/completed").inc()

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #
    def describe(self) -> dict:
        """The split in :func:`lint_disagg`'s vocabulary."""
        return self.config.to_dict()

    def block_accounting(self) -> dict:
        """Per-replica ``(free, used, total)`` across BOTH pools — the
        zero-leak invariant is every pool fully free once no request is
        resident."""
        return {name: eng.block_accounting()
                for name, eng in self.prefill_pool + self.decode_pool}
