"""Batched-inference engine on the Strategy IR's tensor-parallel specs.

The decode program reuses the training stack's hard parts instead of
growing a second model implementation:

* **Prefill** runs the prompt through the same column/row-parallel
  matmul boundaries as the training stage_fn
  (:mod:`autodist_tpu.parallel.tensor` — the ``PartitionerConfig`` spec
  table that answers "how do I train this" also answers "how do I serve
  it", the GSPMD one-IR property), filling the TP-sharded KV cache and
  emitting the first token from *last-position-only* logits.
* **Decode** runs a fused multi-step loop — the ``run_steps``
  steps-per-loop idea repurposed for token steps: one ``lax.scan`` body
  per token, one host dispatch per ``decode_steps`` tokens — attending
  over the cache via in-place ``dynamic_update_slice`` writes.  The
  greedy epilogue (:func:`~autodist_tpu.parallel.tensor
  .vocab_parallel_greedy_token`) keeps the live logits at ``[B, V/tp]``,
  so a decode step never materializes a full-vocab or full-sequence
  buffer (``tools/hlo_probe.py --probe decode`` asserts both
  structurally).

Parameters arrive in the *logical* layout every fetch path produces —
``runner.get_params()`` from a live pipelined-LM runner, or the
``params/`` tree of a ``checkpoint/export.py`` artifact — and the
engine shards them itself from the same rule tables the ``Pipeline``
builder records in the Strategy IR (``PIPELINE_TP_RULES`` /
``PIPELINE_VOCAB_RULES``).  Use :func:`autodist_tpu.serving.serve` for
the entry-point conveniences.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.serving import kv_cache
from autodist_tpu.parallel.tensor import (column_parallel,
                                          normalize_comm_overlap,
                                          row_parallel, vocab_pad,
                                          vocab_parallel_embedding,
                                          vocab_parallel_greedy_token)


def serving_param_specs(params, tp: int, vocab_parallel: bool):
    """Per-leaf ``PartitionSpec`` tree for the serving mesh, from the
    SAME rule tables the ``Pipeline`` builder writes into the Strategy
    IR: stage leaves keep their stacked leading layer dim unsharded and
    shard the Megatron dims the tp rules name; the shared tied table
    shards its vocab dim iff ``vocab_parallel``; everything else
    replicates."""
    import re

    from autodist_tpu.kernel import common
    from autodist_tpu.strategy.parallel_builders import (
        PIPELINE_TP_RULES, PIPELINE_VOCAB_RULES)

    tp_rules = [(re.compile(p), s) for p, s in PIPELINE_TP_RULES]
    v_rules = [(re.compile(p), s) for p, s in PIPELINE_VOCAB_RULES]

    def spec_for(name, leaf):
        shape = tuple(np.shape(leaf))
        if tp > 1 and name.startswith("stages/"):
            for pat, spec in tp_rules:
                if pat.search(name) and len(spec) == len(shape) - 1:
                    for dim, axis in zip(shape[1:], spec):
                        if axis == const.MODEL_AXIS and dim % tp:
                            raise ValueError(
                                f"{name}: dim {dim} does not divide by "
                                f"tensor_parallel={tp}")
                    return P(None, *spec)
        if tp > 1 and vocab_parallel and name.startswith("shared/"):
            short = name[len("shared/"):]
            for pat, spec in v_rules:
                if pat.search(short) and len(spec) == len(shape):
                    return P(*spec)
        return P()

    return common.tree_from_names(params, spec_for)


def seed_engine_kwargs(engine_kwargs: dict, strategy) -> dict:
    """Default the serving parallelism knobs from a training strategy's
    Strategy-IR ``parallel`` record (explicit kwargs win) — the single
    definition behind every ``strategy=`` entry point, so a new
    Strategy-IR serving knob cannot be seeded by one path and missed by
    another."""
    if strategy is not None:
        from autodist_tpu.strategy.ir import normalize_kv_layout

        par = strategy.graph_config.parallel or {}
        engine_kwargs.setdefault(
            "tensor_parallel", int(par.get("tensor_parallel", 1) or 1))
        engine_kwargs.setdefault(
            "vocab_parallel", bool(par.get("vocab_parallel", False)))
        engine_kwargs.setdefault("comm_overlap", par.get("comm_overlap"))
        engine_kwargs.setdefault(
            "kv_layout", normalize_kv_layout(par.get("kv_layout")))
        kern = getattr(strategy.graph_config, "kernel", None)
        if kern:
            engine_kwargs.setdefault("kernel", dict(kern))
    return engine_kwargs


class ServingEngine:
    """Prefill/decode engine for the pipelined transformer LM family.

    ``params``: the logical ``{"stages": ..., "shared": ...}`` tree of
    :func:`~autodist_tpu.models.pipeline_lm.make_pipeline_lm_trainable`
    (stacked per-layer leaves + tied embedding/unembedding).  Slots,
    prompt bucket, and the fused-decode width are static so the whole
    serving loop is exactly two compiled programs:

    * ``num_slots`` — batch slots the continuous batcher fills;
    * ``prefill_len`` — the prompt bucket (prompts zero-padded up to
      it; padded positions write garbage k/v that masked reads never
      see and forward decode overwrites);
    * ``decode_steps`` — tokens per fused decode dispatch (``K``).

    ``tensor_parallel``/``vocab_parallel``/``comm_overlap`` mirror the
    training ``Pipeline`` knobs; with ``tensor_parallel == 1`` the same
    code runs unsharded with zero collectives (the decode goldens'
    sequential-reference property).

    ``kv_layout`` (Strategy-IR serving knob, ``"dense"``/``"paged"``):
    ``"paged"`` replaces the per-slot ``max_len`` lanes with a block
    pool of ``kv_num_blocks`` blocks of ``kv_block_len`` positions and
    a per-slot block table — requests reserve only the blocks their
    ``prompt + budget`` span needs (:meth:`blocks_needed` /
    :meth:`reserve_slot` / :meth:`release_slot`), so the batcher admits
    against free blocks, not slots, and ``num_slots`` may exceed what
    the pool could hold at ``max_len``.

    ``temperature``/``top_k`` (the sampling rung): ``temperature == 0``
    (default) compiles the exact greedy program; ``> 0`` samples via
    the shard-invariant gumbel-max epilogue keyed per (request seed,
    context length) — see
    :func:`~autodist_tpu.parallel.tensor.vocab_parallel_sample_token`.
    """

    def __init__(self, cfg, params, *, tensor_parallel: int = 1,
                 vocab_parallel: bool = False, comm_overlap=None,
                 kernel=None,
                 num_slots: int = 4, max_len: Optional[int] = None,
                 prefill_len: Optional[int] = None, decode_steps: int = 8,
                 kv_layout: str = "dense",
                 kv_block_len: Optional[int] = None,
                 kv_num_blocks: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 devices=None):
        from autodist_tpu.strategy.ir import (normalize_kernel,
                                              normalize_kv_layout)

        self.cfg = cfg
        # The fused-kernel election (Strategy IR kernel slot): only
        # flash_decode changes the serving programs — prefill/decode
        # have no grad sync or matmul-overlap ring for the training
        # kernels to replace.
        self.kernel = normalize_kernel(kernel)
        attn_fn = getattr(cfg, "attention_fn", None)
        if attn_fn is not None:
            from autodist_tpu.ops.flash_attention import \
                is_flash_attention_fn
            if not is_flash_attention_fn(attn_fn):
                # The decode step attends over the cache with its own
                # masked kernel; an unrecognized attention_fn (ring,
                # hand-rolled) would serve different numerics than it
                # trained with — reject rather than drift, naming the
                # supported kernel.
                raise NotImplementedError(
                    "serving supports cfg.attention_fn only for the "
                    "flash-attention family (autodist_tpu.ops."
                    "make_attention_fn / flash_attention — numerics-"
                    "equivalent to the trained einsum path, decode "
                    "served by the flash-decode cache kernel); got "
                    f"{getattr(attn_fn, '__name__', attn_fn)!r} — "
                    "clear attention_fn or use the supported kernel")
            # Flash prefill ⇒ flash decode: the decode-parity gate (the
            # greedy goldens pin decode token-for-token against the
            # sequential_logits reference, which runs the same
            # attention_fn).
            self.kernel = dict(self.kernel, flash_decode=True)
        if cfg.dropout_rate or cfg.attention_dropout_rate:
            raise ValueError(
                "serving requires dropout_rate == "
                "attention_dropout_rate == 0 (inference mode)")
        tp = int(tensor_parallel)
        if tp < 1:
            raise ValueError("tensor_parallel must be >= 1")
        if tp > 1 and cfg.num_heads % tp:
            raise ValueError(
                f"num_heads={cfg.num_heads} must divide by "
                f"tensor_parallel={tp}")
        self.tensor_parallel = tp
        self.vocab_parallel = bool(vocab_parallel) and tp > 1
        self.comm_overlap = normalize_comm_overlap(comm_overlap)
        self.num_slots = int(num_slots)
        self.max_len = int(max_len or cfg.max_len)
        if self.max_len > cfg.max_len:
            raise ValueError(
                f"max_len={self.max_len} exceeds the model's trained "
                f"position table ({cfg.max_len})")
        self.prefill_len = int(prefill_len or min(self.max_len, 16))
        if self.prefill_len > self.max_len:
            raise ValueError("prefill_len must be <= max_len")
        self.decode_steps = int(decode_steps)
        # ---- KV layout (Strategy-IR serving knob): dense per-slot
        # lanes, or the block-paged pool + table --------------------------
        self.kv_layout = normalize_kv_layout(kv_layout)
        self.kv_block_len = int(kv_block_len or min(16, self.max_len))
        if self.kv_block_len < 1:
            raise ValueError("kv_block_len must be >= 1")
        self.max_blocks = kv_cache.blocks_for(self.max_len,
                                              self.kv_block_len)
        # Default pool: byte parity with the dense cache (num_slots full
        # lanes) — the capacity win comes from admitting MORE slots than
        # the pool could hold at max_len, gated on free blocks.
        self.kv_num_blocks = int(kv_num_blocks
                                 or self.num_slots * self.max_blocks)
        if self.kv_layout == "paged" \
                and self.kv_num_blocks < self.max_blocks:
            raise ValueError(
                f"kv_num_blocks={self.kv_num_blocks} cannot hold even "
                f"one full-length request ({self.max_blocks} blocks of "
                f"{self.kv_block_len})")
        # ---- sampling rung (temperature == 0 is the exact greedy
        # program: the sampler is never traced, so the compiled decode
        # stays bit-identical to the greedy goldens) ----------------------
        self.temperature = float(temperature)
        if self.temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        self.top_k = int(top_k)
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")
        self._axis = const.MODEL_AXIS if tp > 1 else None

        if devices is None:
            devices = jax.devices()
        if tp > len(devices):
            raise ValueError(
                f"tensor_parallel={tp} needs {tp} devices; "
                f"{len(devices)} visible")
        self.mesh = (Mesh(np.array(devices[:tp]), (const.MODEL_AXIS,))
                     if tp > 1 else None)

        # ---- parameters: pad the vocab-sharded table, shard per the
        # Strategy-IR rule tables, place once ---------------------------
        params = jax.tree.map(jnp.asarray, params)
        if self.vocab_parallel:
            pad = vocab_pad(cfg.vocab_size, tp)
            if pad:
                emb = params["shared"]["embedding"]
                params = dict(params, shared=dict(
                    params["shared"],
                    embedding=jnp.pad(emb, ((0, pad), (0, 0)))))
        self._param_specs = serving_param_specs(params, tp,
                                                self.vocab_parallel)
        if self.mesh is not None:
            shardings = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), self._param_specs,
                is_leaf=lambda x: isinstance(x, P))
            params = jax.tree.map(jax.device_put, params, shardings)
        self.params = params

        # ---- cache + per-slot decode state -----------------------------
        self._tok = jnp.zeros((self.num_slots,), jnp.int32)
        self._sample_seeds = np.zeros((self.num_slots,), np.int32)
        if self.kv_layout == "paged":
            cache = kv_cache.init_paged_cache(
                cfg.num_layers, self.num_slots, cfg.num_heads,
                cfg.head_dim, self.max_len,
                block_len=self.kv_block_len,
                num_blocks=self.kv_num_blocks, dtype=cfg.dtype)
            # Host-side block accounting: the free-list allocator and
            # the numpy mirror of the device block table (refreshed
            # into the compiled programs as a replicated input).
            self._allocator = kv_cache.BlockAllocator(self.kv_num_blocks)
            self._table = np.zeros((self.num_slots, self.max_blocks),
                                   np.int32)
            self._slot_blocks: list = [[] for _ in range(self.num_slots)]
            if self.mesh is not None:
                csh = NamedSharding(self.mesh, kv_cache.cache_spec())
                rep = NamedSharding(self.mesh, P())
                cache = kv_cache.PagedKVCache(
                    k=jax.device_put(cache.k, csh),
                    v=jax.device_put(cache.v, csh),
                    lengths=jax.device_put(cache.lengths, rep),
                    block_table=jax.device_put(cache.block_table, rep))
            self._emit_block_gauges()
        else:
            cache = kv_cache.init_cache(
                cfg.num_layers, self.num_slots, cfg.num_heads,
                cfg.head_dim, self.max_len,
                dtype=cfg.dtype)
            self._allocator = None
            if self.mesh is not None:
                csh = NamedSharding(self.mesh, kv_cache.cache_spec())
                cache = kv_cache.KVCache(
                    k=jax.device_put(cache.k, csh),
                    v=jax.device_put(cache.v, csh),
                    lengths=jax.device_put(
                        cache.lengths, NamedSharding(self.mesh, P())))
        self.cache = cache

        self._prefill_jit = self._build_prefill()
        self._decode_jit = self._build_decode()
        if self.kernel.get("flash_decode"):
            # The serving-side kernel/<name>_elected gauge (the pipeline
            # lowering emits the training kernels' gauges) — schema-
            # gated by `tools/telemetry_report.py --check`.
            from autodist_tpu.parallel._spmd import emit_kernel_gauges
            emit_kernel_gauges({"flash_decode": True})

    # ------------------------------------------------------------------ #
    # constructors from the training stack
    # ------------------------------------------------------------------ #
    @classmethod
    def from_runner(cls, runner, cfg, *, strategy=None, **kw):
        """Serve a live runner's parameters (fetched through the
        gather/unpad path, any training strategy).  When the training
        ``strategy`` is given, its Strategy-IR parallel knobs
        (``tensor_parallel``/``vocab_parallel``/``comm_overlap``) seed
        the serving config unless overridden."""
        return cls(cfg, runner.get_params(),
                   **seed_engine_kwargs(kw, strategy))

    @classmethod
    def from_artifact(cls, path: str, cfg, **kw):
        """Serve a ``checkpoint/export.py`` artifact's ``params/``
        tree (logical names, unpadded shapes)."""
        from autodist_tpu.checkpoint.export import load_exported_params

        return cls(cfg, load_exported_params(path), **kw)

    # ------------------------------------------------------------------ #
    # the model math (one definition serves tp=1 and the shard_map path)
    # ------------------------------------------------------------------ #
    def _embed(self, shared, tokens, positions):
        """Token + position embedding for ``[B, S]`` token ids at
        per-token ``positions`` (``[B, S]`` or a static ``[S]``)."""
        cfg = self.cfg
        x = vocab_parallel_embedding(
            tokens, shared["embedding"], model_axis=self._axis
            if self.vocab_parallel else None,
            comm_overlap=self.comm_overlap).astype(cfg.dtype)
        pos = jnp.take(shared["pos_embed"], positions, axis=0)
        return x + pos.astype(cfg.dtype)

    def _layer_prefill(self, chunk, x, mask):
        """One encoder layer over the whole prompt — the training
        :func:`~autodist_tpu.models.pipeline_lm._tp_encoder_layer`
        itself (``return_kv=True`` hands back the layer's k/v
        projections for the cache fill), so the serving forward cannot
        drift from the trained math."""
        from autodist_tpu.models.pipeline_lm import _tp_encoder_layer

        return _tp_encoder_layer(self.cfg, chunk, x, mask, self._axis,
                                 comm_overlap=self.comm_overlap,
                                 return_kv=True)

    def _layer_decode(self, chunk, x, kc, vc, layer, lengths, table=None,
                      active=None):
        """One encoder layer for a single-token step: project, write
        this layer's k/v into the cache in place (through the block
        table under the paged layout, suppressed for inactive slots
        whose table rows hold no reservation), attend over the cache
        slice."""
        from autodist_tpu.models.pipeline_lm import _flax_layer_norm

        cfg, axis, overlap = self.cfg, self._axis, self.comm_overlap
        dtype = cfg.dtype
        att = chunk["attention"]
        x = x.astype(dtype)
        qkv = column_parallel(x, att["qkv"]["kernel"].astype(dtype),
                              att["qkv"]["bias"].astype(dtype),
                              model_axis=axis, comm_overlap=overlap)
        q, k, v = jnp.moveaxis(qkv, -3, 0)          # [B, 1, heads, dh]
        if table is not None:
            bl = self.kv_block_len
            kc = kv_cache.paged_write_token(kc, layer, k, lengths,
                                            table, bl, write_mask=active)
            vc = kv_cache.paged_write_token(vc, layer, v, lengths,
                                            table, bl, write_mask=active)
            if self.kernel.get("flash_decode"):
                from autodist_tpu.kernel.pallas.flash_decode import \
                    flash_decode_attention_paged
                out = flash_decode_attention_paged(
                    q, kc[layer], vc[layer], lengths, table,
                    block_len=bl, dtype=dtype)
            else:
                out = kv_cache.paged_cached_attention(
                    q, kc[layer], vc[layer], lengths, table,
                    block_len=bl, dtype=dtype)
        else:
            kc = kv_cache.write_token(kc, layer, k, lengths)
            vc = kv_cache.write_token(vc, layer, v, lengths)
            if self.kernel.get("flash_decode"):
                from autodist_tpu.kernel.pallas.flash_decode import \
                    flash_decode_attention
                out = flash_decode_attention(q, kc[layer], vc[layer],
                                             lengths, dtype=dtype)
            else:
                out = kv_cache.cached_attention(q, kc[layer], vc[layer],
                                                lengths, dtype=dtype)
        a = row_parallel(out, att["out"]["kernel"].astype(dtype),
                         att["out"]["bias"].astype(dtype),
                         model_axis=axis, axes=2, comm_overlap=overlap)
        x = _flax_layer_norm(x + a, chunk["ln_attention"], dtype)
        h = column_parallel(x, chunk["mlp"]["wi"]["kernel"].astype(dtype),
                            chunk["mlp"]["wi"]["bias"].astype(dtype),
                            model_axis=axis, comm_overlap=overlap)
        h = jax.nn.gelu(h)
        m = row_parallel(h, chunk["mlp"]["wo"]["kernel"].astype(dtype),
                         chunk["mlp"]["wo"]["bias"].astype(dtype),
                         model_axis=axis, comm_overlap=overlap)
        return _flax_layer_norm(x + m, chunk["ln_mlp"], dtype), kc, vc

    def _greedy(self, shared, h):
        """Next token from ``[B, H]`` last-position hidden states (the
        training loss head's ``_layer_norm`` + tied unembedding)."""
        from autodist_tpu.models.pipeline_lm import _layer_norm

        x = _layer_norm(h, shared["ln_final_scale"],
                        shared["ln_final_bias"])
        return vocab_parallel_greedy_token(
            x, shared["embedding"], vocab_size=self.cfg.vocab_size,
            model_axis=self._axis if self.vocab_parallel else None)

    def _next_token(self, shared, h, seeds, positions):
        """The decode epilogue: greedy at ``temperature == 0`` (the
        exact pre-sampling program — the sampler is never traced), else
        shard-invariant gumbel-max sampling keyed per (request seed,
        context length), so a sampled stream is identical interleaved,
        run-alone, and against the sequential reference."""
        if self.temperature == 0.0:
            return self._greedy(shared, h)
        from autodist_tpu.models.pipeline_lm import _layer_norm
        from autodist_tpu.parallel.tensor import \
            vocab_parallel_sample_token

        x = _layer_norm(h, shared["ln_final_scale"],
                        shared["ln_final_bias"])
        return vocab_parallel_sample_token(
            x, shared["embedding"], vocab_size=self.cfg.vocab_size,
            seeds=seeds, positions=positions,
            temperature=self.temperature, top_k=self.top_k,
            model_axis=self._axis if self.vocab_parallel else None)

    # ------------------------------------------------------------------ #
    # compiled programs
    # ------------------------------------------------------------------ #
    def _wrap(self, fn, n_in_rest: int, n_out_rest: int):
        """jit ``fn(params, k, v, *rest)``, shard_mapped over the model
        mesh at tp>1, with the cache arrays donated so updates alias in
        place.  ``n_in_rest``/``n_out_rest`` count the replicated
        non-cache operands/results after ``(params, k, v)`` /
        ``(k, v)``."""
        if self.mesh is None:
            return jax.jit(fn, donate_argnums=(1, 2))
        cspec = kv_cache.cache_spec()
        sm = jax.shard_map(
            fn, mesh=self.mesh,
            in_specs=(self._param_specs, cspec, cspec)
            + (P(),) * n_in_rest,
            out_specs=(cspec, cspec) + (P(),) * n_out_rest,
            check_vma=False)
        return jax.jit(sm, donate_argnums=(1, 2))

    def _build_prefill(self):
        L, S = self.cfg.num_layers, self.prefill_len
        paged = self.kv_layout == "paged"

        def prefill(params, kc, vc, lengths, tok, table, seeds, prompts,
                    p_lens, admit):
            stages, shared = params["stages"], params["shared"]
            x = self._embed(shared, prompts, jnp.arange(S))
            mask = jnp.tril(jnp.ones((S, S), bool))[None, None]
            for layer in range(L):
                chunk = jax.tree.map(lambda p: p[layer], stages)
                x, k, v = self._layer_prefill(chunk, x, mask)
                if paged:
                    kc = kv_cache.paged_write_prompt(
                        kc, layer, k, admit, table, self.kv_block_len,
                        p_lens)
                    vc = kv_cache.paged_write_prompt(
                        vc, layer, v, admit, table, self.kv_block_len,
                        p_lens)
                else:
                    kc = kv_cache.write_prompt(kc, layer, k, admit)
                    vc = kv_cache.write_prompt(vc, layer, v, admit)
            last = jnp.take_along_axis(
                x, (p_lens - 1)[:, None, None], axis=1)[:, 0]
            # The first emitted token conditions on the p_lens prompt
            # tokens — its sampling key position.
            first_tok, _ = self._next_token(shared, last, seeds, p_lens)
            tok = jnp.where(admit, first_tok, tok)
            lengths = jnp.where(admit, p_lens, lengths)
            return kc, vc, lengths, tok

        return self._wrap(prefill, n_in_rest=7, n_out_rest=2)

    def _build_decode(self):
        L, K = self.cfg.num_layers, self.decode_steps
        paged = self.kv_layout == "paged"

        def decode(params, kc, vc, lengths, tok, table, seeds, active):
            stages, shared = params["stages"], params["shared"]

            def body(carry, _):
                kc, vc, lengths, tok = carry
                x = self._embed(shared, tok[:, None], lengths[:, None])
                for layer in range(L):
                    chunk = jax.tree.map(lambda p: p[layer], stages)
                    x, kc, vc = self._layer_decode(
                        chunk, x, kc, vc, layer, lengths,
                        table=table if paged else None, active=active)
                # The emitted token conditions on lengths + 1 tokens
                # (the one just written included) — its sampling key.
                nxt, _ = self._next_token(shared, x[:, 0], seeds,
                                          lengths + 1)
                nxt = jnp.where(active, nxt, tok)
                lengths = lengths + active.astype(jnp.int32)
                return (kc, vc, lengths, nxt), nxt

            (kc, vc, lengths, tok), toks = lax.scan(
                body, (kc, vc, lengths, tok), None, length=K)
            return kc, vc, lengths, tok, toks

        return self._wrap(decode, n_in_rest=5, n_out_rest=3)

    # ------------------------------------------------------------------ #
    # host-side block accounting (the batcher's admission predicate)
    # ------------------------------------------------------------------ #
    def blocks_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        """Pool blocks a request reserves: its worst-case occupancy
        ``min(prompt + budget, max_len)`` rounded up to blocks (0 under
        the dense layout — admission gates on slots alone there)."""
        if self.kv_layout != "paged":
            return 0
        span = min(int(prompt_len) + int(max_new_tokens), self.max_len)
        return kv_cache.blocks_for(span, self.kv_block_len)

    @property
    def free_blocks(self) -> int:
        """Unreserved pool blocks (dense: the pool concept is vacuous —
        reported as 0 used / 0 free is wrong either way, so dense
        returns a sentinel no admission check consults)."""
        return (self._allocator.free_blocks
                if self._allocator is not None else 0)

    def reserve_slot(self, slot: int, prompt_len: int,
                     max_new_tokens: int) -> None:
        """Map a request's blocks into ``slot``'s table row (paged;
        dense is a no-op).  Raises
        :class:`~autodist_tpu.serving.kv_cache.PoolExhaustedError` when
        the pool cannot cover it — the batcher checks
        :meth:`blocks_needed` against :attr:`free_blocks` first, so a
        raise here is a bookkeeping bug surfacing loudly."""
        if self._allocator is None:
            return
        if self._slot_blocks[slot]:
            raise ValueError(f"slot {slot} already holds blocks "
                             f"{self._slot_blocks[slot]}")
        n = self.blocks_needed(prompt_len, max_new_tokens)
        blocks = self._allocator.alloc(n)
        self._slot_blocks[slot] = blocks
        # Tail-fill the row with the slot's LAST block: an over-decode
        # position past the reservation (a final fused window's
        # overshoot, or the clamped >= max_len write) then routes into
        # the slot's own tail block — never block 0, which may be
        # another slot's live block.
        self._table[slot, :] = blocks[-1]
        self._table[slot, :n] = blocks
        self._sync_table()
        self._emit_block_gauges()

    def release_slot(self, slot: int) -> None:
        """Return ``slot``'s blocks to the free list (paged; dense is a
        no-op).  The pool rows keep their stale content — unreachable
        behind the next owner's length mask."""
        if self._allocator is None:
            return
        if self._slot_blocks[slot]:
            self._allocator.free(self._slot_blocks[slot])
            self._slot_blocks[slot] = []
            self._table[slot, :] = 0
            self._sync_table()
            self._emit_block_gauges()

    def block_accounting(self) -> tuple:
        """``(free, used, total)`` pool blocks — the invariant every
        terminal state must restore is ``free + used == total`` (and
        ``free == total`` once no request is resident).  Dense engines
        report the vacuous ``(0, 0, 0)``."""
        if self._allocator is None:
            return (0, 0, 0)
        return (self._allocator.free_blocks, self._allocator.used_blocks,
                self.kv_num_blocks)

    def release_all_slots(self) -> None:
        """Return EVERY slot's blocks to the free list — the abandon
        path: a fleet replica declared dead releases its engine
        wholesale (a real crashed host frees its HBM with it; the
        in-process model must not let the bookkeeping say otherwise)."""
        for slot in range(self.num_slots):
            self.release_slot(slot)

    def _emit_block_gauges(self):
        from autodist_tpu import telemetry

        telemetry.gauge("serve/kv_blocks_free").set(
            self._allocator.free_blocks)
        telemetry.gauge("serve/kv_blocks_used").set(
            self._allocator.used_blocks)

    def _sync_table(self):
        """Mirror the host block table onto ``cache.block_table`` so
        the live cache pytree IS the complete decode state (a consumer
        serializing/inspecting ``engine.cache`` between dispatches —
        elastic checkpointing, debug dumps — must never see a stale
        mapping; the numpy ``_table`` stays the single source the
        device copy reflects)."""
        self.cache = kv_cache.PagedKVCache(
            k=self.cache.k, v=self.cache.v, lengths=self.cache.lengths,
            block_table=jnp.asarray(self._table))

    def _table_arg(self):
        if self.kv_layout == "paged":
            return self.cache.block_table
        return jnp.zeros((self.num_slots, 1), jnp.int32)

    # ------------------------------------------------------------------ #
    # host-side driver API (the batcher's contract)
    # ------------------------------------------------------------------ #
    def prefill(self, prompts, p_lens, admit, seeds=None):
        """Run one prefill over the slot batch; admitted slots adopt
        their prompt's cache/length and first generated token (greedy,
        or sampled at the engine's temperature under the slot's
        ``seeds`` entry).  Returns the per-slot current token ``[B]``
        (numpy)."""
        prompts = jnp.asarray(prompts, jnp.int32)
        p_lens = jnp.asarray(p_lens, jnp.int32)
        admit = jnp.asarray(admit, bool)
        if seeds is not None:
            self._sample_seeds = np.where(
                np.asarray(admit), np.asarray(seeds, np.int32),
                self._sample_seeds).astype(np.int32)
        c = self.cache
        k, v, lengths, tok = self._prefill_jit(
            self.params, c.k, c.v, c.lengths, self._tok,
            self._table_arg(), jnp.asarray(self._sample_seeds), prompts,
            p_lens, admit)
        self.cache = self._rebuild_cache(k, v, lengths)
        self._tok = tok
        return np.asarray(jax.device_get(tok))

    def decode(self, active):
        """One fused ``decode_steps``-token dispatch; inactive slots
        hold their state.  Returns the emitted tokens ``[K, B]``
        (numpy; inactive columns repeat the held token)."""
        active = jnp.asarray(active, bool)
        c = self.cache
        k, v, lengths, tok, toks = self._decode_jit(
            self.params, c.k, c.v, c.lengths, self._tok,
            self._table_arg(), jnp.asarray(self._sample_seeds), active)
        self.cache = self._rebuild_cache(k, v, lengths)
        self._tok = tok
        return np.asarray(jax.device_get(toks))

    def _rebuild_cache(self, k, v, lengths):
        if self.kv_layout == "paged":
            # block_table is kept current by _sync_table at every
            # reserve/release — the programs consumed this same array.
            return kv_cache.PagedKVCache(
                k=k, v=v, lengths=lengths,
                block_table=self.cache.block_table)
        return kv_cache.KVCache(k=k, v=v, lengths=lengths)

    @property
    def lengths(self):
        return np.asarray(jax.device_get(self.cache.lengths))

    # ------------------------------------------------------------------ #
    # HLO probe hooks (tools/hlo_probe.py --probe decode)
    # ------------------------------------------------------------------ #
    def compiled_decode_text(self) -> str:
        """Optimized HLO of the fused decode program."""
        c = self.cache
        active = jnp.ones((self.num_slots,), bool)
        return self._decode_jit.lower(
            self.params, c.k, c.v, c.lengths, self._tok,
            self._table_arg(), jnp.asarray(self._sample_seeds),
            active).compile().as_text()

    def compiled_prefill_text(self) -> str:
        """Optimized HLO of the prefill program."""
        c = self.cache
        prompts = jnp.zeros((self.num_slots, self.prefill_len), jnp.int32)
        p_lens = jnp.ones((self.num_slots,), jnp.int32)
        admit = jnp.ones((self.num_slots,), bool)
        return self._prefill_jit.lower(
            self.params, c.k, c.v, c.lengths, self._tok,
            self._table_arg(), jnp.asarray(self._sample_seeds), prompts,
            p_lens, admit).compile().as_text()
