"""Batched-inference engine on the Strategy IR's tensor-parallel specs.

The decode program reuses the training stack's hard parts instead of
growing a second model implementation:

* **Prefill** runs the prompt through the same column/row-parallel
  matmul boundaries as the training stage_fn
  (:mod:`autodist_tpu.parallel.tensor` — the ``PartitionerConfig`` spec
  table that answers "how do I train this" also answers "how do I serve
  it", the GSPMD one-IR property), filling the TP-sharded KV cache and
  emitting the first token from *last-position-only* logits.
* **Decode** runs a fused multi-step loop — the ``run_steps``
  steps-per-loop idea repurposed for token steps: one ``lax.scan`` body
  per token, one host dispatch per ``decode_steps`` tokens — attending
  over the cache via in-place ``dynamic_update_slice`` writes.  The
  greedy epilogue (:func:`~autodist_tpu.parallel.tensor
  .vocab_parallel_greedy_token`) keeps the live logits at ``[B, V/tp]``,
  so a decode step never materializes a full-vocab or full-sequence
  buffer (``tools/hlo_probe.py --probe decode`` asserts both
  structurally).

Parameters arrive in the *logical* layout every fetch path produces —
``runner.get_params()`` from a live pipelined-LM runner, or the
``params/`` tree of a ``checkpoint/export.py`` artifact — and the
engine shards them itself from the same rule tables the ``Pipeline``
builder records in the Strategy IR (``PIPELINE_TP_RULES`` /
``PIPELINE_VOCAB_RULES``).  Use :func:`autodist_tpu.serving.serve` for
the entry-point conveniences.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.serving import kv_cache
from autodist_tpu.parallel.tensor import (column_parallel,
                                          normalize_comm_overlap,
                                          row_parallel, vocab_pad,
                                          vocab_parallel_embedding,
                                          vocab_parallel_greedy_token)


@dataclasses.dataclass
class DecodeWindow:
    """One decode window's host-visible outcome — the batcher's unit of
    emission.  ``tokens`` is ``[n, B]`` with column ``i`` valid through
    ``counts[i]`` (vanilla windows emit a fixed ``decode_steps`` per
    active slot; speculative windows emit ``accepted + 1`` — variable,
    but never zero for an active slot, so forward progress is
    unconditional).  ``spec_proposed``/``spec_accepted`` feed the
    acceptance-rate telemetry; both all-zero on vanilla windows."""

    tokens: np.ndarray
    counts: np.ndarray
    spec_proposed: np.ndarray
    spec_accepted: np.ndarray


def serving_param_specs(params, tp: int, vocab_parallel: bool):
    """Per-leaf ``PartitionSpec`` tree for the serving mesh, from the
    SAME rule tables the ``Pipeline`` builder writes into the Strategy
    IR: stage leaves keep their stacked leading layer dim unsharded and
    shard the Megatron dims the tp rules name; the shared tied table
    shards its vocab dim iff ``vocab_parallel``; everything else
    replicates."""
    import re

    from autodist_tpu.kernel import common
    from autodist_tpu.strategy.parallel_builders import (
        PIPELINE_TP_RULES, PIPELINE_VOCAB_RULES)

    tp_rules = [(re.compile(p), s) for p, s in PIPELINE_TP_RULES]
    v_rules = [(re.compile(p), s) for p, s in PIPELINE_VOCAB_RULES]

    def spec_for(name, leaf):
        shape = tuple(np.shape(leaf))
        if tp > 1 and name.startswith("stages/"):
            for pat, spec in tp_rules:
                if pat.search(name) and len(spec) == len(shape) - 1:
                    for dim, axis in zip(shape[1:], spec):
                        if axis == const.MODEL_AXIS and dim % tp:
                            raise ValueError(
                                f"{name}: dim {dim} does not divide by "
                                f"tensor_parallel={tp}")
                    return P(None, *spec)
        if tp > 1 and vocab_parallel and name.startswith("shared/"):
            short = name[len("shared/"):]
            for pat, spec in v_rules:
                if pat.search(short) and len(spec) == len(shape):
                    return P(*spec)
        return P()

    return common.tree_from_names(params, spec_for)


def seed_engine_kwargs(engine_kwargs: dict, strategy) -> dict:
    """Default the serving parallelism knobs from a training strategy's
    Strategy-IR ``parallel`` record (explicit kwargs win) — the single
    definition behind every ``strategy=`` entry point, so a new
    Strategy-IR serving knob cannot be seeded by one path and missed by
    another."""
    if strategy is not None:
        from autodist_tpu.strategy.ir import (normalize_kv_layout,
                                              normalize_prefill_chunk,
                                              normalize_prefix_caching,
                                              normalize_speculative)

        par = strategy.graph_config.parallel or {}
        engine_kwargs.setdefault(
            "tensor_parallel", int(par.get("tensor_parallel", 1) or 1))
        engine_kwargs.setdefault(
            "vocab_parallel", bool(par.get("vocab_parallel", False)))
        engine_kwargs.setdefault("comm_overlap", par.get("comm_overlap"))
        engine_kwargs.setdefault(
            "kv_layout", normalize_kv_layout(par.get("kv_layout")))
        # The throughput-ladder knobs (PR 16) ride the same parallel
        # record; all three normalize to OFF when absent, so pre-PR-16
        # strategies seed exactly the pre-PR-16 engine.  A speculative
        # election still needs the caller to hand the engine its draft
        # model (draft_cfg/draft_params) — the IR records the decision,
        # not the weights.
        engine_kwargs.setdefault(
            "prefill_chunk",
            normalize_prefill_chunk(par.get("prefill_chunk")))
        engine_kwargs.setdefault(
            "prefix_caching",
            normalize_prefix_caching(par.get("prefix_caching")))
        engine_kwargs.setdefault(
            "speculative", normalize_speculative(par.get("speculative")))
        kern = getattr(strategy.graph_config, "kernel", None)
        if kern:
            engine_kwargs.setdefault("kernel", dict(kern))
    return engine_kwargs


class ServingEngine:
    """Prefill/decode engine for the pipelined transformer LM family.

    ``params``: the logical ``{"stages": ..., "shared": ...}`` tree of
    :func:`~autodist_tpu.models.pipeline_lm.make_pipeline_lm_trainable`
    (stacked per-layer leaves + tied embedding/unembedding).  Slots,
    prompt bucket, and the fused-decode width are static so the whole
    serving loop is exactly two compiled programs:

    * ``num_slots`` — batch slots the continuous batcher fills;
    * ``prefill_len`` — the prompt bucket (prompts zero-padded up to
      it; padded positions write garbage k/v that masked reads never
      see and forward decode overwrites);
    * ``decode_steps`` — tokens per fused decode dispatch (``K``).

    ``tensor_parallel``/``vocab_parallel``/``comm_overlap`` mirror the
    training ``Pipeline`` knobs; with ``tensor_parallel == 1`` the same
    code runs unsharded with zero collectives (the decode goldens'
    sequential-reference property).

    ``kv_layout`` (Strategy-IR serving knob, ``"dense"``/``"paged"``):
    ``"paged"`` replaces the per-slot ``max_len`` lanes with a block
    pool of ``kv_num_blocks`` blocks of ``kv_block_len`` positions and
    a per-slot block table — requests reserve only the blocks their
    ``prompt + budget`` span needs (:meth:`blocks_needed` /
    :meth:`reserve_slot` / :meth:`release_slot`), so the batcher admits
    against free blocks, not slots, and ``num_slots`` may exceed what
    the pool could hold at ``max_len``.

    ``temperature``/``top_k`` (the sampling rung): ``temperature == 0``
    (default) compiles the exact greedy program; ``> 0`` samples via
    the shard-invariant gumbel-max epilogue keyed per (request seed,
    context length) — see
    :func:`~autodist_tpu.parallel.tensor.vocab_parallel_sample_token`.
    """

    def __init__(self, cfg, params, *, tensor_parallel: int = 1,
                 vocab_parallel: bool = False, comm_overlap=None,
                 kernel=None,
                 num_slots: int = 4, max_len: Optional[int] = None,
                 prefill_len: Optional[int] = None, decode_steps: int = 8,
                 kv_layout: str = "dense",
                 kv_block_len: Optional[int] = None,
                 kv_num_blocks: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 prefill_chunk: Optional[int] = None,
                 prefix_caching: bool = False,
                 speculative: Optional[int] = None,
                 draft_cfg=None, draft_params=None,
                 devices=None):
        from autodist_tpu.strategy.ir import (normalize_kernel,
                                              normalize_kv_layout,
                                              normalize_prefill_chunk,
                                              normalize_prefix_caching,
                                              normalize_speculative)

        self.cfg = cfg
        # The fused-kernel election (Strategy IR kernel slot): only
        # flash_decode changes the serving programs — prefill/decode
        # have no grad sync or matmul-overlap ring for the training
        # kernels to replace.
        self.kernel = normalize_kernel(kernel)
        attn_fn = getattr(cfg, "attention_fn", None)
        if attn_fn is not None:
            from autodist_tpu.ops.flash_attention import \
                is_flash_attention_fn
            if not is_flash_attention_fn(attn_fn):
                # The decode step attends over the cache with its own
                # masked kernel; an unrecognized attention_fn (ring,
                # hand-rolled) would serve different numerics than it
                # trained with — reject rather than drift, naming the
                # supported kernel.
                raise NotImplementedError(
                    "serving supports cfg.attention_fn only for the "
                    "flash-attention family (autodist_tpu.ops."
                    "make_attention_fn / flash_attention — numerics-"
                    "equivalent to the trained einsum path, decode "
                    "served by the flash-decode cache kernel); got "
                    f"{getattr(attn_fn, '__name__', attn_fn)!r} — "
                    "clear attention_fn or use the supported kernel")
            # Flash prefill ⇒ flash decode: the decode-parity gate (the
            # greedy goldens pin decode token-for-token against the
            # sequential_logits reference, which runs the same
            # attention_fn).
            self.kernel = dict(self.kernel, flash_decode=True)
        if cfg.dropout_rate or cfg.attention_dropout_rate:
            raise ValueError(
                "serving requires dropout_rate == "
                "attention_dropout_rate == 0 (inference mode)")
        tp = int(tensor_parallel)
        if tp < 1:
            raise ValueError("tensor_parallel must be >= 1")
        if tp > 1 and cfg.num_heads % tp:
            raise ValueError(
                f"num_heads={cfg.num_heads} must divide by "
                f"tensor_parallel={tp}")
        self.tensor_parallel = tp
        self.vocab_parallel = bool(vocab_parallel) and tp > 1
        self.comm_overlap = normalize_comm_overlap(comm_overlap)
        self.num_slots = int(num_slots)
        self.max_len = int(max_len or cfg.max_len)
        if self.max_len > cfg.max_len:
            raise ValueError(
                f"max_len={self.max_len} exceeds the model's trained "
                f"position table ({cfg.max_len})")
        self.prefill_len = int(prefill_len or min(self.max_len, 16))
        if self.prefill_len > self.max_len:
            raise ValueError("prefill_len must be <= max_len")
        self.decode_steps = int(decode_steps)
        # ---- KV layout (Strategy-IR serving knob): dense per-slot
        # lanes, or the block-paged pool + table --------------------------
        self.kv_layout = normalize_kv_layout(kv_layout)
        self.kv_block_len = int(kv_block_len or min(16, self.max_len))
        if self.kv_block_len < 1:
            raise ValueError("kv_block_len must be >= 1")
        self.max_blocks = kv_cache.blocks_for(self.max_len,
                                              self.kv_block_len)
        # Default pool: byte parity with the dense cache (num_slots full
        # lanes) — the capacity win comes from admitting MORE slots than
        # the pool could hold at max_len, gated on free blocks.
        self.kv_num_blocks = int(kv_num_blocks
                                 or self.num_slots * self.max_blocks)
        if self.kv_layout == "paged" \
                and self.kv_num_blocks < self.max_blocks:
            raise ValueError(
                f"kv_num_blocks={self.kv_num_blocks} cannot hold even "
                f"one full-length request ({self.max_blocks} blocks of "
                f"{self.kv_block_len})")
        # ---- throughput-ladder knobs (PR 16): chunked prefill, prefix
        # caching, speculative decoding — all Strategy-IR seeded ---------
        self.prefill_chunk = normalize_prefill_chunk(prefill_chunk)
        if self.prefill_chunk is not None:
            if self.kv_layout != "paged":
                raise ValueError(
                    "prefill_chunk writes prompt chunks through the "
                    "block table — it requires kv_layout='paged'")
            if self.prefill_chunk % self.kv_block_len:
                raise ValueError(
                    f"prefill_chunk={self.prefill_chunk} must be a "
                    f"multiple of kv_block_len={self.kv_block_len} so "
                    "chunk writes stay block-granular")
        self.prefix_caching = normalize_prefix_caching(prefix_caching)
        if self.prefix_caching and self.kv_layout != "paged":
            raise ValueError(
                "prefix_caching shares physical pool blocks — it "
                "requires kv_layout='paged'")
        self.speculative = normalize_speculative(speculative)
        if self.speculative is not None \
                and (draft_cfg is None or draft_params is None):
            raise ValueError(
                "speculative decoding needs a draft model: pass "
                "draft_cfg and draft_params (the Strategy IR records "
                "the k election, not the weights)")
        # ---- sampling rung (temperature == 0 is the exact greedy
        # program: the sampler is never traced, so the compiled decode
        # stays bit-identical to the greedy goldens) ----------------------
        self.temperature = float(temperature)
        if self.temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        self.top_k = int(top_k)
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")
        self._axis = const.MODEL_AXIS if tp > 1 else None

        if devices is None:
            devices = jax.devices()
        if tp > len(devices):
            raise ValueError(
                f"tensor_parallel={tp} needs {tp} devices; "
                f"{len(devices)} visible")
        self.mesh = (Mesh(np.array(devices[:tp]), (const.MODEL_AXIS,))
                     if tp > 1 else None)

        # ---- parameters: pad the vocab-sharded table, shard per the
        # Strategy-IR rule tables, place once ---------------------------
        params = jax.tree.map(jnp.asarray, params)
        if self.vocab_parallel:
            pad = vocab_pad(cfg.vocab_size, tp)
            if pad:
                emb = params["shared"]["embedding"]
                params = dict(params, shared=dict(
                    params["shared"],
                    embedding=jnp.pad(emb, ((0, pad), (0, 0)))))
        self._param_specs = serving_param_specs(params, tp,
                                                self.vocab_parallel)
        if self.mesh is not None:
            shardings = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), self._param_specs,
                is_leaf=lambda x: isinstance(x, P))
            params = jax.tree.map(jax.device_put, params, shardings)
        self.params = params

        # ---- cache + per-slot decode state -----------------------------
        self._tok = jnp.zeros((self.num_slots,), jnp.int32)
        self._sample_seeds = np.zeros((self.num_slots,), np.int32)
        if self.kv_layout == "paged":
            cache = kv_cache.init_paged_cache(
                cfg.num_layers, self.num_slots, cfg.num_heads,
                cfg.head_dim, self.max_len,
                block_len=self.kv_block_len,
                num_blocks=self.kv_num_blocks, dtype=cfg.dtype)
            # Host-side block accounting: the free-list allocator and
            # the numpy mirror of the device block table (refreshed
            # into the compiled programs as a replicated input).
            self._allocator = kv_cache.BlockAllocator(self.kv_num_blocks)
            self._table = np.zeros((self.num_slots, self.max_blocks),
                                   np.int32)
            self._slot_blocks: list = [[] for _ in range(self.num_slots)]
            # Prefix-cache state: block-content keys -> ready physical
            # block (registered only AFTER the owning prefill dispatch
            # wrote it — a same-batch sibling must never share an
            # unwritten block), the reverse map for retirement at
            # refcount 0, per-slot novel-write floor and hit telemetry,
            # registrations pending the prefill, and the CoW reserve
            # pool: one pre-allocated replacement block per extra
            # reference on a shared *partial-tail* block, so a
            # copy-on-write can never hit an exhausted pool mid-stream.
            self._prefix_index: dict = {}
            self._block_keys: dict = {}
            self._pending_register: dict = {}
            self._cow_reserve: dict = {}
            self._write_from = np.zeros((self.num_slots,), np.int32)
            self._slot_hits = np.zeros((self.num_slots,), np.int32)
            if self.mesh is not None:
                csh = NamedSharding(self.mesh, kv_cache.cache_spec())
                rep = NamedSharding(self.mesh, P())
                cache = kv_cache.PagedKVCache(
                    k=jax.device_put(cache.k, csh),
                    v=jax.device_put(cache.v, csh),
                    lengths=jax.device_put(cache.lengths, rep),
                    block_table=jax.device_put(cache.block_table, rep))
            self._emit_block_gauges()
        else:
            cache = kv_cache.init_cache(
                cfg.num_layers, self.num_slots, cfg.num_heads,
                cfg.head_dim, self.max_len,
                dtype=cfg.dtype)
            self._allocator = None
            if self.mesh is not None:
                csh = NamedSharding(self.mesh, kv_cache.cache_spec())
                cache = kv_cache.KVCache(
                    k=jax.device_put(cache.k, csh),
                    v=jax.device_put(cache.v, csh),
                    lengths=jax.device_put(
                        cache.lengths, NamedSharding(self.mesh, P())))
        self.cache = cache

        self._prefill_jit = (self._build_chunk_prefill()
                             if self.prefill_chunk is not None
                             else self._build_prefill())
        self._decode_jit = self._build_decode()
        self._decode1_jit = None           # lazy K=1 program (catch-up)
        self._copy_block_jit = None        # lazy CoW device copy
        self.last_prefill_chunks = 0

        # ---- speculative draft: a nested engine sharing the cache
        # layout (same block scheme, own pool/params), run unsharded —
        # the draft is small by construction and its choices are shard-
        # invariant anyway (the gumbel keys are (seed, position)) -------
        self.draft = None
        if self.speculative is not None:
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab_size={draft_cfg.vocab_size} must "
                    f"match the target's {cfg.vocab_size} — accept/"
                    "reject compares token ids")
            self.draft = ServingEngine(
                draft_cfg, draft_params, tensor_parallel=1,
                vocab_parallel=False, num_slots=self.num_slots,
                max_len=self.max_len, prefill_len=self.prefill_len,
                decode_steps=self.speculative, kv_layout=self.kv_layout,
                kv_block_len=self.kv_block_len,
                temperature=self.temperature, top_k=self.top_k,
                prefill_chunk=self.prefill_chunk)
            self._spec_verify_jit = self._build_spec_verify()
            self._spec_catch = np.zeros((self.num_slots,), bool)
            self._spec_catch_tok = np.zeros((self.num_slots,), np.int32)

        gauges = {k: True for k in ("flash_decode", "flash_prefill")
                  if self.kernel.get(k)}
        if gauges:
            # The serving-side kernel/<name>_elected gauge (the pipeline
            # lowering emits the training kernels' gauges) — schema-
            # gated by `tools/telemetry_report.py --check`.
            from autodist_tpu.parallel._spmd import emit_kernel_gauges
            emit_kernel_gauges(gauges)

    # ------------------------------------------------------------------ #
    # constructors from the training stack
    # ------------------------------------------------------------------ #
    @classmethod
    def from_runner(cls, runner, cfg, *, strategy=None, **kw):
        """Serve a live runner's parameters (fetched through the
        gather/unpad path, any training strategy).  When the training
        ``strategy`` is given, its Strategy-IR parallel knobs
        (``tensor_parallel``/``vocab_parallel``/``comm_overlap``) seed
        the serving config unless overridden."""
        return cls(cfg, runner.get_params(),
                   **seed_engine_kwargs(kw, strategy))

    @classmethod
    def from_artifact(cls, path: str, cfg, **kw):
        """Serve a ``checkpoint/export.py`` artifact's ``params/``
        tree (logical names, unpadded shapes)."""
        from autodist_tpu.checkpoint.export import load_exported_params

        return cls(cfg, load_exported_params(path), **kw)

    # ------------------------------------------------------------------ #
    # the model math (one definition serves tp=1 and the shard_map path)
    # ------------------------------------------------------------------ #
    def _embed(self, shared, tokens, positions):
        """Token + position embedding for ``[B, S]`` token ids at
        per-token ``positions`` (``[B, S]`` or a static ``[S]``)."""
        cfg = self.cfg
        x = vocab_parallel_embedding(
            tokens, shared["embedding"], model_axis=self._axis
            if self.vocab_parallel else None,
            comm_overlap=self.comm_overlap).astype(cfg.dtype)
        pos = jnp.take(shared["pos_embed"], positions, axis=0)
        return x + pos.astype(cfg.dtype)

    def _layer_prefill(self, chunk, x, mask):
        """One encoder layer over the whole prompt — the training
        :func:`~autodist_tpu.models.pipeline_lm._tp_encoder_layer`
        itself (``return_kv=True`` hands back the layer's k/v
        projections for the cache fill), so the serving forward cannot
        drift from the trained math."""
        from autodist_tpu.models.pipeline_lm import _tp_encoder_layer

        return _tp_encoder_layer(self.cfg, chunk, x, mask, self._axis,
                                 comm_overlap=self.comm_overlap,
                                 return_kv=True)

    def _layer_decode(self, chunk, x, kc, vc, layer, lengths, table=None,
                      active=None):
        """One encoder layer for a single-token step: project, write
        this layer's k/v into the cache in place (through the block
        table under the paged layout, suppressed for inactive slots
        whose table rows hold no reservation), attend over the cache
        slice."""
        from autodist_tpu.models.pipeline_lm import _flax_layer_norm

        cfg, axis, overlap = self.cfg, self._axis, self.comm_overlap
        dtype = cfg.dtype
        att = chunk["attention"]
        x = x.astype(dtype)
        qkv = column_parallel(x, att["qkv"]["kernel"].astype(dtype),
                              att["qkv"]["bias"].astype(dtype),
                              model_axis=axis, comm_overlap=overlap)
        q, k, v = jnp.moveaxis(qkv, -3, 0)          # [B, 1, heads, dh]
        if table is not None:
            bl = self.kv_block_len
            kc = kv_cache.paged_write_token(kc, layer, k, lengths,
                                            table, bl, write_mask=active)
            vc = kv_cache.paged_write_token(vc, layer, v, lengths,
                                            table, bl, write_mask=active)
            if self.kernel.get("flash_decode"):
                from autodist_tpu.kernel.pallas.flash_decode import \
                    flash_decode_attention_paged
                out = flash_decode_attention_paged(
                    q, kc[layer], vc[layer], lengths, table,
                    block_len=bl, dtype=dtype)
            else:
                out = kv_cache.paged_cached_attention(
                    q, kc[layer], vc[layer], lengths, table,
                    block_len=bl, dtype=dtype)
        else:
            kc = kv_cache.write_token(kc, layer, k, lengths)
            vc = kv_cache.write_token(vc, layer, v, lengths)
            if self.kernel.get("flash_decode"):
                from autodist_tpu.kernel.pallas.flash_decode import \
                    flash_decode_attention
                out = flash_decode_attention(q, kc[layer], vc[layer],
                                             lengths, dtype=dtype)
            else:
                out = kv_cache.cached_attention(q, kc[layer], vc[layer],
                                                lengths, dtype=dtype)
        a = row_parallel(out, att["out"]["kernel"].astype(dtype),
                         att["out"]["bias"].astype(dtype),
                         model_axis=axis, axes=2, comm_overlap=overlap)
        x = _flax_layer_norm(x + a, chunk["ln_attention"], dtype)
        h = column_parallel(x, chunk["mlp"]["wi"]["kernel"].astype(dtype),
                            chunk["mlp"]["wi"]["bias"].astype(dtype),
                            model_axis=axis, comm_overlap=overlap)
        h = jax.nn.gelu(h)
        m = row_parallel(h, chunk["mlp"]["wo"]["kernel"].astype(dtype),
                         chunk["mlp"]["wo"]["bias"].astype(dtype),
                         model_axis=axis, comm_overlap=overlap)
        return _flax_layer_norm(x + m, chunk["ln_mlp"], dtype), kc, vc

    def _layer_chunk(self, chunk, x, kc, vc, layer, starts, table, write):
        """One encoder layer for a ``[B, C]`` token *window* against the
        live cache — the shape chunked prefill and the speculative
        verify pass share.  Project the window's qkv, hand k/v to the
        caller's ``write`` (block-granular for prompt chunks,
        token-granular for the verify window), then attend the window's
        queries over the cache — which now holds every earlier position
        AND this window's own rows (write-then-attend, the decode
        step's ordering), masked causally at ``key <= starts + row``."""
        from autodist_tpu.models.pipeline_lm import _flax_layer_norm

        cfg, axis, overlap = self.cfg, self._axis, self.comm_overlap
        dtype = cfg.dtype
        att = chunk["attention"]
        x = x.astype(dtype)
        qkv = column_parallel(x, att["qkv"]["kernel"].astype(dtype),
                              att["qkv"]["bias"].astype(dtype),
                              model_axis=axis, comm_overlap=overlap)
        q, k, v = jnp.moveaxis(qkv, -3, 0)          # [B, C, heads, dh]
        kc, vc = write(kc, vc, k, v)
        if table is not None:
            bl = self.kv_block_len
            if self.kernel.get("flash_prefill"):
                from autodist_tpu.kernel.pallas.flash_prefill import \
                    flash_prefill_attention_paged
                out = flash_prefill_attention_paged(
                    q, kc[layer], vc[layer], starts, table,
                    block_len=bl, dtype=dtype)
            else:
                out = kv_cache.paged_chunk_attention(
                    q, kc[layer], vc[layer], starts, table,
                    block_len=bl, dtype=dtype)
        else:
            out = kv_cache.chunk_attention(q, kc[layer], vc[layer],
                                           starts, dtype=dtype)
        a = row_parallel(out, att["out"]["kernel"].astype(dtype),
                         att["out"]["bias"].astype(dtype),
                         model_axis=axis, axes=2, comm_overlap=overlap)
        x = _flax_layer_norm(x + a, chunk["ln_attention"], dtype)
        h = column_parallel(x, chunk["mlp"]["wi"]["kernel"].astype(dtype),
                            chunk["mlp"]["wi"]["bias"].astype(dtype),
                            model_axis=axis, comm_overlap=overlap)
        h = jax.nn.gelu(h)
        m = row_parallel(h, chunk["mlp"]["wo"]["kernel"].astype(dtype),
                         chunk["mlp"]["wo"]["bias"].astype(dtype),
                         model_axis=axis, comm_overlap=overlap)
        return _flax_layer_norm(x + m, chunk["ln_mlp"], dtype), kc, vc

    def _greedy(self, shared, h):
        """Next token from ``[B, H]`` last-position hidden states (the
        training loss head's ``_layer_norm`` + tied unembedding)."""
        from autodist_tpu.models.pipeline_lm import _layer_norm

        x = _layer_norm(h, shared["ln_final_scale"],
                        shared["ln_final_bias"])
        return vocab_parallel_greedy_token(
            x, shared["embedding"], vocab_size=self.cfg.vocab_size,
            model_axis=self._axis if self.vocab_parallel else None)

    def _next_token(self, shared, h, seeds, positions):
        """The decode epilogue: greedy at ``temperature == 0`` (the
        exact pre-sampling program — the sampler is never traced), else
        shard-invariant gumbel-max sampling keyed per (request seed,
        context length), so a sampled stream is identical interleaved,
        run-alone, and against the sequential reference."""
        if self.temperature == 0.0:
            return self._greedy(shared, h)
        from autodist_tpu.models.pipeline_lm import _layer_norm
        from autodist_tpu.parallel.tensor import \
            vocab_parallel_sample_token

        x = _layer_norm(h, shared["ln_final_scale"],
                        shared["ln_final_bias"])
        return vocab_parallel_sample_token(
            x, shared["embedding"], vocab_size=self.cfg.vocab_size,
            seeds=seeds, positions=positions,
            temperature=self.temperature, top_k=self.top_k,
            model_axis=self._axis if self.vocab_parallel else None)

    # ------------------------------------------------------------------ #
    # compiled programs
    # ------------------------------------------------------------------ #
    def _wrap(self, fn, n_in_rest: int, n_out_rest: int):
        """jit ``fn(params, k, v, *rest)``, shard_mapped over the model
        mesh at tp>1, with the cache arrays donated so updates alias in
        place.  ``n_in_rest``/``n_out_rest`` count the replicated
        non-cache operands/results after ``(params, k, v)`` /
        ``(k, v)``."""
        if self.mesh is None:
            return jax.jit(fn, donate_argnums=(1, 2))
        cspec = kv_cache.cache_spec()
        sm = jax.shard_map(
            fn, mesh=self.mesh,
            in_specs=(self._param_specs, cspec, cspec)
            + (P(),) * n_in_rest,
            out_specs=(cspec, cspec) + (P(),) * n_out_rest,
            check_vma=False)
        return jax.jit(sm, donate_argnums=(1, 2))

    def _build_prefill(self):
        L, S = self.cfg.num_layers, self.prefill_len
        paged = self.kv_layout == "paged"
        prefix = self.prefix_caching

        def prefill(params, kc, vc, lengths, tok, table, seeds, prompts,
                    p_lens, admit, *rest):
            # Prefix-caching engines thread a per-slot novel-write
            # floor; without the knob the program keeps its pre-PR-16
            # signature and HLO bit-for-bit.
            wf = rest[0] if prefix else None
            stages, shared = params["stages"], params["shared"]
            x = self._embed(shared, prompts, jnp.arange(S))
            mask = jnp.tril(jnp.ones((S, S), bool))[None, None]
            for layer in range(L):
                chunk = jax.tree.map(lambda p: p[layer], stages)
                x, k, v = self._layer_prefill(chunk, x, mask)
                if paged:
                    kc = kv_cache.paged_write_prompt(
                        kc, layer, k, admit, table, self.kv_block_len,
                        p_lens, write_from=wf)
                    vc = kv_cache.paged_write_prompt(
                        vc, layer, v, admit, table, self.kv_block_len,
                        p_lens, write_from=wf)
                else:
                    kc = kv_cache.write_prompt(kc, layer, k, admit)
                    vc = kv_cache.write_prompt(vc, layer, v, admit)
            last = jnp.take_along_axis(
                x, (p_lens - 1)[:, None, None], axis=1)[:, 0]
            # The first emitted token conditions on the p_lens prompt
            # tokens — its sampling key position.
            first_tok, _ = self._next_token(shared, last, seeds, p_lens)
            tok = jnp.where(admit, first_tok, tok)
            lengths = jnp.where(admit, p_lens, lengths)
            return kc, vc, lengths, tok

        return self._wrap(prefill, n_in_rest=7 + (1 if prefix else 0),
                          n_out_rest=2)

    def _build_chunk_prefill(self):
        """The chunked prefill program: ONE compiled ``[B, C]`` window
        serves every chunk of every prompt length (``chunk_start`` is a
        traced scalar), writing k/v block-granularly through the table
        and attending across chunks via the paged chunk attention (the
        flash-prefill kernel when elected).  The slot whose final
        prompt token falls inside this chunk emits its first generated
        token here — other slots pass through — so the host loop's last
        relevant chunk completes exactly what single-shot prefill does,
        token-for-token (the parity golden)."""
        L, C = self.cfg.num_layers, self.prefill_chunk
        bl = self.kv_block_len
        prefix = self.prefix_caching

        def chunk_prefill(params, kc, vc, lengths, tok, table, seeds,
                          chunk_toks, chunk_start, p_lens, admit, *rest):
            wf = rest[0] if prefix else None
            stages, shared = params["stages"], params["shared"]
            x = self._embed(shared, chunk_toks,
                            chunk_start + jnp.arange(C))
            starts = jnp.zeros_like(p_lens) + chunk_start
            for layer in range(L):
                chunk = jax.tree.map(lambda p: p[layer], stages)

                def write(kc, vc, k, v, layer=layer):
                    kc = kv_cache.paged_write_chunk(
                        kc, layer, k, admit, table, bl, chunk_start,
                        p_lens, write_from=wf)
                    vc = kv_cache.paged_write_chunk(
                        vc, layer, v, admit, table, bl, chunk_start,
                        p_lens, write_from=wf)
                    return kc, vc

                x, kc, vc = self._layer_chunk(chunk, x, kc, vc, layer,
                                              starts, table, write)
            emit_here = admit & (p_lens > chunk_start) \
                & (p_lens <= chunk_start + C)
            last_idx = jnp.clip(p_lens - 1 - chunk_start, 0, C - 1)
            last = jnp.take_along_axis(
                x, last_idx[:, None, None], axis=1)[:, 0]
            first_tok, _ = self._next_token(shared, last, seeds, p_lens)
            tok = jnp.where(emit_here, first_tok, tok)
            lengths = jnp.where(emit_here, p_lens, lengths)
            return kc, vc, lengths, tok

        return self._wrap(chunk_prefill,
                          n_in_rest=8 + (1 if prefix else 0),
                          n_out_rest=2)

    def _build_spec_verify(self):
        """The speculative verify program: feed the current token plus
        the draft's k proposals as one ``[B, k+1]`` window starting at
        each slot's own length, write their k/v token-granularly, and
        return the target's choice at EVERY window position — computed
        by the same epilogue and the same (seed, position) keys vanilla
        decode would use, so the accepted prefix is token-for-token
        (greedy) and draw-for-draw (sampled) what vanilla would have
        emitted.  Lengths do NOT advance here: the host applies the
        accept/reject rule and rolls the rejected tail back by setting
        lengths, which un-materializes the stale rows behind the length
        mask (their blocks stay within the slot's reservation)."""
        L, C = self.cfg.num_layers, self.speculative + 1
        paged = self.kv_layout == "paged"
        bl = self.kv_block_len

        def verify(params, kc, vc, lengths, tok, table, seeds,
                   tokens_in, active):
            stages, shared = params["stages"], params["shared"]
            positions = lengths[:, None] + jnp.arange(C)[None, :]
            x = self._embed(shared, tokens_in, positions)
            for layer in range(L):
                chunk = jax.tree.map(lambda p: p[layer], stages)

                def write(kc, vc, k, v, layer=layer):
                    for c in range(C):
                        if paged:
                            kc = kv_cache.paged_write_token(
                                kc, layer, k[:, c:c + 1], lengths + c,
                                table, bl, write_mask=active)
                            vc = kv_cache.paged_write_token(
                                vc, layer, v[:, c:c + 1], lengths + c,
                                table, bl, write_mask=active)
                        else:
                            kc = kv_cache.write_token(
                                kc, layer, k[:, c:c + 1], lengths + c)
                            vc = kv_cache.write_token(
                                vc, layer, v[:, c:c + 1], lengths + c)
                    return kc, vc

                x, kc, vc = self._layer_chunk(
                    chunk, x, kc, vc, layer, lengths,
                    table if paged else None, write)
            # Choice at window row c conditions on lengths + 1 + c
            # tokens — exactly the position key the c-th vanilla decode
            # step would use.
            choices = jnp.stack(
                [self._next_token(shared, x[:, c], seeds,
                                  lengths + 1 + c)[0]
                 for c in range(C)], axis=1)         # [B, C]
            return kc, vc, lengths, tok, choices

        return self._wrap(verify, n_in_rest=6, n_out_rest=3)

    def _build_decode(self, steps: Optional[int] = None):
        L, K = self.cfg.num_layers, int(steps or self.decode_steps)
        paged = self.kv_layout == "paged"

        def decode(params, kc, vc, lengths, tok, table, seeds, active):
            stages, shared = params["stages"], params["shared"]

            def body(carry, _):
                kc, vc, lengths, tok = carry
                x = self._embed(shared, tok[:, None], lengths[:, None])
                for layer in range(L):
                    chunk = jax.tree.map(lambda p: p[layer], stages)
                    x, kc, vc = self._layer_decode(
                        chunk, x, kc, vc, layer, lengths,
                        table=table if paged else None, active=active)
                # The emitted token conditions on lengths + 1 tokens
                # (the one just written included) — its sampling key.
                nxt, _ = self._next_token(shared, x[:, 0], seeds,
                                          lengths + 1)
                nxt = jnp.where(active, nxt, tok)
                lengths = lengths + active.astype(jnp.int32)
                return (kc, vc, lengths, nxt), nxt

            (kc, vc, lengths, tok), toks = lax.scan(
                body, (kc, vc, lengths, tok), None, length=K)
            return kc, vc, lengths, tok, toks

        return self._wrap(decode, n_in_rest=5, n_out_rest=3)

    # ------------------------------------------------------------------ #
    # host-side block accounting (the batcher's admission predicate)
    # ------------------------------------------------------------------ #
    def _prefix_lookup(self, prompt, prompt_len):
        """Walk the prefix index for ``prompt``'s leading blocks.
        Returns ``(hits, novel, partial_hit)``: ``hits`` — physical
        blocks already holding the shared prefix (a contiguous leading
        run; the chained keys make the first miss terminal), ``novel``
        — ``{logical_index: key}`` for the blocks THIS request must
        compute (registered only after its prefill lands, so a same-
        batch sharer can never read an unwritten block), and
        ``partial_hit`` — the shared partial-tail physical block, or
        ``None``.  A partial hit is the one shared block decode will
        write into, so admission pre-funds its copy-on-write."""
        if not self.prefix_caching or prompt is None:
            return [], {}, None
        toks = np.asarray(prompt).reshape(-1)[:int(prompt_len)]
        full_keys, partial_key = kv_cache.prefix_block_keys(
            toks, self.kv_block_len)
        hits, novel, partial_hit = [], {}, None
        miss = False
        for j, key in enumerate(full_keys):
            phys = None if miss else self._prefix_index.get(key)
            if phys is None:
                miss = True
                novel[j] = key
            else:
                hits.append(phys)
        if partial_key is not None:
            j = len(full_keys)
            phys = None if miss else self._prefix_index.get(partial_key)
            if phys is None:
                novel[j] = partial_key
            else:
                hits.append(phys)
                partial_hit = phys
        return hits, novel, partial_hit

    def blocks_needed(self, prompt_len: int, max_new_tokens: int,
                      prompt=None) -> int:
        """Pool blocks a request reserves: its worst-case occupancy
        ``min(prompt + budget, max_len)`` rounded up to blocks (0 under
        the dense layout — admission gates on slots alone there).
        Under prefix caching, pass ``prompt`` and the charge drops to
        the NOVEL suffix — shared leading blocks cost nothing (plus one
        pre-funded copy-on-write reserve when the partial tail is
        shared: the block decode writes into must have a private copy
        standing by, or a full pool could deadlock the write)."""
        if self.kv_layout != "paged":
            return 0
        span = min(int(prompt_len) + int(max_new_tokens), self.max_len)
        n = kv_cache.blocks_for(span, self.kv_block_len)
        hits, _, partial_hit = self._prefix_lookup(prompt, prompt_len)
        return n - len(hits) + (1 if partial_hit is not None else 0)

    @property
    def free_blocks(self) -> int:
        """Unreserved pool blocks (dense: the pool concept is vacuous —
        reported as 0 used / 0 free is wrong either way, so dense
        returns a sentinel no admission check consults)."""
        return (self._allocator.free_blocks
                if self._allocator is not None else 0)

    def reserve_slot(self, slot: int, prompt_len: int,
                     max_new_tokens: int, prompt=None) -> int:
        """Map a request's blocks into ``slot``'s table row (paged;
        dense is a no-op).  Under prefix caching (``prompt`` given) the
        leading shared blocks are reference-bumped instead of
        allocated; only the novel suffix (plus one copy-on-write
        reserve for a shared partial tail) draws on the pool.  Returns
        the number of prefix-hit blocks.  Raises
        :class:`~autodist_tpu.serving.kv_cache.PoolExhaustedError` when
        the pool cannot cover it — the batcher checks
        :meth:`blocks_needed` against :attr:`free_blocks` first, so a
        raise here is a bookkeeping bug surfacing loudly (and it raises
        BEFORE any refcount is bumped, so a failed admission leaves the
        pool untouched)."""
        if self._allocator is None:
            return 0
        if self._slot_blocks[slot]:
            raise ValueError(f"slot {slot} already holds blocks "
                             f"{self._slot_blocks[slot]}")
        span = min(int(prompt_len) + int(max_new_tokens), self.max_len)
        n = kv_cache.blocks_for(span, self.kv_block_len)
        hits, novel, partial_hit = self._prefix_lookup(prompt, prompt_len)
        n_hit = len(hits)
        need = n - n_hit + (1 if partial_hit is not None else 0)
        new_blocks = self._allocator.alloc(need)
        if partial_hit is not None:
            # The shared partial-tail block WILL be written (the first
            # generated token lands inside it): park one replacement
            # block per extra reference so the copy-on-write in
            # _cow_protect never has to allocate mid-stream.
            self._cow_reserve.setdefault(partial_hit, []).append(
                new_blocks.pop())
        for b in hits:
            self._allocator.share(b)
        blocks = hits + new_blocks
        self._slot_blocks[slot] = blocks
        self._write_from[slot] = n_hit
        self._slot_hits[slot] = n_hit
        if novel:
            self._pending_register[slot] = novel
        # Tail-fill the row with the slot's LAST block: an over-decode
        # position past the reservation (a final fused window's
        # overshoot, or the clamped >= max_len write) then routes into
        # the slot's own tail block — never block 0, which may be
        # another slot's live block.
        self._table[slot, :] = blocks[-1]
        self._table[slot, :n] = blocks
        self._sync_table()
        self._emit_block_gauges()
        if self.draft is not None:
            self.draft.reserve_slot(slot, prompt_len, max_new_tokens)
        return n_hit

    def _trim_reserves(self, block: int) -> None:
        """Keep ``_cow_reserve[block]`` at one replacement per EXTRA
        reference (``max(rc - 1, 0)``) — a sharer releasing, or a
        copy-on-write consuming a reference, returns the now-surplus
        reserve to the pool."""
        pool = self._cow_reserve.get(block)
        if pool is None:
            return
        want = max(self._allocator.refcount(block) - 1, 0)
        while len(pool) > want:
            self._allocator.free_one(pool.pop())
        if not pool:
            del self._cow_reserve[block]

    def _free_blocks(self, blocks) -> None:
        """Drop one reference per block; fully-released blocks retire
        their prefix-index registration, and shared survivors shed any
        now-surplus copy-on-write reserves."""
        for b in blocks:
            if self._allocator.free_one(b):
                key = self._block_keys.pop(b, None)
                if key is not None and self._prefix_index.get(key) == b:
                    del self._prefix_index[key]
            self._trim_reserves(b)

    def release_slot(self, slot: int) -> None:
        """Return ``slot``'s blocks to the free list (paged; dense is a
        no-op) — under prefix caching this drops ONE reference per
        block, so shared prefixes survive their sharers.  The pool rows
        keep their stale content — unreachable behind the next owner's
        length mask."""
        if self._allocator is not None and self._slot_blocks[slot]:
            self._free_blocks(self._slot_blocks[slot])
            self._slot_blocks[slot] = []
            self._table[slot, :] = 0
            self._pending_register.pop(slot, None)
            self._write_from[slot] = 0
            self._slot_hits[slot] = 0
            self._sync_table()
            self._emit_block_gauges()
        if self.speculative is not None:
            self._spec_catch[slot] = False
        if self.draft is not None:
            self.draft.release_slot(slot)

    def block_accounting(self) -> tuple:
        """``(free, used, total)`` pool blocks — the invariant every
        terminal state must restore is ``free + used == total`` (and
        ``free == total`` once no request is resident).  Dense engines
        report the vacuous ``(0, 0, 0)``."""
        if self._allocator is None:
            return (0, 0, 0)
        return (self._allocator.free_blocks, self._allocator.used_blocks,
                self.kv_num_blocks)

    def release_all_slots(self) -> None:
        """Return EVERY slot's blocks to the free list — the abandon
        path: a fleet replica declared dead releases its engine
        wholesale (a real crashed host frees its HBM with it; the
        in-process model must not let the bookkeeping say otherwise)."""
        for slot in range(self.num_slots):
            self.release_slot(slot)

    def _emit_block_gauges(self):
        from autodist_tpu import telemetry

        telemetry.gauge("serve/kv_blocks_free").set(
            self._allocator.free_blocks)
        telemetry.gauge("serve/kv_blocks_used").set(
            self._allocator.used_blocks)

    def _sync_table(self):
        """Mirror the host block table onto ``cache.block_table`` so
        the live cache pytree IS the complete decode state (a consumer
        serializing/inspecting ``engine.cache`` between dispatches —
        elastic checkpointing, debug dumps — must never see a stale
        mapping; the numpy ``_table`` stays the single source the
        device copy reflects)."""
        self.cache = kv_cache.PagedKVCache(
            k=self.cache.k, v=self.cache.v, lengths=self.cache.lengths,
            block_table=jnp.asarray(self._table))

    def _table_arg(self):
        if self.kv_layout == "paged":
            return self.cache.block_table
        return jnp.zeros((self.num_slots, 1), jnp.int32)

    # ------------------------------------------------------------------ #
    # copy-on-write + prefix registration (the sharing protocol)
    # ------------------------------------------------------------------ #
    def _copy_block(self, src: int, dst: int) -> None:
        """Device-copy pool block ``src`` into ``dst`` across every
        layer's k/v pools (the copy-on-write data move)."""
        if self._copy_block_jit is None:
            self._copy_block_jit = jax.jit(kv_cache.copy_pool_block,
                                           donate_argnums=(0, 1))
        k, v = self._copy_block_jit(self.cache.k, self.cache.v,
                                    jnp.int32(src), jnp.int32(dst))
        self.cache = kv_cache.PagedKVCache(
            k=k, v=v, lengths=self.cache.lengths,
            block_table=self.cache.block_table)

    def _cow_protect(self, active, lengths, n: int) -> None:
        """The copy-on-write gate: before a dispatch writes positions
        ``[L, L + n)`` of each active slot, any table entry in that
        span whose physical block is shared (refcount > 1) is copied
        into the slot's pre-funded reserve and the writer's row
        redirected — the sharer keeps the pristine block, and the ADT
        rule that no write goes through a shared table entry holds by
        construction.  Every span block (post-redirect) is noted as a
        ``write`` trace event so ``lint_block_trace`` can replay the
        protocol."""
        if self._allocator is None:
            return
        bl = self.kv_block_len
        max_blocks = self._table.shape[1]
        changed = False
        for slot in range(self.num_slots):
            if not active[slot]:
                continue
            L = int(lengths[slot])
            lo = L // bl
            hi = min((L + n - 1) // bl, max_blocks - 1)
            for j in range(lo, hi + 1):
                b = int(self._table[slot, j])
                if self._allocator.refcount(b) > 1:
                    pool = self._cow_reserve.get(b)
                    if not pool:
                        raise RuntimeError(
                            f"shared block {b} in slot {slot}'s write "
                            "span has no copy-on-write reserve — "
                            "admission must pre-fund every extra "
                            "reference on a writable block")
                    r = pool.pop()
                    if not pool:
                        del self._cow_reserve[b]
                    self._copy_block(b, r)
                    # Redirect EVERY row entry holding b (tail-fill
                    # duplicates included) — the slot must never write
                    # through the shared id again.
                    row = self._table[slot]
                    row[row == b] = r
                    self._slot_blocks[slot] = [
                        r if x == b else x
                        for x in self._slot_blocks[slot]]
                    self._allocator.note("cow", b, r)
                    self._allocator.free_one(b)
                    self._trim_reserves(b)
                    changed = True
                self._allocator.note("write", int(self._table[slot, j]))
        if changed:
            self._sync_table()
            self._emit_block_gauges()

    def _flush_registration(self, admit) -> None:
        """Publish the prefix keys of blocks the just-landed prefill
        actually wrote.  Registration waits until AFTER the dispatch so
        a same-batch request can never hit a block whose content is
        still pending; two same-batch requests with equal prefixes each
        keep private blocks and the first to flush wins the index."""
        if not self.prefix_caching:
            return
        for slot in range(self.num_slots):
            pend = self._pending_register.get(slot)
            if not pend or not admit[slot]:
                continue
            blocks = self._slot_blocks[slot]
            for j, key in pend.items():
                if j >= len(blocks) or key in self._prefix_index:
                    continue
                self._prefix_index[key] = blocks[j]
                self._block_keys[blocks[j]] = key
            self._pending_register.pop(slot, None)

    # ------------------------------------------------------------------ #
    # host-side driver API (the batcher's contract)
    # ------------------------------------------------------------------ #
    @property
    def max_prompt_tokens(self) -> int:
        """Longest admissible prompt: the prefill bucket single-shot;
        the whole context minus one generated token once chunked
        prefill makes long prompts first-class."""
        return (self.max_len - 1 if self.prefill_chunk is not None
                else self.prefill_len)
    def prefill(self, prompts, p_lens, admit, seeds=None):
        """Run one prefill over the slot batch; admitted slots adopt
        their prompt's cache/length and first generated token (greedy,
        or sampled at the engine's temperature under the slot's
        ``seeds`` entry).  Single-shot engines dispatch the one
        ``[B, prefill_len]`` program; chunked engines walk the prompt
        in ``prefill_chunk`` windows through ONE compiled program
        (``chunk_start`` is traced), skipping leading chunks every
        admitted slot already has cached via prefix hits.  Returns the
        per-slot current token ``[B]`` (numpy)."""
        prompts_np = np.asarray(prompts)
        p_lens_np = np.asarray(p_lens)
        admit_np = np.asarray(admit, bool)
        if seeds is not None:
            self._sample_seeds = np.where(
                admit_np, np.asarray(seeds, np.int32),
                self._sample_seeds).astype(np.int32)
        p_lens_j = jnp.asarray(p_lens_np, jnp.int32)
        admit_j = jnp.asarray(admit_np)
        rest = ((jnp.asarray(self._write_from),)
                if self.prefix_caching else ())
        if self.prefill_chunk is None:
            c = self.cache
            k, v, lengths, tok = self._prefill_jit(
                self.params, c.k, c.v, c.lengths, self._tok,
                self._table_arg(), jnp.asarray(self._sample_seeds),
                jnp.asarray(prompts_np, jnp.int32), p_lens_j, admit_j,
                *rest)
            self.cache = self._rebuild_cache(k, v, lengths)
            self._tok = tok
            self.last_prefill_chunks = 1
        else:
            self._chunked_prefill(prompts_np, p_lens_np, admit_np,
                                  p_lens_j, admit_j, rest)
        self._flush_registration(admit_np)
        if self.draft is not None:
            # The draft mirrors the target's resident prompts so its
            # proposals condition on the same context; its first-token
            # emission is discarded (decode_window aligns _tok to the
            # target's before every proposal run).
            self.draft.prefill(prompts_np, p_lens_np, admit_np, seeds)
        return np.asarray(jax.device_get(self._tok))

    def _chunked_prefill(self, prompts_np, p_lens_np, admit_np,
                         p_lens_j, admit_j, rest):
        C = self.prefill_chunk
        if not admit_np.any():
            self.last_prefill_chunks = 0
            return
        hi_len = int(p_lens_np[admit_np].max())
        n_chunks = kv_cache.blocks_for(hi_len, C)
        padded = np.zeros((self.num_slots, n_chunks * C), np.int64)
        width = min(prompts_np.shape[1], padded.shape[1])
        padded[:, :width] = prompts_np[:, :width]
        # Chunks fully covered by prefix hits for EVERY admitted slot
        # carry no novel writes and no emission — skip them (their
        # keys/values are already resident in the shared blocks the
        # later chunks attend through).  The chunk holding a slot's
        # final prompt token always runs: it produces the activation
        # the first generated token samples from.
        first = 0
        if self.prefix_caching:
            firsts = [min(int(self._write_from[i]) * self.kv_block_len,
                          int(p_lens_np[i]) - 1)
                      for i in range(self.num_slots) if admit_np[i]]
            first = min(firsts) // C
        dispatched = 0
        for ci in range(first, n_chunks):
            cs = ci * C
            c = self.cache
            k, v, lengths, tok = self._prefill_jit(
                self.params, c.k, c.v, c.lengths, self._tok,
                self._table_arg(), jnp.asarray(self._sample_seeds),
                jnp.asarray(padded[:, cs:cs + C], jnp.int32),
                jnp.int32(cs), p_lens_j, admit_j, *rest)
            self.cache = self._rebuild_cache(k, v, lengths)
            self._tok = tok
            dispatched += 1
        self.last_prefill_chunks = dispatched

    def decode(self, active):
        """One fused ``decode_steps``-token dispatch; inactive slots
        hold their state.  Returns the emitted tokens ``[K, B]``
        (numpy; inactive columns repeat the held token)."""
        active_np = np.asarray(active, bool)
        if self.kv_layout == "paged":
            self._cow_protect(active_np, self.lengths, self.decode_steps)
        c = self.cache
        k, v, lengths, tok, toks = self._decode_jit(
            self.params, c.k, c.v, c.lengths, self._tok,
            self._table_arg(), jnp.asarray(self._sample_seeds),
            jnp.asarray(active_np))
        self.cache = self._rebuild_cache(k, v, lengths)
        self._tok = tok
        return np.asarray(jax.device_get(toks))

    def decode_one(self, active):
        """A single-token dispatch through a lazily-built K=1 program —
        the speculative draft's catch-up path (feeding the one proposal
        a fully-accepted window verified but the draft never wrote)."""
        if self._decode1_jit is None:
            self._decode1_jit = self._build_decode(steps=1)
        active_np = np.asarray(active, bool)
        if self.kv_layout == "paged":
            self._cow_protect(active_np, self.lengths, 1)
        c = self.cache
        k, v, lengths, tok, toks = self._decode1_jit(
            self.params, c.k, c.v, c.lengths, self._tok,
            self._table_arg(), jnp.asarray(self._sample_seeds),
            jnp.asarray(active_np))
        self.cache = self._rebuild_cache(k, v, lengths)
        self._tok = tok
        return np.asarray(jax.device_get(toks))

    def decode_window(self, active) -> DecodeWindow:
        """The batcher's decode unit.  Vanilla engines emit a fixed
        ``decode_steps`` tokens per active slot.  Speculative engines
        run draft-propose → target-verify → host accept/reject: the
        draft proposes ``k`` tokens autoregressively, ONE target
        dispatch scores the ``k + 1`` window, and each slot keeps the
        longest prefix the target agrees with plus the target's own
        next token — token-for-token (greedy) and draw-for-draw
        (sampled) what vanilla decode would have emitted, because both
        sides sample through the same position-keyed draws.  Rejected
        tokens roll back by resetting lengths through the block table's
        masked reads — no data movement."""
        active_np = np.asarray(active, bool)
        B = self.num_slots
        if self.speculative is None:
            toks = self.decode(active_np)
            counts = np.where(active_np, self.decode_steps,
                              0).astype(np.int32)
            z = np.zeros((B,), np.int32)
            return DecodeWindow(tokens=toks, counts=counts,
                                spec_proposed=z, spec_accepted=z.copy())
        ks = self.speculative
        # 1. Catch-up: a slot whose last window accepted every proposal
        # verified token d_k but the draft never wrote it — feed it
        # through the K=1 program so the draft's cache matches the
        # target's length before proposing again.
        need = self._spec_catch & active_np
        if need.any():
            draft_tok = np.asarray(jax.device_get(self.draft._tok))
            self.draft._tok = jnp.asarray(
                np.where(need, self._spec_catch_tok,
                         draft_tok).astype(np.int32))
            self.draft.decode_one(need)
            self._spec_catch &= ~need
        # 2. Align: the draft continues from the target's current token.
        tgt_tok = np.asarray(jax.device_get(self._tok))
        self.draft._tok = jnp.asarray(tgt_tok.astype(np.int32))
        # 3. Propose: the draft's fused decode IS the k-token proposer.
        proposals = self.draft.decode(active_np)           # [k, B]
        # 4. Verify: one target dispatch over [tok, d_1..d_k].
        lengths_np = self.lengths
        if self.kv_layout == "paged":
            self._cow_protect(active_np, lengths_np, ks + 1)
        tokens_in = np.zeros((B, ks + 1), np.int64)
        tokens_in[:, 0] = tgt_tok
        tokens_in[:, 1:] = proposals.T
        c = self.cache
        k, v, lengths, tok, choices = self._spec_verify_jit(
            self.params, c.k, c.v, c.lengths, self._tok,
            self._table_arg(), jnp.asarray(self._sample_seeds),
            jnp.asarray(tokens_in, jnp.int32), jnp.asarray(active_np))
        self.cache = self._rebuild_cache(k, v, lengths)
        choices_np = np.asarray(jax.device_get(choices))   # [B, k+1]
        # 5. Accept/reject + rollback (host-side lengths are the only
        # state that moves — stale verified rows hide behind them).
        tok_np = tgt_tok.copy()
        new_len = lengths_np.copy()
        draft_len = np.asarray(
            jax.device_get(self.draft.cache.lengths)).copy()
        tokens = np.zeros((ks + 1, B), np.int32)
        counts = np.zeros((B,), np.int32)
        accepted = np.zeros((B,), np.int32)
        proposed = np.zeros((B,), np.int32)
        for i in range(B):
            if not active_np[i]:
                continue
            j = 0
            while j < ks and choices_np[i, j] == proposals[j, i]:
                j += 1
            m = j + 1
            tokens[:m, i] = choices_np[i, :m]
            counts[i] = m
            accepted[i] = j
            proposed[i] = ks
            tok_np[i] = choices_np[i, j]
            new_len[i] = lengths_np[i] + m
            draft_len[i] = lengths_np[i] + min(m, ks)
            if j == ks:
                self._spec_catch[i] = True
                self._spec_catch_tok[i] = proposals[ks - 1, i]
        self._tok = jnp.asarray(tok_np.astype(np.int32))
        self.cache = dataclasses.replace(
            self.cache, lengths=jnp.asarray(new_len, jnp.int32))
        self.draft.cache = dataclasses.replace(
            self.draft.cache, lengths=jnp.asarray(draft_len, jnp.int32))
        return DecodeWindow(tokens=tokens, counts=counts,
                            spec_proposed=proposed, spec_accepted=accepted)

    def _rebuild_cache(self, k, v, lengths):
        if self.kv_layout == "paged":
            # block_table is kept current by _sync_table at every
            # reserve/release — the programs consumed this same array.
            return kv_cache.PagedKVCache(
                k=k, v=v, lengths=lengths,
                block_table=self.cache.block_table)
        return kv_cache.KVCache(k=k, v=v, lengths=lengths)

    @property
    def lengths(self):
        return np.asarray(jax.device_get(self.cache.lengths))

    # ------------------------------------------------------------------ #
    # HLO probe hooks (tools/hlo_probe.py --probe decode)
    # ------------------------------------------------------------------ #
    def compiled_decode_text(self) -> str:
        """Optimized HLO of the fused decode program."""
        c = self.cache
        active = jnp.ones((self.num_slots,), bool)
        return self._decode_jit.lower(
            self.params, c.k, c.v, c.lengths, self._tok,
            self._table_arg(), jnp.asarray(self._sample_seeds),
            active).compile().as_text()

    def compiled_prefill_text(self) -> str:
        """Optimized HLO of the prefill program."""
        c = self.cache
        prompts = jnp.zeros((self.num_slots, self.prefill_len), jnp.int32)
        p_lens = jnp.ones((self.num_slots,), jnp.int32)
        admit = jnp.ones((self.num_slots,), bool)
        return self._prefill_jit.lower(
            self.params, c.k, c.v, c.lengths, self._tok,
            self._table_arg(), jnp.asarray(self._sample_seeds), prompts,
            p_lens, admit).compile().as_text()
