"""Fault-tolerant multi-replica serving: the fleet behind the router.

A :class:`ServingFleet` runs N *replicas* — each a full
``ServingEngine`` + ``ContinuousBatcher`` group (one tp group; the
engine's ``tensor_parallel`` spans its own device set) — behind one
:class:`~autodist_tpu.serving.router.Router`.  The fleet owns the parts
a single engine cannot answer:

* **Lifecycle** — every replica walks ``admitting → draining → dead →
  replaced``: an admitting replica takes new dispatches; a draining one
  finishes its in-flight requests while the router re-homes its queue;
  a dead one (crash detected, or hang declared by the health check) is
  abandoned — its engine's paged blocks released wholesale, exactly as
  a crashed host's HBM dies with it — and *replaced* from the engine
  factory under a ``SupervisionConfig``-style replacement budget with
  backoff; budget exhausted escalates to a permanently shrunk fleet
  (coded, recorded — never silent).
* **Health** — per-replica heartbeats: a replica beats once per healthy
  scheduler round, and the fleet's health check runs the SAME freshness
  semantics as the training plane's
  :class:`~autodist_tpu.runtime.cluster.HeartbeatMonitor` (its
  ``poll_once`` is literally reused over an in-process beat client), so
  a hung replica is *detected* after ``heartbeat_timeout_s``, not
  never.  On real hosts the replica group runs behind
  ``runtime/cluster.py`` — the Coordinator launches one engine-loop
  process per replica host set and the same monitor polls the
  coordination-service counters; the in-process backing used here and
  in tests keeps every semantic (states, beats, detection windows,
  records) identical.
* **Fault injection** — ``runtime/faults.py``'s serving-plane kinds
  (``replica_crash``/``replica_hang``/``replica_slow``) land on
  :meth:`inject` via the ``FaultInjector(fleet=...)`` binding; every
  recovery path the router exercises is proven by an injected fault
  (``tools/chaos_run.py --matrix --plane serving``).

Every replica death/replacement emits a ``kind="fault"`` telemetry
record (``tools/telemetry_report.py --check`` pairs a router failover
with it), and fleet configs are linted by
:func:`autodist_tpu.analysis.lint_fleet` (ADT085+) before launch.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

from autodist_tpu import telemetry
from autodist_tpu.runtime.retry import RetryPolicy
from autodist_tpu.serving.batcher import ContinuousBatcher
from autodist_tpu.utils import logging

REPLICA_STATES = ("admitting", "draining", "dead", "replaced")


class ReplicaCrashedError(RuntimeError):
    """A replica's engine died mid-dispatch (the in-process rendering
    of a crashed replica host).  The fleet catches it, declares the
    replica dead, and the router fails its in-flight requests over."""

    code = "serve/replica_crashed"


class FleetDrainedError(RuntimeError):
    """No live replica remains and the replacement budget is spent —
    open requests are shed (coded) for the caller to resubmit
    elsewhere; nothing hangs."""

    code = "serve/fleet_drained"


@dataclasses.dataclass
class FleetConfig:
    """The fleet's robustness knobs (the serving-plane sibling of
    :class:`~autodist_tpu.runtime.cluster.SupervisionConfig`).  Lint
    with :func:`autodist_tpu.analysis.lint_fleet` before launch — the
    ADT085+ rules catch the configs that turn the recovery machinery
    into silent damage.

    * ``hedge_timeout_s`` — straggler deadline: a request whose primary
      dispatch is still open past it gets a duplicate dispatch on
      another replica (first completion wins, the loser is cancelled
      and its blocks freed).  ``None`` calibrates the deadline from the
      completed-request latency distribution instead:
      ``hedge_percentile`` of the last completions × ``hedge_factor``,
      armed once ``hedge_min_samples`` completions exist.  Set
      ``hedge_percentile=None`` too to disable hedging entirely.
    * ``request_deadline_s`` — default per-request deadline stamped at
      ``Router.submit`` (a request carries its remaining deadline
      through every failover re-dispatch).
    * ``max_replacements`` / ``replacement_backoff`` — the restart
      budget per replica name: a dead replica is rebuilt from the
      engine factory at most this many times, with the policy's delay
      between attempts; beyond it the fleet continues permanently
      shrunk (``escalated`` record).
    * ``heartbeat_*`` — the health-check windows (same semantics as
      ``SupervisionConfig``: interval must stay well under timeout —
      ADT081 — and a fresh replica gets the startup grace while its
      programs compile).
    """

    replicas: int = 2
    hedge_timeout_s: Optional[float] = None
    hedge_percentile: Optional[float] = 99.0
    hedge_factor: float = 3.0
    hedge_min_samples: int = 8
    request_deadline_s: Optional[float] = None
    max_replacements: int = 1
    replacement_backoff: RetryPolicy = dataclasses.field(
        default_factory=lambda: RetryPolicy(
            max_attempts=4, base_delay_s=0.0, cap_delay_s=0.0))
    heartbeat_interval_s: float = 0.5
    heartbeat_timeout_s: float = 30.0
    heartbeat_startup_grace_s: float = 120.0

    def to_dict(self) -> dict:
        return {
            "replicas": self.replicas,
            "hedge_timeout_s": self.hedge_timeout_s,
            "hedge_percentile": self.hedge_percentile,
            "hedge_factor": self.hedge_factor,
            "hedge_min_samples": self.hedge_min_samples,
            "request_deadline_s": self.request_deadline_s,
            "max_replacements": self.max_replacements,
            "heartbeat_interval_s": self.heartbeat_interval_s,
            "heartbeat_timeout_s": self.heartbeat_timeout_s,
        }


class Replica:
    """One serving replica: engine + batcher + lifecycle + health.

    Duck-typed like a :class:`~autodist_tpu.runtime.cluster
    .WorkerHandle` (``name``/``running``/``superseded``/``started_s``)
    so the training plane's ``HeartbeatMonitor.poll_once`` monitors it
    unchanged."""

    def __init__(self, name: str, engine, *, incarnation: int = 0,
                 warm: bool = True):
        self.name = name
        self.incarnation = incarnation
        self.engine = engine
        self.batcher = ContinuousBatcher(engine)
        self.state = "admitting"
        self.started_s = time.monotonic()
        self.superseded = False
        self.declared_fault: Optional[str] = None
        self.beats = 0
        self._fault: Optional[str] = None
        self._slow_until = 0.0
        self.replace_on_retire = False   # set by ServingFleet.drain
        if warm:
            self._warm_programs()

    def _warm_programs(self):
        """Compile the prefill/decode programs with all-slots-masked
        dispatches (state untouched) so the first real request never
        stalls a scheduler round across the heartbeat window — a
        replica mid-compile must look starting-up (grace), not hung."""
        import numpy as np

        B, S = self.engine.num_slots, self.engine.prefill_len
        self.engine.prefill(np.zeros((B, S), np.int32),
                            np.ones((B,), np.int32),
                            np.zeros((B,), bool))
        self.engine.decode(np.zeros((B,), bool))

    # ------------------------------------------------------------------ #
    @property
    def running(self) -> bool:
        return self.state in ("admitting", "draining")

    @property
    def load(self) -> int:
        """Queued + in-flight requests — the dispatch signal."""
        return self.batcher.queue_depth + self.batcher.active_slots

    def step(self):
        """One scheduler round (admit/decode/evict) + one heartbeat.
        Injected faults act here: a crashed replica raises, a hung one
        neither progresses nor beats, a slow one beats (healthy!) but
        stalls its rounds until the slow window passes."""
        if not self.running:
            return
        if self._fault == "hang":
            return
        if self._fault == "crash":
            raise ReplicaCrashedError(
                f"[{ReplicaCrashedError.code}] replica {self.name} "
                "crashed")
        if self._fault == "slow":
            if time.monotonic() < self._slow_until:
                self.beats += 1
                return
            self._fault = None
            # The straggler came back: the terminal record the report's
            # injected↔outcome pairing gate expects (slow is the one
            # serving fault with no death — hedging absorbed it).
            telemetry.record_event("fault", fault="replica_slow",
                                   target=self.name, phase="recovered",
                                   action="resumed")
        self.batcher.step()
        self.beats += 1


class _FleetBeatClient:
    """The in-process stand-in for the coordination-service client the
    HeartbeatMonitor polls: ``hb/<replica>`` counters read straight off
    the live replicas' beat counts."""

    def __init__(self, fleet: "ServingFleet"):
        self._fleet = fleet

    def counter_add(self, key: str, delta: int = 0) -> int:
        name = key[len("hb/"):] if key.startswith("hb/") else key
        replica = self._fleet._by_name.get(name)
        return replica.beats if replica is not None else 0


class _FleetCoordShim:
    """Duck-types the two Coordinator touchpoints
    ``HeartbeatMonitor.poll_once`` uses (``workers`` and
    ``declare_dead``) onto the fleet's replicas."""

    def __init__(self, fleet: "ServingFleet"):
        self._fleet = fleet

    @property
    def workers(self):
        return [r for r in self._fleet.replicas if r.running]

    def declare_dead(self, replica, reason: str):
        self._fleet.declare_dead(replica, reason, fault="replica_hang")


class ServingFleet:
    """N replica serving groups + lifecycle + health + replacement.

    ``engine_factory`` builds one fresh ``ServingEngine`` per call —
    the params source replacements are rebuilt from (an exported
    artifact, a checkpoint, a params tree in memory).  Drive the fleet
    through a :class:`~autodist_tpu.serving.router.Router`; the fleet
    itself never sees requests."""

    def __init__(self, engine_factory: Callable[[], object], *,
                 replicas: Optional[int] = None,
                 config: Optional[FleetConfig] = None,
                 warm: bool = True):
        self.config = config or FleetConfig()
        if replicas is not None:
            self.config = dataclasses.replace(self.config,
                                              replicas=int(replicas))
        if self.config.replicas < 1:
            raise ValueError("a fleet needs at least one replica")
        self.engine_factory = engine_factory
        self._warm = warm
        self.replicas: list[Replica] = []
        self._by_name: dict[str, Replica] = {}
        self._replacements: dict[str, int] = {}
        self.escalated = False
        for i in range(self.config.replicas):
            self._spawn(f"replica-{i}")
        # The training plane's monitor, verbatim: poll_once over the
        # in-process beat client gives the serving plane the exact
        # detection semantics chaos already proved for workers.
        from autodist_tpu.runtime.cluster import HeartbeatMonitor

        self._beat_client = _FleetBeatClient(self)
        self._last_poll_s: Optional[float] = None
        self._monitor = HeartbeatMonitor(
            _FleetCoordShim(self), lambda: self._beat_client,
            interval_s=self.config.heartbeat_interval_s,
            timeout_s=self.config.heartbeat_timeout_s,
            startup_grace_s=self.config.heartbeat_startup_grace_s)

    # ------------------------------------------------------------------ #
    def _spawn(self, name: str, incarnation: int = 0) -> Replica:
        replica = Replica(name, self.engine_factory(),
                          incarnation=incarnation, warm=self._warm)
        self.replicas.append(replica)
        self._by_name[name] = replica
        if getattr(self, "_monitor", None) is not None:
            # A spawn blocks the whole scheduler (engine build +
            # program compile): forget every freshness window so the
            # stall cannot read as the OTHER replicas hanging — the
            # restart-grace idea, fleet-wide.
            self._monitor._last.clear()
        self._emit_live_gauge()
        return replica

    def _emit_live_gauge(self):
        telemetry.gauge("fleet/replicas_live").set(
            sum(r.running for r in self.replicas))

    @property
    def live(self) -> list:
        return [r for r in self.replicas if r.running]

    @property
    def admitting(self) -> list:
        """Routing targets: live replicas accepting new dispatches."""
        return [r for r in self.replicas if r.state == "admitting"]

    def has_replica(self, name: str) -> bool:
        """FaultInjector ownership predicate (``fleet=`` binding)."""
        replica = self._by_name.get(name)
        return replica is not None and replica.running

    def describe(self) -> dict:
        """The fleet-shape dict :func:`autodist_tpu.analysis.lint_fleet`
        checks (config knobs + the engine-derived shape keys).  A
        constructed fleet always has a factory, so
        ``has_engine_source`` is True here — ADT087 exists for the
        hand-written/serialized fleet configs that reach ``lint_fleet``
        without one."""
        d = self.config.to_dict()
        probe = self.replicas[0].engine
        d["tensor_parallel"] = int(getattr(probe, "tensor_parallel", 1))
        d["kv_layout"] = getattr(probe, "kv_layout", "dense")
        d["has_engine_source"] = self.engine_factory is not None
        return d

    def lint(self, resource_spec=None):
        from autodist_tpu.analysis import lint_fleet

        return lint_fleet(self.describe(), resource_spec=resource_spec)

    # ------------------------------------------------------------------ #
    # health + faults
    # ------------------------------------------------------------------ #
    def poll_health(self):
        """One synchronous freshness sweep (the router calls this every
        scheduler round) — ``HeartbeatMonitor.poll_once`` verbatim, so
        hang detection is the training plane's code path.

        Beats only advance while the scheduler steps, so a caller-side
        idle gap (no requests for a while, a blocking compile) would
        read as EVERY replica hanging at the next poll: when the time
        since the previous poll itself exceeds the timeout, the
        freshness windows are meaningless and are reset — a hang is a
        replica that stalls while the scheduler is actively polling,
        never a scheduler that went quiet."""
        now = time.monotonic()
        if self._last_poll_s is not None \
                and now - self._last_poll_s > \
                self.config.heartbeat_timeout_s:
            self._monitor._last.clear()
        self._last_poll_s = now
        client = self._monitor.poll_once(self._beat_client)
        if client is None:   # cannot happen in-process; keep the contract
            self._beat_client = _FleetBeatClient(self)

    def inject(self, name: str, kind: str, duration_s: float = 0.5):
        """The ``FaultInjector`` landing pad for the serving-plane
        fault kinds: ``crash`` (next dispatch raises), ``hang`` (no
        progress, no beats — only the health check ends it), ``slow``
        (beats but stalls for ``duration_s`` — a straggler, hedging's
        territory, and explicitly NOT the health check's)."""
        replica = self._by_name.get(name)
        if replica is None or not replica.running:
            raise ValueError(f"no live replica {name!r} to inject into")
        if kind == "slow":
            replica._slow_until = time.monotonic() + duration_s
        elif kind not in ("crash", "hang"):
            raise ValueError(f"unknown replica fault {kind!r}")
        replica._fault = kind

    def declare_dead(self, replica: Replica, reason: str,
                     fault: str = "replica_crash"):
        """Mark a replica dead (crash caught, or hang declared by the
        health check): emit the detection record the report pairs
        failovers with, abandon the engine (paged blocks released — a
        dead host's HBM dies with it), and let the router re-home its
        requests."""
        if not replica.running:
            return
        logging.error("fleet: declaring %s dead: %s", replica.name, reason)
        replica.declared_fault = fault
        replica.state = "dead"
        replica.engine.release_all_slots()
        telemetry.counter("fleet/replica_deaths").inc()
        telemetry.record_event("fault", fault=fault, target=replica.name,
                               phase="detected", reason=reason)
        self._emit_live_gauge()

    def maybe_replace(self, replica: Replica) -> Optional[Replica]:
        """Rebuild a dead replica from the engine factory under the
        replacement budget; beyond it, escalate to the permanently
        shrunk fleet (recorded, coded — never silent)."""
        if replica.state != "dead" or replica.superseded:
            return None
        fault = replica.declared_fault or "replica_crash"
        n = self._replacements.get(replica.name, 0)
        replica.superseded = True
        if n >= self.config.max_replacements:
            self.escalated = True
            telemetry.counter("fleet/escalations").inc()
            telemetry.record_event(
                "fault", fault=fault, target=replica.name,
                phase="escalated", action="shrink_fleet",
                survivors=[r.name for r in self.live])
            logging.error(
                "fleet: %s dead beyond its replacement budget (%d); "
                "continuing with %d replica(s)", replica.name, n,
                len(self.live))
            self._emit_live_gauge()
            return None
        delay = self.config.replacement_backoff.delay_s(n + 1)
        if delay > 0:
            time.sleep(delay)
        self._replacements[replica.name] = n + 1
        # "replaced" only once the successor actually exists — an
        # escalated (never-rebuilt) replica stays "dead", so state
        # printouts report the shrunk capacity honestly.
        replica.state = "replaced"
        fresh = self._spawn(replica.name, incarnation=n + 1)
        telemetry.counter("fleet/replacements").inc()
        telemetry.record_event(
            "fault", fault=fault, target=replica.name, phase="recovered",
            action="replace", incarnation=n + 1)
        logging.info("fleet: replaced %s (incarnation %d)", replica.name,
                     n + 1)
        return fresh

    # ------------------------------------------------------------------ #
    def grow(self, name: Optional[str] = None) -> Replica:
        """Spawn one ADDITIONAL admitting replica — the autoscaler's
        scale-out edge.  Not a replacement: no charge against the
        failure budget, no fault record (the scale event itself is the
        autoscaler's ``kind="scale"`` record).  The router's next
        ``_pick`` sees the newcomer through ``fleet.admitting``."""
        if name is None:
            i = len(self.replicas)
            while f"replica-{i}" in self._by_name:
                i += 1
            name = f"replica-{i}"
        elif name in self._by_name:
            raise ValueError(f"replica {name!r} already exists")
        return self._spawn(name)

    def drain(self, name: str, replace: bool = False):
        """Start draining a replica (rolling restart / re-election /
        preemption notice): it stops admitting, finishes its in-flight
        requests, and the router re-homes its queued ones (each move a
        ``reason="drain"`` dispatch record).  ``replace=True`` rebuilds
        a fresh replica from the engine factory once the drain
        completes — the rolling-restart shape; the default retires the
        slot for good (an intentional shrink)."""
        replica = self._by_name.get(name)
        if replica is None or replica.state != "admitting":
            raise ValueError(f"no admitting replica {name!r} to drain")
        replica.state = "draining"
        replica.replace_on_retire = bool(replace)
        telemetry.counter("fleet/drains").inc()
        self._emit_live_gauge()

    def retire_drained(self):
        """Finish the drain lifecycle: a draining replica with no work
        left becomes dead (clean teardown — its blocks were freed by
        its own evictions; ``release_all_slots`` is a no-op backstop),
        and a ``drain(replace=True)`` rolling restart spawns its
        successor — planned maintenance, so no fault record and no
        charge against the failure-replacement budget."""
        for replica in self.replicas:
            if replica.state == "draining" and replica.load == 0:
                replica.state = "dead"
                replica.superseded = True   # a drain is not a failure
                replica.engine.release_all_slots()
                if replica.replace_on_retire:
                    self._spawn(replica.name,
                                incarnation=replica.incarnation + 1)
                    replica.state = "replaced"
                    telemetry.counter("fleet/replacements").inc()
                    logging.info("fleet: rolled %s (incarnation %d)",
                                 replica.name, replica.incarnation + 1)
                self._emit_live_gauge()

    def block_accounting(self) -> dict:
        """Per-live-replica ``(free, used, total)`` pool accounting —
        the zero-leak invariant the chaos matrix asserts."""
        return {r.name: r.engine.block_accounting() for r in self.live}
