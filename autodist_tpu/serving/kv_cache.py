"""TP-sharded KV cache for batched decode: dense and block-paged.

**Dense** (the original layout): one pair of arrays holds every layer's
keys and values, laid out

    ``[layer, batch_slot, heads/tp, max_len, head_dim]``

so the whole cache shards over the ``model`` mesh axis with a single
``P(None, None, 'model', None, None)`` spec — the same head split the
Megatron column-parallel qkv projection produces, so a decode step's
freshly projected k/v shards land in their cache slots with zero
resharding (the GSPMD property: one sharding-annotated layout serves
both the training program's attention and the decode program's cache,
arxiv 2105.04663).

**Paged** (the vLLM PagedAttention design adapted to this layout,
PAPERS.md: block tables + non-contiguous KV): the per-slot ``max_len``
lane is replaced by a pool of fixed-size blocks

    ``[layer, num_blocks, heads/tp, block_len, head_dim]``

with the SAME model-axis sharding spec (axis 2 is still the head
split), a per-slot **block table** ``[num_slots, max_blocks]`` mapping
logical block ``j`` of a slot's sequence to a pool block, and a
host-side free-list :class:`BlockAllocator`.  A logical position ``p``
of slot ``s`` lives at pool coordinates
``(block_table[s, p // block_len], p % block_len)``.  Short requests
stop squatting on ``max_len`` bytes they never touch: the batcher
admits against *free blocks*, not slots, so equal pool bytes carry
strictly more concurrent short requests than dense reservation.

Writes are in-place ``lax.dynamic_update_slice`` updates in both
layouts (the paged write's start index merely routes through the
table); under ``jax.jit`` with the cache donated, XLA aliases the
update into the live buffer — ``tools/hlo_probe.py --probe decode``
and the ADT111/ADT115 program-lint rules assert the compiled step
carries the dynamic-update-slices, no per-step full-cache copy, and
(paged) no dense ``[slots, max_len]``-shaped cache buffer at all.
Slots are recycled by the batcher: a newly admitted request's prefill
overwrites positions ``[0, prompt_len)`` and decode overwrites forward
from there, and reads are always masked to ``pos < length``, so stale
tail entries from the previous occupant — or, paged, from a freed
block's previous owner — are never observable.
"""
from __future__ import annotations

import collections
import dataclasses
import hashlib
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from autodist_tpu import const


def cache_spec() -> P:
    """Partition spec of either cache array: heads over the model axis."""
    return P(None, None, const.MODEL_AXIS, None, None)


@dataclasses.dataclass
class KVCache:
    """The decode-time state: cache arrays + per-slot occupancy.

    ``k``/``v``: ``[L, B, heads_local, T, head_dim]`` (``heads_local =
    num_heads/tp`` inside ``shard_map``; the full head count on the host
    view).  ``lengths``: ``[B]`` int32 — tokens currently materialized
    per slot (the next write position).  Registered as a pytree so the
    whole cache rides jit/scan carries and donation in one piece.
    """

    k: Any
    v: Any
    lengths: Any

    def tree_flatten(self):
        return (self.k, self.v, self.lengths), None

    @classmethod
    def tree_unflatten(cls, _, leaves):
        return cls(*leaves)


jax.tree_util.register_pytree_node(
    KVCache, KVCache.tree_flatten, KVCache.tree_unflatten)


def init_cache(num_layers: int, num_slots: int, num_heads: int,
               head_dim: int, max_len: int, dtype=jnp.float32) -> KVCache:
    """All-zero cache with every slot empty."""
    shape = (num_layers, num_slots, num_heads, max_len, head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   lengths=jnp.zeros((num_slots,), jnp.int32))


def write_token(cache_arr, layer: int, kv, positions):
    """Write one decode step's projections into ``cache_arr`` in place.

    ``kv``: ``[B, 1, heads, head_dim]`` (the qkv projection's layout for
    a single-token step); ``positions``: ``[B]`` int32 — slot ``i``'s
    row lands at ``[layer, i, :, positions[i], :]``.  Per-slot scalar
    positions keep the update a true ``dynamic_update_slice`` (the
    in-place form XLA aliases) instead of a scatter; the slot loop is
    unrolled — ``B`` is the static slot count, small by construction.
    """
    B = kv.shape[0]
    for slot in range(B):
        upd = kv[slot, 0][None, None, :, None, :].astype(cache_arr.dtype)
        cache_arr = lax.dynamic_update_slice(
            cache_arr, upd, (layer, slot, 0, positions[slot], 0))
    return cache_arr


def write_prompt(cache_arr, layer: int, kv, admit):
    """Write a prefill's whole-prompt projections for admitted slots.

    ``kv``: ``[B, S, heads, head_dim]``; slot ``i``'s rows land at
    ``[layer, i, :, 0:S, :]`` when ``admit[i]``, and its existing cache
    rows are kept bit-for-bit otherwise — the read-modify-write touches
    only the ``[heads, S, head_dim]`` window, never the full cache (the
    masking that lets one compiled prefill admit any subset of slots
    while the others keep decoding state).
    """
    B, S = kv.shape[0], kv.shape[1]
    for slot in range(B):
        new = jnp.transpose(kv[slot], (1, 0, 2))[None, None] \
            .astype(cache_arr.dtype)                 # [1,1,heads,S,dh]
        cur = lax.dynamic_slice(cache_arr, (layer, slot, 0, 0, 0),
                                new.shape)
        sel = jnp.where(admit[slot], new, cur)
        cache_arr = lax.dynamic_update_slice(cache_arr, sel,
                                             (layer, slot, 0, 0, 0))
    return cache_arr


def cached_attention(q, k_layer, v_layer, lengths, *, dtype=jnp.float32):
    """One decode step's attention over a layer's cache slice.

    ``q``: ``[B, 1, heads, head_dim]`` (the step's query — the token
    just written at position ``lengths``); ``k_layer``/``v_layer``:
    ``[B, heads, T, head_dim]``.  Key positions ``> lengths`` are masked
    (the just-written token attends to itself and everything before it),
    so stale or zero entries past a slot's occupancy are unreachable.
    Softmax in fp32 with the trained model's scaling — matching
    :func:`~autodist_tpu.models.transformer.dot_product_attention`
    numerics so incremental decode agrees with full-sequence recompute.
    Scores live at ``[B, heads, 1, T]`` — never the ``[T, T]`` square
    the prefill's causal pass needs (the HLO decode probe asserts no
    such buffer exists).
    """
    depth = q.shape[-1]
    q2 = jnp.transpose(q, (0, 2, 1, 3))              # [B, heads, 1, dh]
    # dot_general contracting head_dim directly against the cache's
    # native [.., T, head_dim] layout — an einsum spelling makes XLA
    # transpose (= copy) the whole cache lane every step.
    scores = lax.dot_general(
        q2, k_layer.astype(q.dtype),
        (((3,), (3,)), ((0, 1), (0, 1)))) / np.sqrt(depth)
    scores = scores.astype(jnp.float32)              # [B, heads, 1, T]
    T = k_layer.shape[2]
    ok = jnp.arange(T)[None, None, None, :] <= \
        lengths[:, None, None, None]
    scores = jnp.where(ok, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    out = lax.dot_general(
        probs, v_layer.astype(dtype),
        (((3,), (2,)), ((0, 1), (0, 1))))            # [B, heads, 1, dh]
    return jnp.transpose(out, (0, 2, 1, 3))          # [B, 1, heads, dh]


# --------------------------------------------------------------------------- #
# Block-paged cache
# --------------------------------------------------------------------------- #
class PoolExhaustedError(RuntimeError):
    """The block pool cannot satisfy an allocation: the request must
    wait in the admission queue (or be shed) instead of silently
    corrupting another slot's blocks.  Coded, like the batcher's
    :class:`~autodist_tpu.serving.batcher.OverloadedError`."""

    code = "serve/kv_pool_exhausted"


class BlockAllocator:
    """Host-side refcounted free-list over the pool's ``num_blocks`` ids.

    Pure accounting — no device traffic.  Allocation pops from one flat
    free list, so there is no fragmentation by construction: any
    ``n <= free_blocks`` allocation succeeds, and
    ``free_blocks + used_blocks == num_blocks`` is an invariant the unit
    tests pin (``used_blocks`` counts *physical* blocks with refcount
    >= 1, not table references).  Prefix caching shares a physical block
    between slots by bumping its refcount (:meth:`share`); :meth:`free`
    decrements, and a block returns to the free list only when the last
    reference drops — so ``free + used == total`` survives sharing with
    no special cases.  Double-frees and foreign ids are rejected loudly
    (a bookkeeping bug must not silently double-map a block to two
    slots).

    Every allocate/share/free transition is appended to :attr:`events`
    — the block event trace the ADT116/ADT117 shared-block rules replay
    (``lint_block_trace``).  The engine appends ``write``/``cow``
    events through :meth:`note` for the writes it dispatches, so the
    trace carries enough to prove no shared block is ever written
    through a table entry without a copy first."""

    #: bounded so a long-lived serving process cannot grow the trace
    #: without bound; the lints run over fresh, short traces.
    TRACE_LIMIT = 1 << 18

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError("num_blocks must be >= 1")
        self.num_blocks = int(num_blocks)
        # LIFO free list: deterministic reuse order (a freed block is
        # handed to the next admission — the recycling edge the paged
        # parity goldens pin).
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._rc: dict = {}
        self.events = collections.deque(maxlen=self.TRACE_LIMIT)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return len(self._rc)

    def refcount(self, block: int) -> int:
        return self._rc.get(block, 0)

    def note(self, *event) -> None:
        """Append an engine-observed event (``write``/``cow``) to the
        trace.  The allocator records its own alloc/share/free."""
        self.events.append(tuple(event))

    def alloc(self, n: int) -> list:
        if n < 0:
            raise ValueError("alloc count must be >= 0")
        if n > len(self._free):
            raise PoolExhaustedError(
                f"[{PoolExhaustedError.code}] {n} block(s) requested, "
                f"{len(self._free)} free of {self.num_blocks} — the "
                "admission predicate must gate on free blocks")
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._rc[b] = 1
            self.events.append(("alloc", b))
        return blocks

    def share(self, block: int) -> int:
        """Take one more reference on an allocated block (prefix hit)."""
        if block not in self._rc:
            raise ValueError(
                f"block {block} is not allocated — cannot share a free "
                "block")
        self._rc[block] += 1
        self.events.append(("share", block))
        return block

    def free(self, blocks) -> list:
        """Drop one reference per listed block.  Returns the blocks
        whose LAST reference dropped (now back on the free list) so the
        caller can retire any prefix-index entries keyed on them."""
        released = []
        for b in blocks:
            if self.free_one(b):
                released.append(b)
        return released

    def free_one(self, block: int) -> bool:
        """Drop one reference; True iff the block was fully released."""
        if block not in self._rc:
            raise ValueError(
                f"block {block} is not allocated (double-free or "
                "foreign id)")
        self.events.append(("free", block))
        self._rc[block] -= 1
        if self._rc[block] == 0:
            del self._rc[block]
            self._free.append(block)
            return True
        return False


def blocks_for(tokens: int, block_len: int) -> int:
    """Pool blocks covering ``tokens`` logical positions."""
    return -(-max(int(tokens), 0) // int(block_len))


def prefix_block_keys(prompt, block_len: int):
    """Content keys for a prompt's blocks, chained so a key commits to
    the WHOLE prefix through its block (two prompts agreeing on block
    ``j``'s key agree on every token before it — the property that
    makes a single dict lookup sufficient for prefix matching).

    Returns ``(full_keys, partial_key)``: one key per *full* prompt
    block, plus a key for the trailing partial block (``None`` when the
    prompt length is a block multiple).  The partial key commits to the
    exact tail run — a prompt extending past another's partial tail
    does NOT match it (the shared block would be missing the extra
    tokens' projections)."""
    toks = np.asarray(prompt, dtype=np.int64)
    bl = int(block_len)
    n_full = len(toks) // bl
    full_keys, h = [], hashlib.sha1(b"adt-prefix")
    for j in range(n_full):
        h.update(toks[j * bl:(j + 1) * bl].tobytes())
        full_keys.append(("full", h.hexdigest()))
    partial_key = None
    tail = toks[n_full * bl:]
    if len(tail):
        h.update(tail.tobytes())
        partial_key = ("partial", len(tail), h.hexdigest())
    return full_keys, partial_key


@dataclasses.dataclass
class PagedKVCache:
    """The paged decode state: block pools + table + occupancy.

    ``k``/``v``: ``[L, num_blocks, heads_local, block_len, head_dim]``
    pools.  ``lengths``: ``[num_slots]`` int32.  ``block_table``:
    ``[num_slots, max_blocks]`` int32 — logical block ``j`` of slot
    ``s`` lives in pool block ``block_table[s, j]`` (unassigned entries
    hold 0; reads past a slot's occupancy are masked, so the value is
    never observable).  Registered as a pytree so the whole cache rides
    jit/scan carries and donation in one piece."""

    k: Any
    v: Any
    lengths: Any
    block_table: Any

    def tree_flatten(self):
        return (self.k, self.v, self.lengths, self.block_table), None

    @classmethod
    def tree_unflatten(cls, _, leaves):
        return cls(*leaves)


jax.tree_util.register_pytree_node(
    PagedKVCache, PagedKVCache.tree_flatten, PagedKVCache.tree_unflatten)


def init_paged_cache(num_layers: int, num_slots: int, num_heads: int,
                     head_dim: int, max_len: int, *, block_len: int,
                     num_blocks: int, dtype=jnp.float32) -> PagedKVCache:
    """All-zero block pool with every slot empty and no block mapped."""
    if block_len < 1:
        raise ValueError("block_len must be >= 1")
    max_blocks = blocks_for(max_len, block_len)
    if num_blocks < max_blocks:
        raise ValueError(
            f"num_blocks={num_blocks} cannot hold even one full-length "
            f"request ({max_blocks} blocks of {block_len} for "
            f"max_len={max_len})")
    shape = (num_layers, num_blocks, num_heads, block_len, head_dim)
    return PagedKVCache(
        k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
        lengths=jnp.zeros((num_slots,), jnp.int32),
        block_table=jnp.zeros((num_slots, max_blocks), jnp.int32))


def paged_write_token(cache_arr, layer: int, kv, positions, block_table,
                      block_len: int, write_mask=None):
    """The paged :func:`write_token`: slot ``i``'s row lands in pool
    block ``block_table[i, positions[i] // block_len]`` at in-block
    offset ``positions[i] % block_len`` — still one true
    ``dynamic_update_slice`` per slot (the block id merely becomes part
    of the dynamic start index), so XLA aliases the write exactly like
    the dense path.

    ``write_mask`` (``[B]`` bool): slots where it is False keep the
    target row bit-for-bit (read-modify-write).  The dense path can
    afford garbage writes for inactive slots — each slot owns its whole
    lane — but a paged slot holding NO reservation has a zeroed table
    row pointing at pool block 0, which may be another slot's live
    block, so inactive writes must be suppressed, not just masked at
    read time.  A logical block index past the table's extent clamps
    (jnp gather semantics) to the row's last entry, which the allocator
    tail-fills with the slot's own last block — so a final window's
    over-decode dirties the slot's own tail block only, the paged
    analog of the dense path's clamped last-lane writes."""
    B = kv.shape[0]
    for slot in range(B):
        pos = positions[slot]
        blk = block_table[slot, pos // block_len]
        upd = kv[slot, 0][None, None, :, None, :].astype(cache_arr.dtype)
        start = (layer, blk, 0, pos % block_len, 0)
        if write_mask is not None:
            cur = lax.dynamic_slice(cache_arr, start, upd.shape)
            upd = jnp.where(write_mask[slot], upd, cur)
        cache_arr = lax.dynamic_update_slice(cache_arr, upd, start)
    return cache_arr


def paged_write_prompt(cache_arr, layer: int, kv, admit, block_table,
                       block_len: int, p_lens, write_from=None):
    """The paged :func:`write_prompt`: slot ``i``'s prompt rows land
    block by block through the table when ``admit[i]``.  Unlike the
    dense path — which writes the whole zero-padded prompt bucket into
    the slot's private lane — a logical block holding NO real prompt
    row (``j·block_len >= p_lens[i]``) is left untouched: a short
    request reserves only its own blocks, so its table row past the
    reservation points at block 0 (possibly another slot's), and the
    padding garbage must never land there.  The final *partial* prompt
    block (``lo < p_lens[i] < hi``) is the slot's own reserved block
    and is overwritten WHOLE — its tail takes the prompt bucket's
    zero-padding projections, unreachable behind the length mask (the
    block-granular write never splits below a block, so only the
    all-or-nothing ``lo < p_lens`` predicate decides).  Non-admitted
    slots' mapped blocks are kept bit-for-bit via the same
    read-modify-write select the dense path uses.

    ``write_from`` (``[B]`` int32, optional): logical blocks
    ``j < write_from[i]`` are skipped — they are prefix-cache hits
    whose physical blocks already hold the identical projections
    (possibly shared with another slot, where an unsuppressed write
    would be a write through a shared table entry — ADT116)."""
    B, S = kv.shape[0], kv.shape[1]
    n_blocks = blocks_for(S, block_len)
    for slot in range(B):
        rows = jnp.transpose(kv[slot], (1, 0, 2))    # [heads, S, dh]
        for j in range(n_blocks):
            lo = j * block_len
            hi = min(lo + block_len, S)
            new = rows[:, lo:hi][None, None].astype(cache_arr.dtype)
            blk = block_table[slot, j]
            cur = lax.dynamic_slice(cache_arr, (layer, blk, 0, 0, 0),
                                    new.shape)
            take = admit[slot] & (lo < p_lens[slot])
            if write_from is not None:
                take = take & (j >= write_from[slot])
            sel = jnp.where(take, new, cur)
            cache_arr = lax.dynamic_update_slice(
                cache_arr, sel, (layer, blk, 0, 0, 0))
    return cache_arr


def paged_write_chunk(cache_arr, layer: int, kv, admit, block_table,
                      block_len: int, chunk_start, p_lens,
                      write_from=None):
    """The chunked :func:`paged_write_prompt`: one prompt *chunk*'s
    projections land block by block through the table at logical blocks
    ``chunk_start // block_len + j``.  ``kv``: ``[B, C, heads, dh]``
    with ``C % block_len == 0`` (the engine validates the chunk knob),
    so every chunk covers whole logical blocks and the write stays
    block-granular; ``chunk_start`` is a traced scalar — ONE compiled
    program serves every chunk of every prompt length.  The same
    ``lo < p_lens`` / ``write_from`` predicates as the single-shot
    writer decide per block; a chunk wholly past a slot's prompt writes
    nothing for it."""
    B, C = kv.shape[0], kv.shape[1]
    n_blocks = C // block_len
    base = chunk_start // block_len
    for slot in range(B):
        rows = jnp.transpose(kv[slot], (1, 0, 2))    # [heads, C, dh]
        for j in range(n_blocks):
            lo = j * block_len
            new = rows[:, lo:lo + block_len][None, None] \
                .astype(cache_arr.dtype)
            blk = block_table[slot, base + j]
            cur = lax.dynamic_slice(cache_arr, (layer, blk, 0, 0, 0),
                                    new.shape)
            take = admit[slot] & (chunk_start + lo < p_lens[slot])
            if write_from is not None:
                take = take & (base + j >= write_from[slot])
            sel = jnp.where(take, new, cur)
            cache_arr = lax.dynamic_update_slice(
                cache_arr, sel, (layer, blk, 0, 0, 0))
    return cache_arr


def chunk_attention(q, k_layer, v_layer, starts, *, dtype=jnp.float32):
    """A token window's causal attention over contiguous cache lanes:
    window row ``r`` of slot ``i`` is the query at absolute position
    ``starts[i] + r`` and attends to every cached key at positions
    ``<= starts[i] + r`` — earlier chunks AND this window's own rows,
    which the caller writes into the cache FIRST (write-then-attend,
    exactly the decode step's ordering).  ``q``: ``[B, C, heads,
    head_dim]``; ``k_layer``/``v_layer``: ``[B, heads, T, head_dim]``.
    Serves the chunked-prefill composed path (via
    :func:`paged_chunk_attention`) and the dense speculative verify
    pass, where every slot's window begins at its own length."""
    depth = q.shape[-1]
    C = q.shape[1]
    q2 = jnp.transpose(q, (0, 2, 1, 3))              # [B, H, C, dh]
    scores = lax.dot_general(
        q2, k_layer.astype(q.dtype),
        (((3,), (3,)), ((0, 1), (0, 1)))) / np.sqrt(depth)
    scores = scores.astype(jnp.float32)              # [B, H, C, T]
    T = k_layer.shape[2]
    ok = jnp.arange(T)[None, None, None, :] <= \
        (starts[:, None] + jnp.arange(C)[None, :])[:, None, :, None]
    scores = jnp.where(ok, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    out = lax.dot_general(
        probs, v_layer.astype(dtype),
        (((3,), (2,)), ((0, 1), (0, 1))))            # [B, H, C, dh]
    return jnp.transpose(out, (0, 2, 1, 3))          # [B, C, H, dh]


def paged_chunk_attention(q, k_pool, v_pool, starts, block_table, *,
                          block_len: int, dtype=jnp.float32):
    """The paged :func:`chunk_attention`: gather the slot's blocks into
    contiguous lanes, then the same masked math (``T`` becomes the
    padded ``max_blocks * block_len`` extent).  The composed gather
    fallback the paged flash-prefill kernel replaces, and its
    interpreter-mode golden."""
    del block_len  # implied by the pool's block extent
    k_layer = gather_blocks(k_pool, block_table)     # [B, H, T, dh]
    v_layer = gather_blocks(v_pool, block_table)
    return chunk_attention(q, k_layer, v_layer, starts, dtype=dtype)


def copy_pool_block(k_pool, v_pool, src, dst):
    """Copy one physical block's K/V rows across every layer — the
    copy-on-write device op: the writer redirects its table entry to
    ``dst`` and writes there, while the other holders keep reading the
    untouched ``src``.  ``src``/``dst`` are traced scalars so one
    compiled copy serves every CoW; a dynamic slice along the block
    axis only, so the model-axis head sharding passes through."""
    kb = lax.dynamic_slice_in_dim(k_pool, src, 1, axis=1)
    vb = lax.dynamic_slice_in_dim(v_pool, src, 1, axis=1)
    k_pool = lax.dynamic_update_slice_in_dim(k_pool, kb, dst, axis=1)
    v_pool = lax.dynamic_update_slice_in_dim(v_pool, vb, dst, axis=1)
    return k_pool, v_pool


def gather_blocks(pool, block_table):
    """Assemble per-slot contiguous K/V lanes from the pool.

    ``pool``: one layer's ``[num_blocks, heads, block_len, head_dim]``
    slice; ``block_table``: ``[B, max_blocks]`` int32.  Returns
    ``[B, heads, max_blocks * block_len, head_dim]`` — the block-table
    *gather* (the structural evidence the ADT115 paged program rule
    keys on).  Positions past a slot's occupancy come from unassigned
    table entries (block 0) and are masked by every reader."""
    B, mb = block_table.shape
    nb, H, bl, dh = pool.shape
    g = jnp.take(pool, block_table, axis=0)      # [B, mb, H, bl, dh]
    g = jnp.moveaxis(g, 2, 1)                    # [B, H, mb, bl, dh]
    return g.reshape(B, H, mb * bl, dh)


def paged_cached_attention(q, k_pool, v_pool, lengths, block_table, *,
                           block_len: int, dtype=jnp.float32):
    """One decode step's attention over a layer's *paged* cache slice:
    gather the slot's blocks into a contiguous lane, then run the exact
    :func:`cached_attention` masked math (T becomes the padded
    ``max_blocks * block_len`` extent; the same ``<= length`` mask
    hides the padded tail and any stale block content).  The composed
    fallback the paged flash-decode kernel replaces."""
    del block_len  # implied by the pool's block extent
    k_layer = gather_blocks(k_pool, block_table)
    v_layer = gather_blocks(v_pool, block_table)
    return cached_attention(q, k_layer, v_layer, lengths, dtype=dtype)
