"""TP-sharded KV cache for batched decode.

One pair of arrays holds every layer's keys and values, laid out

    ``[layer, batch_slot, heads/tp, max_len, head_dim]``

so the whole cache shards over the ``model`` mesh axis with a single
``P(None, None, 'model', None, None)`` spec — the same head split the
Megatron column-parallel qkv projection produces, so a decode step's
freshly projected k/v shards land in their cache slots with zero
resharding (the GSPMD property: one sharding-annotated layout serves
both the training program's attention and the decode program's cache,
arxiv 2105.04663).

Writes are in-place ``lax.dynamic_update_slice`` updates at per-slot
positions (each batch slot advances its own sequence under continuous
batching); under ``jax.jit`` with the cache donated, XLA aliases the
update into the live buffer — ``tools/hlo_probe.py --probe decode``
asserts the compiled step carries the dynamic-update-slices and no
per-step full-cache copy.  Slots are recycled by the batcher: a newly
admitted request's prefill overwrites positions ``[0, prompt_len)`` and
decode overwrites forward from there, and reads are always masked to
``pos < length``, so stale tail entries from the previous occupant are
never observable.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from autodist_tpu import const


def cache_spec() -> P:
    """Partition spec of either cache array: heads over the model axis."""
    return P(None, None, const.MODEL_AXIS, None, None)


@dataclasses.dataclass
class KVCache:
    """The decode-time state: cache arrays + per-slot occupancy.

    ``k``/``v``: ``[L, B, heads_local, T, head_dim]`` (``heads_local =
    num_heads/tp`` inside ``shard_map``; the full head count on the host
    view).  ``lengths``: ``[B]`` int32 — tokens currently materialized
    per slot (the next write position).  Registered as a pytree so the
    whole cache rides jit/scan carries and donation in one piece.
    """

    k: Any
    v: Any
    lengths: Any

    def tree_flatten(self):
        return (self.k, self.v, self.lengths), None

    @classmethod
    def tree_unflatten(cls, _, leaves):
        return cls(*leaves)


jax.tree_util.register_pytree_node(
    KVCache, KVCache.tree_flatten, KVCache.tree_unflatten)


def init_cache(num_layers: int, num_slots: int, num_heads: int,
               head_dim: int, max_len: int, dtype=jnp.float32) -> KVCache:
    """All-zero cache with every slot empty."""
    shape = (num_layers, num_slots, num_heads, max_len, head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   lengths=jnp.zeros((num_slots,), jnp.int32))


def write_token(cache_arr, layer: int, kv, positions):
    """Write one decode step's projections into ``cache_arr`` in place.

    ``kv``: ``[B, 1, heads, head_dim]`` (the qkv projection's layout for
    a single-token step); ``positions``: ``[B]`` int32 — slot ``i``'s
    row lands at ``[layer, i, :, positions[i], :]``.  Per-slot scalar
    positions keep the update a true ``dynamic_update_slice`` (the
    in-place form XLA aliases) instead of a scatter; the slot loop is
    unrolled — ``B`` is the static slot count, small by construction.
    """
    B = kv.shape[0]
    for slot in range(B):
        upd = kv[slot, 0][None, None, :, None, :].astype(cache_arr.dtype)
        cache_arr = lax.dynamic_update_slice(
            cache_arr, upd, (layer, slot, 0, positions[slot], 0))
    return cache_arr


def write_prompt(cache_arr, layer: int, kv, admit):
    """Write a prefill's whole-prompt projections for admitted slots.

    ``kv``: ``[B, S, heads, head_dim]``; slot ``i``'s rows land at
    ``[layer, i, :, 0:S, :]`` when ``admit[i]``, and its existing cache
    rows are kept bit-for-bit otherwise — the read-modify-write touches
    only the ``[heads, S, head_dim]`` window, never the full cache (the
    masking that lets one compiled prefill admit any subset of slots
    while the others keep decoding state).
    """
    B, S = kv.shape[0], kv.shape[1]
    for slot in range(B):
        new = jnp.transpose(kv[slot], (1, 0, 2))[None, None] \
            .astype(cache_arr.dtype)                 # [1,1,heads,S,dh]
        cur = lax.dynamic_slice(cache_arr, (layer, slot, 0, 0, 0),
                                new.shape)
        sel = jnp.where(admit[slot], new, cur)
        cache_arr = lax.dynamic_update_slice(cache_arr, sel,
                                             (layer, slot, 0, 0, 0))
    return cache_arr


def cached_attention(q, k_layer, v_layer, lengths, *, dtype=jnp.float32):
    """One decode step's attention over a layer's cache slice.

    ``q``: ``[B, 1, heads, head_dim]`` (the step's query — the token
    just written at position ``lengths``); ``k_layer``/``v_layer``:
    ``[B, heads, T, head_dim]``.  Key positions ``> lengths`` are masked
    (the just-written token attends to itself and everything before it),
    so stale or zero entries past a slot's occupancy are unreachable.
    Softmax in fp32 with the trained model's scaling — matching
    :func:`~autodist_tpu.models.transformer.dot_product_attention`
    numerics so incremental decode agrees with full-sequence recompute.
    Scores live at ``[B, heads, 1, T]`` — never the ``[T, T]`` square
    the prefill's causal pass needs (the HLO decode probe asserts no
    such buffer exists).
    """
    depth = q.shape[-1]
    q2 = jnp.transpose(q, (0, 2, 1, 3))              # [B, heads, 1, dh]
    # dot_general contracting head_dim directly against the cache's
    # native [.., T, head_dim] layout — an einsum spelling makes XLA
    # transpose (= copy) the whole cache lane every step.
    scores = lax.dot_general(
        q2, k_layer.astype(q.dtype),
        (((3,), (3,)), ((0, 1), (0, 1)))) / np.sqrt(depth)
    scores = scores.astype(jnp.float32)              # [B, heads, 1, T]
    T = k_layer.shape[2]
    ok = jnp.arange(T)[None, None, None, :] <= \
        lengths[:, None, None, None]
    scores = jnp.where(ok, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    out = lax.dot_general(
        probs, v_layer.astype(dtype),
        (((3,), (2,)), ((0, 1), (0, 1))))            # [B, heads, 1, dh]
    return jnp.transpose(out, (0, 2, 1, 3))          # [B, 1, heads, dh]
