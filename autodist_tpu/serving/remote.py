"""Cross-process serving replicas: the Router protocol over the
coordination service.

:class:`~autodist_tpu.serving.fleet.ServingFleet` runs its replicas
in-process; this module runs each replica as a REAL process — one
engine-loop worker per replica host set, launched through
:class:`~autodist_tpu.runtime.cluster.Coordinator` — while the chief
keeps driving the *unchanged*
:class:`~autodist_tpu.serving.router.Router`.  The RPC plane is the
coordination service itself (no new transport):

* **ops** travel chief → worker on the queue
  ``rpc/<name>/i<incarnation>/op`` (JSON ``submit``/``cancel``/
  ``slow``/``stop``);
* **state** travels worker → chief as one idempotent JSON snapshot per
  scheduler round on the KV key ``rpc/<name>/i<incarnation>/state``
  (queue rids, in-flight slot token streams, completions, block-pool
  accounting) — the chief-side :class:`RemoteBatcher` mirrors it into
  the exact duck-type surface the router already reads
  (``completions``/``_slots``/``_queue``/``cancel``);
* **health** is the training plane's machinery verbatim: workers bump
  ``hb/<name>`` via :func:`~autodist_tpu.runtime.cluster.heartbeat`,
  and :meth:`ProcessFleet.poll_health` runs
  ``HeartbeatMonitor.poll_once`` over a real service client — a
  SIGSTOPped replica process is *detected* after the timeout and
  SIGKILLed, exactly a hung worker;
* **faults are real**: a crashed replica is a dead process (the chief
  sees ``WorkerHandle.running`` go false and raises
  :class:`~autodist_tpu.serving.fleet.ReplicaCrashedError` into the
  router's existing declare-dead path), and chaos workers self-inject
  their own deaths from a shipped
  :class:`~autodist_tpu.runtime.faults.FaultPlan`.

Because every router contract (at-most-once emission, failover
re-dispatch of ``prompt + emitted``, hedging, drain re-homing) is
enforced CHIEF-side on the emitted stream, the process boundary adds
no new token-accounting machinery: the sub-rid
``<rid>@<replica>i<inc>.<n>`` travels token-for-token across it, and a
replacement incarnation gets fresh ``rpc/.../i<inc+1>/...`` keys so a
dead incarnation's queued ops can never replay into its successor.

Incarnation keys also scope the snapshot: a mirror ignores state blobs
whose ``inc`` differs from its own, so a stale KV value left by a
killed process cannot masquerade as its replacement's progress.

Worker entry: ``python -m autodist_tpu.serving.remote`` with the env
plane below (the chief's :meth:`ProcessFleet._spawn` ships it)::

    AUTODIST_TPU_REMOTE_REPLICA    replica name (hb/<name> counter key)
    AUTODIST_TPU_REMOTE_ENGINE     {"factory": "mod:fn", "kwargs": {...},
                                    "max_queue": null}
    AUTODIST_TPU_WORKER_INCARNATION  0, 1, ... (replacements)
    AUTODIST_TPU_REMOTE_TELEMETRY  per-worker telemetry dir base
    AUTODIST_TPU_COORD_SERVICE     host:port (+ _TOKEN) of the chief's
                                   coordination server
    AUTODIST_TPU_FAULT_PLAN        optional self-injection plan
"""
from __future__ import annotations

import dataclasses
import importlib
import json
import os
import signal
import sys
import time
from collections import deque
from typing import Optional

from autodist_tpu import telemetry
from autodist_tpu.serving.batcher import OverloadedError
from autodist_tpu.serving.fleet import (FleetConfig, ReplicaCrashedError,
                                        ServingFleet)
from autodist_tpu.utils import logging

ENGINE_ENV = "AUTODIST_TPU_REMOTE_ENGINE"
REPLICA_ENV = "AUTODIST_TPU_REMOTE_REPLICA"
TELEMETRY_ENV = "AUTODIST_TPU_REMOTE_TELEMETRY"
_HB_ENV = "AUTODIST_TPU_REMOTE_HB_S"


def _rpc_keys(name: str, incarnation: int) -> tuple:
    base = f"rpc/{name}/i{incarnation}"
    return f"{base}/meta", f"{base}/op", f"{base}/state"


def _resolve_factory(path: str):
    """``"pkg.mod:fn"`` → the callable (the engine factory must be a
    module-level name — a closure cannot cross a process boundary)."""
    mod, sep, fn = path.partition(":")
    if not sep or not fn:
        raise ValueError(
            f"engine factory {path!r} must be 'module:function'")
    return getattr(importlib.import_module(mod), fn)


def tiny_engine_factory(*, vocab_size: int = 33, hidden_size: int = 16,
                        num_layers: int = 2, num_heads: int = 2,
                        mlp_dim: int = 32, max_len: int = 24,
                        num_slots: int = 2, prefill_len: int = 16,
                        decode_steps: int = 2, kv_layout: str = "paged",
                        kv_block_len: int = 5, seed: int = 0):
    """The test/chaos engine: a deterministic tiny pipeline-LM
    (``PRNGKey(seed)`` params, greedy decode), so every process that
    builds it from the same kwargs serves the SAME token streams — the
    cross-process chaos matrix's parity anchor against the in-process
    golden."""
    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.serving.engine import ServingEngine

    cfg = TransformerConfig(vocab_size=vocab_size, hidden_size=hidden_size,
                            num_layers=num_layers, num_heads=num_heads,
                            mlp_dim=mlp_dim, max_len=max_len,
                            dtype=jnp.float32, dropout_rate=0.0,
                            attention_dropout_rate=0.0)
    params = make_pipeline_lm_trainable(
        cfg, optax.sgd(0.1), jax.random.PRNGKey(seed)).params
    return ServingEngine(cfg, params, num_slots=num_slots, max_len=max_len,
                         prefill_len=prefill_len, decode_steps=decode_steps,
                         kv_layout=kv_layout, kv_block_len=kv_block_len)


# --------------------------------------------------------------------------- #
# Worker side
# --------------------------------------------------------------------------- #
class _SelfFaultPlane:
    """The worker-side landing pad for
    :class:`~autodist_tpu.runtime.faults.FaultInjector`'s serving-plane
    kinds (its ``fleet=`` binding): the process IS the replica, so a
    ``replica_crash`` is a real exit, a ``replica_hang`` a real
    SIGSTOP (only the chief's SIGKILL ends it), and a ``replica_slow``
    an in-loop stall while the heartbeat thread keeps beating —
    healthy-but-straggling, hedging's territory."""

    def __init__(self, name: str):
        self.name = name

    def has_replica(self, name: str) -> bool:
        return name == self.name

    def _flush(self):
        try:
            if telemetry.get().out_dir:
                telemetry.flush()
        except OSError:
            pass

    def inject(self, name: str, kind: str, duration_s: float = 0.5):
        if kind == "crash":
            self._flush()
            os._exit(17)
        elif kind == "hang":
            self._flush()
            os.kill(os.getpid(), signal.SIGSTOP)
        elif kind == "slow":
            time.sleep(duration_s)
            # The straggler's own resume record — the terminal the
            # report's injected↔outcome pairing expects for the one
            # serving fault with no death (mirrors Replica.step).
            telemetry.record_event("fault", fault="replica_slow",
                                   target=self.name, phase="recovered",
                                   action="resumed")
            self._flush()
        else:
            raise ValueError(f"unknown replica fault {kind!r}")


def _engine_meta(engine, max_queue: Optional[int]) -> dict:
    """The scalar engine facts the chief-side proxy needs (published
    once at startup — doubling as the replica-ready handshake)."""
    blocks = list(engine.block_accounting()) \
        if hasattr(engine, "block_accounting") else [0, 0, 0]
    return {
        "pid": os.getpid(),
        "num_slots": int(engine.num_slots),
        "prefill_len": int(engine.prefill_len),
        "max_len": int(engine.max_len),
        "decode_steps": int(engine.decode_steps),
        "kv_layout": getattr(engine, "kv_layout", "dense"),
        "tensor_parallel": int(getattr(engine, "tensor_parallel", 1)),
        "max_prompt_tokens": int(getattr(engine, "max_prompt_tokens",
                                         engine.prefill_len)),
        "prefill_chunk": getattr(engine, "prefill_chunk", None),
        "max_queue": max_queue,
        "blocks": blocks,
    }


def _snapshot(batcher, engine, incarnation: int, step: int,
              extra_done: dict) -> dict:
    """One idempotent state blob: everything the chief's mirror needs,
    written whole each round so a reader never sees a torn update."""
    done = {rid: {"tokens": list(c.tokens), "finish": c.finish_reason}
            for rid, c in batcher.completions.items()}
    done.update(extra_done)
    blocks = list(engine.block_accounting()) \
        if hasattr(engine, "block_accounting") else [0, 0, 0]
    return {
        "inc": incarnation, "step": step,
        "queue": [r.rid for r in batcher._queue],
        "slots": [[s.req.rid, list(s.tokens)]
                  for s in batcher._slots if s is not None],
        "done": done,
        "blocks": blocks,
    }


def _apply_op(batcher, op: dict, extra_done: dict) -> bool:
    """Apply one chief op; returns True on ``stop``.  A submit the
    batcher sheds (queue bound tripped, drain race) synthesizes a
    ``finish="shed"`` completion so the router re-homes the dispatch —
    the replica-local terminal crossing the process boundary."""
    kind = op.get("op")
    if kind == "submit":
        try:
            batcher.submit(op["prompt"],
                           max_new_tokens=int(op["max_new_tokens"]),
                           eos_id=op.get("eos_id"), rid=op["rid"],
                           seed=int(op.get("seed", 0)),
                           deadline_s=op.get("deadline_s"),
                           trace_id=op.get("trace_id"))
        except (OverloadedError, ValueError) as e:
            logging.warning("remote replica shed %s: %s", op["rid"], e)
            extra_done[op["rid"]] = {"tokens": [], "finish": "shed"}
    elif kind == "cancel":
        batcher.cancel(op["rid"])
    elif kind == "slow":
        # Chief-side slow injection: stall this loop while the
        # heartbeat thread keeps beating (straggler, not hang).
        time.sleep(float(op.get("duration_s", 0.5)))
        telemetry.record_event(
            "fault", fault="replica_slow",
            target=os.environ.get(REPLICA_ENV, "?"),
            phase="recovered", action="resumed")
    elif kind == "stop":
        return True
    else:
        logging.warning("remote replica: unknown op %r", kind)
    return False


def run_replica_worker() -> int:
    """The replica engine-loop process (module ``__main__``): build the
    engine from the shipped spec, heartbeat, consume ops, publish state
    snapshots — until a ``stop`` op, an orphaning (the chief died), or
    a self-injected fault ends it."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from autodist_tpu.runtime import cluster, coordination, faults
    from autodist_tpu.serving.batcher import ContinuousBatcher

    name = os.environ.get(REPLICA_ENV, "")
    if not name:
        print(f"remote replica worker: {REPLICA_ENV} not set",
              file=sys.stderr)
        return 2
    incarnation = int(os.environ.get("AUTODIST_TPU_WORKER_INCARNATION",
                                     "0"))
    tel_base = os.environ.get(TELEMETRY_ENV, "")
    if tel_base:
        telemetry.configure(out_dir=os.path.join(
            tel_base, f"{name}-i{incarnation}"))
    client = coordination.service_client()
    if client is None:
        print("remote replica worker: no coordination service "
              "(AUTODIST_TPU_COORD_SERVICE)", file=sys.stderr)
        return 3
    cluster.heartbeat(client, name,
                      interval_s=float(os.environ.get(_HB_ENV, "0.1")))
    spec = json.loads(os.environ[ENGINE_ENV])
    engine = _resolve_factory(spec["factory"])(**spec.get("kwargs", {}))
    max_queue = spec.get("max_queue")
    batcher = ContinuousBatcher(engine, max_queue=max_queue)
    meta_key, op_key, state_key = _rpc_keys(name, incarnation)
    client.put(meta_key,
               json.dumps(_engine_meta(engine, max_queue)).encode())
    injector = None
    # A restarted incarnation must not re-inject its own death.
    plan = faults.load_fault_plan() if incarnation == 0 else None
    ppid = os.getppid()
    extra_done: dict = {}
    step = 0
    stop = False
    while not stop:
        if injector is not None:
            injector.maybe_fire(step)
        for _ in range(64):   # bounded op drain per round
            raw = client.queue_get(op_key, timeout_ms=0)
            if raw is None:
                break
            op = json.loads(raw)
            if plan is not None and injector is None \
                    and op.get("op") == "submit":
                # Arm the self-injection clock at FIRST TRAFFIC, not at
                # boot: a shipped ``at_s`` trigger means "seconds into
                # serving", so the fault lands on in-flight requests no
                # matter how long the rest of the fleet took to boot.
                injector = faults.FaultInjector(
                    plan, self_target=name, fleet=_SelfFaultPlane(name))
            stop = _apply_op(batcher, op, extra_done) or stop
        if batcher._queue or batcher.active_slots:
            batcher.step()
        elif not stop:
            time.sleep(0.01)
        client.put(state_key, json.dumps(
            _snapshot(batcher, engine, incarnation, step,
                      extra_done)).encode())
        if os.getppid() != ppid:
            logging.warning("remote replica %s orphaned; exiting", name)
            break
        step += 1
    if tel_base:
        telemetry.flush()
    return 0


# --------------------------------------------------------------------------- #
# Chief side: the mirror the Router drives
# --------------------------------------------------------------------------- #
@dataclasses.dataclass
class _MirrorCompletion:
    rid: str
    tokens: list
    finish_reason: str


@dataclasses.dataclass
class _MirrorRequest:
    rid: str


@dataclasses.dataclass
class _MirrorSlot:
    req: _MirrorRequest
    tokens: list


class _RemoteEngineProxy:
    """The engine attributes the router/fleet read chief-side, off the
    worker's published meta.  ``release_all_slots`` is a no-op: a dead
    replica process's HBM died with it, and a drained one freed its own
    blocks through its evictions."""

    def __init__(self, meta: dict):
        self.num_slots = meta["num_slots"]
        self.prefill_len = meta["prefill_len"]
        self.max_len = meta["max_len"]
        self.decode_steps = meta["decode_steps"]
        self.kv_layout = meta["kv_layout"]
        self.tensor_parallel = meta["tensor_parallel"]
        self.max_prompt_tokens = meta["max_prompt_tokens"]
        if meta.get("prefill_chunk") is not None:
            self.prefill_chunk = meta["prefill_chunk"]
        self._blocks = tuple(meta.get("blocks") or (0, 0, 0))

    def release_all_slots(self):
        pass

    def block_accounting(self) -> tuple:
        return self._blocks


class RemoteBatcher:
    """The chief-side mirror of one worker's ``ContinuousBatcher``,
    duck-typing exactly the surface the Router reads:
    ``submit``/``cancel``/``completions``/``_slots``/``_queue``/
    ``queue_depth``/``active_slots``.

    Writes are ops on the worker's queue; reads are the last published
    snapshot.  Local echo keeps the mirror honest between snapshots: a
    submit appears in ``_queue`` immediately (so the router's
    least-loaded pick and drain sweep see it before the worker does),
    and a cancel hides its rid until the worker's terminal lands — an
    op in flight is part of the replica's state, not absent from it."""

    def __init__(self, client, meta: dict, *, op_key: str,
                 state_key: str, incarnation: int,
                 engine: _RemoteEngineProxy):
        self._client = client
        self._op_key = op_key
        self._state_key = state_key
        self._incarnation = incarnation
        self._engine = engine
        self.max_queue = meta.get("max_queue")
        self._max_prompt = meta["max_prompt_tokens"]
        self.completions: dict = {}
        self._slots: list = []
        self._queue: deque = deque()
        self._pending: set = set()   # submitted, not yet in a snapshot
        self._gone: set = set()      # cancelled, terminal not yet seen
        self._step = -1

    # ---- writes (ops) ------------------------------------------------- #
    def _put_op(self, op: dict):
        self._client.queue_put(self._op_key, json.dumps(op).encode())

    def submit(self, prompt, *, max_new_tokens: int = 16,
               eos_id: Optional[int] = None, rid: Optional[str] = None,
               deadline_s: Optional[float] = None, seed: int = 0,
               trace_id: Optional[str] = None) -> str:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self._max_prompt:
            raise ValueError(
                f"prompt of {len(prompt)} tokens exceeds the replica's "
                f"admissible {self._max_prompt}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        if self.max_queue is not None \
                and self.queue_depth >= self.max_queue:
            raise OverloadedError(
                f"[{OverloadedError.code}] remote admission queue full "
                f"({self.queue_depth}/{self.max_queue})")
        if rid is None:
            raise ValueError("remote submit needs an explicit rid "
                             "(the router always provides one)")
        self._put_op({"op": "submit", "rid": rid, "prompt": prompt,
                      "max_new_tokens": int(max_new_tokens),
                      "eos_id": eos_id, "seed": int(seed),
                      "deadline_s": deadline_s, "trace_id": trace_id})
        self._pending.add(rid)
        self._queue.append(_MirrorRequest(rid))
        return rid

    def cancel(self, rid: str) -> bool:
        live = rid in self._pending \
            or any(r.rid == rid for r in self._queue) \
            or any(s.req.rid == rid for s in self._slots)
        if not live:
            return False
        self._put_op({"op": "cancel", "rid": rid})
        self._gone.add(rid)
        self._pending.discard(rid)
        self._queue = deque(r for r in self._queue if r.rid != rid)
        self._slots = [s for s in self._slots if s.req.rid != rid]
        return True

    def shutdown(self):
        try:
            self._put_op({"op": "stop"})
        except OSError:
            pass   # worker (or service) already gone

    # ---- reads (snapshot mirror) -------------------------------------- #
    def refresh(self):
        raw = self._client.get(self._state_key, timeout_ms=0)
        if raw is None:
            return
        snap = json.loads(raw)
        if snap.get("inc") != self._incarnation \
                or snap.get("step", -1) < self._step:
            return   # a stale incarnation's blob, or a re-read
        self._step = snap["step"]
        done = snap.get("done", {})
        seen = set(snap.get("queue", ())) | set(done) \
            | {rid for rid, _ in snap.get("slots", ())}
        self._pending -= seen
        self._gone &= seen - set(done)   # terminal seen: stop hiding
        self.completions = {
            rid: _MirrorCompletion(rid, d["tokens"], d["finish"])
            for rid, d in done.items()}
        self._slots = [_MirrorSlot(_MirrorRequest(rid), toks)
                       for rid, toks in snap.get("slots", ())
                       if rid not in self._gone]
        self._queue = deque(
            [_MirrorRequest(rid) for rid in snap.get("queue", ())
             if rid not in self._gone]
            + [_MirrorRequest(rid) for rid in sorted(self._pending)])
        self._engine._blocks = tuple(snap.get("blocks") or (0, 0, 0))

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def active_slots(self) -> int:
        return len(self._slots)


class RemoteReplica:
    """One process-backed replica, duck-typed like
    :class:`~autodist_tpu.serving.fleet.Replica` (lifecycle states,
    ``load``, ``step``, the WorkerHandle-ish monitor surface) so both
    the Router and ``HeartbeatMonitor.poll_once`` drive it unchanged.

    ``step()`` is the chief-side pump: refresh the mirror, and raise
    :class:`~autodist_tpu.serving.fleet.ReplicaCrashedError` when the
    process died — the router's existing catch declares the replica
    dead, exactly as an in-process engine crash."""

    def __init__(self, name: str, handle, *, client, incarnation: int = 0,
                 ready_timeout_s: float = 120.0):
        self.name = name
        self.incarnation = incarnation
        self.handle = handle
        self.state = "admitting"
        self.superseded = False
        self.declared_fault: Optional[str] = None
        self.beats = 0                  # real beats live in hb/<name>
        self.replace_on_retire = False
        self._fault = None              # in-process-injection parity
        self._slow_until = 0.0
        meta_key, op_key, state_key = _rpc_keys(name, incarnation)
        raw = client.get(meta_key, timeout_ms=int(ready_timeout_s * 1e3))
        if raw is None:
            handle.kill()
            raise RuntimeError(
                f"replica {name} (incarnation {incarnation}) never "
                f"published its engine meta within {ready_timeout_s}s")
        meta = json.loads(raw)
        self.pid = meta.get("pid")
        self.engine = _RemoteEngineProxy(meta)
        self.batcher = RemoteBatcher(client, meta, op_key=op_key,
                                     state_key=state_key,
                                     incarnation=incarnation,
                                     engine=self.engine)
        # The monitor's freshness window starts once the replica is
        # READY — the engine build/compile already happened.
        self.started_s = time.monotonic()

    @property
    def running(self) -> bool:
        return self.state in ("admitting", "draining")

    @property
    def load(self) -> int:
        return self.batcher.queue_depth + self.batcher.active_slots

    def step(self):
        if not self.running:
            return
        if not self.handle.running:
            raise ReplicaCrashedError(
                f"[{ReplicaCrashedError.code}] replica {self.name} "
                f"process died (rc={self.handle.proc.poll()})")
        self.batcher.refresh()

    def shutdown(self):
        self.batcher.shutdown()


class ProcessFleet(ServingFleet):
    """A :class:`~autodist_tpu.serving.fleet.ServingFleet` whose
    replicas are real processes.

    The lifecycle machinery is INHERITED — replacement budgets and
    escalation, drain/retire, block accounting, the fault-record
    vocabulary all run the base class's code over
    :class:`RemoteReplica` mirrors; only the edges differ:

    * ``_spawn`` launches ``python -m autodist_tpu.serving.remote``
      through a :class:`~autodist_tpu.runtime.cluster.Coordinator`
      (``fail_fast=False`` — replica deaths are THIS class's to
      absorb, through ``maybe_replace``'s budget, not the
      coordinator's fail-fast teardown) and waits for the worker's
      ready meta;
    * the beat client is a real
      :func:`~autodist_tpu.runtime.coordination.service_client`, so
      ``poll_health`` reads cross-process ``hb/<name>`` counters with
      the training plane's exact freshness semantics;
    * ``declare_dead`` SIGKILLs the process group first (the only
      signal a SIGSTOPped replica still honors), then runs the base
      bookkeeping/record path.

    ``engine_spec`` is the shippable engine recipe:
    ``{"factory": "module:function", "kwargs": {...}, "max_queue":
    None, "env": {...extra worker env...}}``.
    """

    def __init__(self, engine_spec: dict, *,
                 replicas: Optional[int] = None,
                 config: Optional[FleetConfig] = None,
                 telemetry_dir: Optional[str] = None,
                 fault_plan=None, ready_timeout_s: float = 120.0):
        from autodist_tpu.runtime.cluster import Coordinator
        from autodist_tpu.runtime.coordination import (
            CoordServer, reserve_coord_port, service_client)

        if "factory" not in engine_spec:
            raise ValueError("engine_spec needs a 'factory' "
                             "('module:function') entry")
        self.engine_spec = dict(engine_spec)
        self.telemetry_dir = telemetry_dir
        self.fault_plan = fault_plan
        self.ready_timeout_s = ready_timeout_s
        self.coordinator = Coordinator(fail_fast=False)
        self._server = CoordServer(listen_sock=reserve_coord_port())
        self._addr = f"127.0.0.1:{self._server.port}"
        self._prev_service = os.environ.get("AUTODIST_TPU_COORD_SERVICE")
        os.environ["AUTODIST_TPU_COORD_SERVICE"] = self._addr
        self._client = service_client()
        if self._client is None:   # cannot happen with a live server
            raise RuntimeError("coordination service client unavailable")
        self._closed = False
        super().__init__(self._no_local_engines, replicas=replicas,
                         config=config, warm=False)
        # Health over the REAL service counters (one client per thread;
        # the fleet is single-threaded like the router, so the op
        # client doubles as the beat client).
        self._beat_client = self._client

    @staticmethod
    def _no_local_engines():
        raise RuntimeError(
            "ProcessFleet builds engines in worker processes — the "
            "in-process factory must never be called")

    # ------------------------------------------------------------------ #
    def _spawn(self, name: str, incarnation: int = 0) -> RemoteReplica:
        import autodist_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(autodist_tpu.__file__)))
        py_path = os.environ.get("PYTHONPATH", "")
        env = {
            REPLICA_ENV: name,
            "AUTODIST_TPU_WORKER_INCARNATION": str(incarnation),
            ENGINE_ENV: json.dumps({
                k: v for k, v in self.engine_spec.items()
                if k in ("factory", "kwargs", "max_queue")}),
            "AUTODIST_TPU_COORD_SERVICE": self._addr,
            "PYTHONPATH": (f"{pkg_root}:{py_path}" if py_path
                           else pkg_root),
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
            "XLA_FLAGS": "",   # replicas never inherit a simulated mesh
            _HB_ENV: str(min(self.config.heartbeat_interval_s, 0.2)),
        }
        token = os.environ.get("AUTODIST_TPU_COORD_TOKEN", "")
        if token:
            env["AUTODIST_TPU_COORD_TOKEN"] = token
        if self.telemetry_dir:
            env[TELEMETRY_ENV] = self.telemetry_dir
        env.update(self.engine_spec.get("env") or {})
        if self.fault_plan is not None:
            self.fault_plan.ship(env)
        handle = self.coordinator.launch(
            f"{name}-i{incarnation}",
            [sys.executable, "-m", "autodist_tpu.serving.remote"],
            env=env)
        replica = RemoteReplica(name, handle, client=self._client,
                                incarnation=incarnation,
                                ready_timeout_s=self.ready_timeout_s)
        self.replicas.append(replica)
        self._by_name[name] = replica
        if getattr(self, "_monitor", None) is not None:
            # The spawn stalled the whole scheduler (worker boot +
            # compile): forget every freshness window, as the base
            # class does, so the stall cannot read as the OTHER
            # replicas hanging.
            self._monitor._last.clear()
        self._emit_live_gauge()
        return replica

    # ------------------------------------------------------------------ #
    def poll_health(self):
        """The base sweep over the REAL beat client; a control-plane
        blip (poll_once returns None — blind sample) keeps the current
        client, whose own reconnect-and-retry recovers it."""
        now = time.monotonic()
        if self._last_poll_s is not None \
                and now - self._last_poll_s > \
                self.config.heartbeat_timeout_s:
            self._monitor._last.clear()
        self._last_poll_s = now
        self._monitor.poll_once(self._beat_client)

    def inject(self, name: str, kind: str, duration_s: float = 0.5):
        """Chief-side fault injection against the real process: crash
        = SIGKILL, hang = SIGSTOP (only the health check ends it),
        slow = a worker-loop stall op (the heartbeat thread keeps
        beating — a straggler, not a hang)."""
        replica = self._by_name.get(name)
        if replica is None or not replica.running:
            raise ValueError(f"no live replica {name!r} to inject into")
        if kind == "crash":
            replica.handle.kill()
        elif kind == "hang":
            try:
                os.killpg(os.getpgid(replica.handle.proc.pid),
                          signal.SIGSTOP)
            except (ProcessLookupError, PermissionError):
                replica.handle.proc.send_signal(signal.SIGSTOP)
        elif kind == "slow":
            replica.batcher._put_op({"op": "slow",
                                     "duration_s": duration_s})
        else:
            raise ValueError(f"unknown replica fault {kind!r}")

    def declare_dead(self, replica, reason: str,
                     fault: str = "replica_crash"):
        if replica.running and replica.handle.running:
            replica.handle.kill()
        replica.handle.superseded = True   # its exit is accounted HERE
        super().declare_dead(replica, reason, fault=fault)

    def retire_drained(self):
        retiring = [r for r in self.replicas
                    if r.state == "draining" and r.load == 0]
        super().retire_drained()
        for replica in retiring:
            replica.shutdown()

    def block_accounting(self, settle_s: float = 2.0) -> dict:
        """Per-live-replica ``(free, used, total)`` — refreshed from
        the workers' snapshots, polling up to ``settle_s`` for a state
        stable across two reads: a worker evicts its finished slots one
        scheduler round after the chief saw the completion, so the
        zero-leak invariant must be judged on a settled pool, not a
        mirror one round behind it."""
        deadline = time.monotonic() + settle_s
        prev = None
        while True:
            for replica in self.live:
                try:
                    replica.batcher.refresh()
                except OSError:
                    pass   # control-plane blip; judge what we have
            acct = {r.name: r.engine.block_accounting()
                    for r in self.live}
            if acct == prev or time.monotonic() >= deadline:
                return acct
            prev = acct
            time.sleep(0.1)

    # ------------------------------------------------------------------ #
    def close(self):
        """Tear the fleet down: stop ops to live workers, SIGKILL the
        rest, coordination server down, env restored."""
        if self._closed:
            return
        self._closed = True
        from autodist_tpu.runtime import coordination

        for replica in self.replicas:
            if replica.running:
                replica.shutdown()
        # Workers flush their telemetry shards at stop-op exit: give
        # the live ones a graceful window to drain the op before the
        # SIGTERM sweep, or the shards a distributed trace stitches
        # from die with their processes.
        deadline = time.monotonic() + 5.0
        while any(r.handle.running for r in self.replicas) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        self.coordinator.terminate()
        if self._prev_service is None:
            os.environ.pop("AUTODIST_TPU_COORD_SERVICE", None)
        else:
            os.environ["AUTODIST_TPU_COORD_SERVICE"] = self._prev_service
        coordination.reset_service_client()
        self._server.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):   # best-effort: never leak replica processes
        try:
            self.close()
        except Exception:   # noqa: BLE001 — interpreter teardown
            pass


if __name__ == "__main__":
    sys.exit(run_replica_worker())
