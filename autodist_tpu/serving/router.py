"""Queue-depth-aware request router with failover, hedging, and an
at-most-once token-emission contract.

The router is the fleet's single client-facing surface: requests enter
here, get dispatched to the least-loaded admitting replica, and leave
as exactly one :class:`FleetCompletion` each — whatever dies in
between.  The robustness contracts:

* **At-most-once emission** — the router owns the per-request *emitted*
  stream (the tokens a client has already seen).  Replicas only ever
  extend it: a token index is appended exactly once, and any dispatch
  re-covering an already-emitted index must agree with it (the
  interleave-parity property extended across replicas) — the
  ``kind="dispatch"`` telemetry record's ``re_emitted`` count is
  structurally 0 and the report's ``--check`` gates it.
* **Failover re-dispatch** — a dead replica's open requests re-prefill
  *prompt + already-emitted tokens* on a healthy replica (the paged
  block table stores arbitrary prefixes, so the re-prefill is one
  admission) and continue the stream where it stopped: greedy decode
  continues identically because both paths pin to the sequential
  reference, and sampled decode continues identically because the
  gumbel keys fold (request seed, context length, vocab row) — a
  position-keyed draw is re-dispatch-invariant by construction.
* **Hedging** — a request still open past the hedge deadline (explicit
  ``hedge_timeout_s``, or calibrated from the completed-latency
  percentile) gets a duplicate dispatch on a second replica; the first
  terminal wins, the loser is cancelled and its blocks freed the same
  round.
* **Drain** — a draining replica's queued-but-unadmitted dispatches are
  withdrawn and re-homed (``reason="drain"``); its in-flight ones
  finish in place.

Every dispatch decision is one ``kind="dispatch"`` record
(``request``/``replica``/``reason ∈ {route, failover, hedge, drain}``/
``re_emitted``), schema-gated by ``tools/telemetry_report.py --check``
— a failover record additionally requires the paired replica fault
record the fleet emitted when it declared the replica dead.
"""
from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Optional

from autodist_tpu import telemetry
from autodist_tpu.serving.batcher import OverloadedError
from autodist_tpu.serving.fleet import FleetDrainedError, Replica, \
    ServingFleet
from autodist_tpu.utils import logging

DISPATCH_REASONS = ("route", "failover", "hedge", "drain")


class PromptBudgetError(ValueError):
    """The request cannot fit the fleet's failover contract: re-
    prefilling ``prompt + emitted`` must fit every engine's admissible
    prompt (the prefill bucket single-shot; the whole context under
    chunked prefill).  Coded — like ``serve/overloaded`` — so a client
    can tell this *permanent* sizing rejection (shrink the request or
    turn on chunked prefill) from transient overload it should retry.
    Subclasses ``ValueError`` so pre-existing callers' handlers keep
    working."""

    code = "serve/prompt_budget"


@dataclasses.dataclass
class FleetCompletion:
    """One finished fleet request: the emitted stream + how it got
    there (which replica won, how many failovers it survived, whether a
    hedge raced — the facts the fleet report aggregates)."""

    rid: str
    tokens: list
    finish_reason: str
    ttft_s: float
    e2e_s: float
    replica: Optional[str]       # the winning dispatch's replica
    failovers: int = 0
    hedged: bool = False
    hedge_won: bool = False
    trace_id: Optional[str] = None


@dataclasses.dataclass
class _Dispatch:
    replica: Replica
    rid: str                     # the replica-batcher request id
    base: int                    # request tokens already emitted at dispatch
    reason: str                  # one of DISPATCH_REASONS
    t_s: float


@dataclasses.dataclass
class _Open:
    rid: str
    prompt: list
    max_new_tokens: int
    eos_id: Optional[int]
    seed: int
    submit_s: float
    deadline_abs: Optional[float]
    emitted: list = dataclasses.field(default_factory=list)
    dispatches: list = dataclasses.field(default_factory=list)
    first_tok_s: Optional[float] = None
    failovers: int = 0
    hedged: bool = False
    # The replica the request must fail over FROM, remembered across
    # replica-less gaps: a re-home delayed by a replacement compile is
    # still a failover and must be recorded as one, not relabeled a
    # plain route once a replica appears.  drain_pending is the drain
    # sweep's sibling flag (a drain re-home delayed the same way).
    failover_from: Optional[str] = None
    drain_pending: bool = False
    trace_id: Optional[str] = None


class Router:
    """Dispatch/failover/hedge driver over a :class:`ServingFleet`.

    The scheduler is explicit and single-threaded like the batcher's:
    :meth:`step` runs one fleet round (health check → replica rounds →
    harvest → failover/drain re-dispatch → hedging → replacement);
    :meth:`run` steps until every submitted request has its completion.
    """

    def __init__(self, fleet: ServingFleet):
        self.fleet = fleet
        self.config = fleet.config
        self._open: dict[str, _Open] = {}
        self._ids = itertools.count()
        self.completions: dict[str, FleetCompletion] = {}
        # The fleet-level telemetry view: hedge calibration reads the
        # shared ``e2e_s`` window, the autoscaler views ``ttft_ms``, and
        # the SLO gauges are emitted from the same numbers — one
        # windowed-percentile implementation, zero private copies.
        self.aggregator = telemetry.TelemetryAggregator()

    # ------------------------------------------------------------------ #
    # submission + dispatch
    # ------------------------------------------------------------------ #
    def submit(self, prompt, *, max_new_tokens: int = 16,
               eos_id: Optional[int] = None, seed: int = 0,
               rid: Optional[str] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None) -> str:
        """Queue one request with the fleet; returns its id.  The
        failover contract needs room to re-prefill *prompt + emitted*,
        so ``len(prompt) + max_new_tokens - 1`` must fit the engines'
        admissible prompt — the prefill bucket single-shot, the whole
        context under chunked prefill (the rung that makes a long
        re-prefill a first-class admission instead of a rejection).
        A request that cannot fit even that is rejected with the coded
        :class:`PromptBudgetError` — a permanent sizing fact the
        caller must not retry, unlike transient overload.

        Every request gets a distributed-trace id here at the fleet
        edge (``trace_id`` to supply one, ambient trace context next,
        a freshly minted id otherwise); every dispatch/serve/handoff
        record and span the request touches — on any replica, in any
        process — carries it, and ``telemetry.stitch_trace`` resolves
        it into one per-request timeline."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        bucket = min(getattr(r.engine, "max_prompt_tokens",
                             r.engine.prefill_len)
                     for r in self.fleet.replicas)
        if len(prompt) + max_new_tokens - 1 > bucket:
            chunked = all(
                getattr(r.engine, "prefill_chunk", None) is not None
                for r in self.fleet.replicas)
            hint = ("the whole context is the bucket — the request "
                    "exceeds the cache capacity itself" if chunked else
                    "enable prefill_chunk to lift the bucket to the "
                    "whole context")
            raise PromptBudgetError(
                f"[{PromptBudgetError.code}] prompt ({len(prompt)}) + "
                f"max_new_tokens ({max_new_tokens}) - 1 exceeds the "
                f"fleet's prompt bucket ({bucket}); a failover could "
                f"not re-prefill the emitted stream — {hint}")
        if deadline_s is None:
            deadline_s = self.config.request_deadline_s
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        rid = rid if rid is not None else f"freq-{next(self._ids)}"
        if trace_id is None:
            trace_id = telemetry.current_trace_id() \
                or telemetry.mint_trace_id()
        now = time.perf_counter()
        req = _Open(rid=rid, prompt=prompt,
                    max_new_tokens=int(max_new_tokens), eos_id=eos_id,
                    seed=int(seed), submit_s=now,
                    deadline_abs=(now + deadline_s
                                  if deadline_s is not None else None),
                    trace_id=trace_id)
        self._open[rid] = req
        self._dispatch(req, reason="route")
        return rid

    def _pick(self, exclude=()) -> Optional[Replica]:
        """Least-loaded admitting replica (deterministic tie-break on
        name) — the queue-depth-aware dispatch policy."""
        targets = [r for r in self.fleet.admitting if r not in exclude]
        if not targets:
            return None
        return min(targets, key=lambda r: (r.load, r.name))

    def _dispatch(self, req: _Open, reason: str, exclude=(),
                  from_replica: Optional[str] = None
                  ) -> Optional[_Dispatch]:
        """One dispatch of ``req``'s remaining stream onto a replica;
        ``None`` when no admitting replica exists (the request stays
        pending and re-dispatches on a later round)."""
        replica = self._pick(exclude=exclude)
        if replica is None:
            return None
        base = len(req.emitted)
        budget = req.max_new_tokens - base
        remaining = None
        if req.deadline_abs is not None:
            remaining = req.deadline_abs - time.perf_counter()
            if remaining <= 0:
                return None   # the deadline sweep completes it
        sub = f"{req.rid}@{replica.name}i{replica.incarnation}" \
              f".{len(req.dispatches)}"
        try:
            replica.batcher.submit(
                req.prompt + req.emitted, max_new_tokens=budget,
                eos_id=req.eos_id, rid=sub, seed=req.seed,
                deadline_s=remaining, trace_id=req.trace_id)
        except OverloadedError:
            # Shed at the replica (it started draining between pick and
            # submit, or its queue bound tripped): try the others.
            return self._dispatch(req, reason,
                                  exclude=tuple(exclude) + (replica,),
                                  from_replica=from_replica)
        disp = _Dispatch(replica=replica, rid=sub, base=base,
                         reason=reason, t_s=time.perf_counter())
        req.dispatches.append(disp)
        if reason == "failover":
            telemetry.counter("fleet/failovers").inc()
        elif reason == "hedge":
            telemetry.counter("fleet/hedges").inc()
        # The dispatch record: one per routing decision.  re_emitted is
        # the at-most-once contract made auditable — the router never
        # re-emits an already-streamed token, so it is structurally 0
        # and the report's schema gate fails anything else.
        telemetry.record_event(
            "dispatch", request=req.rid, replica=replica.name,
            reason=reason, re_emitted=0, base=base,
            queue_depth=replica.load, from_replica=from_replica,
            **({"trace_id": req.trace_id} if req.trace_id else {}))
        self._emit_depth_gauges()
        return disp

    def _emit_depth_gauges(self):
        for r in self.fleet.live:
            telemetry.gauge(f"fleet/{r.name}/queue_depth").set(r.load)

    # ------------------------------------------------------------------ #
    # harvest: at-most-once emission + completion resolution
    # ------------------------------------------------------------------ #
    def _tokens_of(self, disp: _Dispatch):
        """``(tokens, finish_reason|None)`` of one dispatch as its
        replica currently knows them — completion, in-flight slot, or
        still queued.  A dead replica's state is unreadable (lost with
        the host); callers drop the dispatch instead."""
        batcher = disp.replica.batcher
        comp = batcher.completions.get(disp.rid)
        if comp is not None:
            return list(comp.tokens), comp.finish_reason
        for slot in batcher._slots:
            if slot is not None and slot.req.rid == disp.rid:
                return list(slot.tokens), None
        return [], None

    def _harvest(self):
        now = time.perf_counter()
        for req in list(self._open.values()):
            terminal: Optional[str] = None
            winner: Optional[_Dispatch] = None
            for disp in list(req.dispatches):
                if not disp.replica.running:
                    continue   # the failover sweep handles it
                toks, finish = self._tokens_of(disp)
                stream = disp.base + len(toks)
                for idx in range(len(req.emitted), stream):
                    req.emitted.append(toks[idx - disp.base])
                    if req.first_tok_s is None:
                        req.first_tok_s = now
                # Overlap agreement: a token the client already saw can
                # never be re-emitted, and parity guarantees the
                # re-covering dispatch AGREES with it — a disagreement
                # is a correctness bug, surfaced loudly.
                for idx in range(disp.base, min(len(req.emitted), stream)):
                    if toks[idx - disp.base] != req.emitted[idx]:
                        raise RuntimeError(
                            f"replica {disp.replica.name} diverged on "
                            f"{req.rid} token {idx}: "
                            f"{toks[idx - disp.base]} != already-"
                            f"emitted {req.emitted[idx]} — the at-most-"
                            "once contract would re-emit")
                if finish in ("shed", "drained", "cancelled"):
                    # Replica-local terminals, not request terminals:
                    # the dispatch is gone, the request re-homes.
                    req.dispatches.remove(disp)
                elif finish in ("max_len", "deadline_exceeded") \
                        and terminal is None:
                    terminal, winner = finish, disp
            # Router-side terminals rule (a crash can eat a replica's
            # completion record, but never the emitted stream):
            if req.eos_id is not None and req.eos_id in req.emitted:
                req.emitted = req.emitted[:req.emitted.index(req.eos_id)
                                          + 1]
                terminal = "eos"
                winner = winner or self._covering(req)
            elif len(req.emitted) >= req.max_new_tokens:
                req.emitted = req.emitted[:req.max_new_tokens]
                terminal = "max_tokens"
                winner = winner or self._covering(req)
            if req.deadline_abs is not None and now >= req.deadline_abs \
                    and terminal is None:
                terminal = "deadline_exceeded"
                winner = self._covering(req)
            if terminal is not None:
                self._complete(req, terminal, winner)

    def _covering(self, req: _Open) -> Optional[_Dispatch]:
        """The dispatch whose stream reached the request's last emitted
        token (the winner of a hedge race)."""
        best = None
        for disp in req.dispatches:
            if disp.replica.running:
                toks, _ = self._tokens_of(disp)
                if disp.base + len(toks) >= len(req.emitted) \
                        and (best is None or disp.t_s < best.t_s):
                    best = disp
        return best

    def _complete(self, req: _Open, reason: str,
                  winner: Optional[_Dispatch]):
        now = time.perf_counter()
        # Withdraw EVERY live dispatch — the hedge loser's, and the
        # winner's own slot when the router resolved the terminal ahead
        # of the replica (eos/budget seen in the emitted stream): a
        # completed request must not hold cache blocks one round longer
        # (cancel is a no-op for a dispatch the replica already
        # evicted).
        for disp in req.dispatches:
            if disp.replica.running:
                disp.replica.batcher.cancel(disp.rid)
        hedge_won = winner is not None and winner.reason == "hedge"
        if hedge_won:
            telemetry.counter("fleet/hedge_wins").inc()
        comp = FleetCompletion(
            rid=req.rid, tokens=list(req.emitted), finish_reason=reason,
            ttft_s=(req.first_tok_s or now) - req.submit_s,
            e2e_s=now - req.submit_s,
            replica=winner.replica.name if winner is not None else None,
            failovers=req.failovers, hedged=req.hedged,
            hedge_won=hedge_won, trace_id=req.trace_id)
        self.completions[req.rid] = comp
        del self._open[req.rid]
        self.aggregator.observe_completion(
            ttft_s=comp.ttft_s, e2e_s=comp.e2e_s, finish_reason=reason)
        self.aggregator.emit_slo_gauges()
        telemetry.counter("fleet/requests").inc()
        self._emit_depth_gauges()

    # ------------------------------------------------------------------ #
    # recovery sweeps
    # ------------------------------------------------------------------ #
    def _sweep_failover(self):
        """Re-home requests whose every dispatch died with its replica:
        re-prefill prompt + emitted on a healthy replica.  With no
        healthy replica this round, the request stays pending — but
        keeps its failover provenance, so the eventual re-dispatch
        (after the replacement sweep mints a replica) is still
        recorded as the failover it is."""
        for req in list(self._open.values()):
            live = [d for d in req.dispatches if d.replica.running]
            if live:
                req.dispatches = live
                continue
            if req.dispatches:
                req.failover_from = req.dispatches[-1].replica.name
                req.dispatches = []
            if req.failover_from is not None:
                disp = self._dispatch(req, reason="failover",
                                      from_replica=req.failover_from)
                if disp is not None:
                    req.failovers += 1
                    req.failover_from = None
            elif req.drain_pending:
                # A drain re-home that found no target last round —
                # still a drain move, recorded as one.
                if self._dispatch(req, reason="drain") is not None:
                    req.drain_pending = False
            else:
                # Never dispatched (submitted into a replica-less gap):
                # plain routing, not a failover.
                self._dispatch(req, reason="route")

    def _sweep_drain(self):
        """Withdraw queued-but-unadmitted dispatches from draining
        replicas and re-home them (``reason="drain"``); in-flight ones
        finish where they run."""
        for req in list(self._open.values()):
            for disp in list(req.dispatches):
                replica = disp.replica
                if replica.state != "draining":
                    continue
                batcher = replica.batcher
                if any(r.rid == disp.rid for r in batcher._queue):
                    batcher.cancel(disp.rid)
                    req.dispatches.remove(disp)
                    if not req.dispatches \
                            and self._dispatch(req, reason="drain",
                                               exclude=(replica,)) \
                            is None:
                        # No target this round (single-replica rolling
                        # restart): keep the drain provenance so the
                        # delayed re-home is still recorded as one.
                        req.drain_pending = True

    def _hedge_deadline(self) -> Optional[float]:
        cfg = self.config
        if cfg.hedge_timeout_s is not None:
            return cfg.hedge_timeout_s
        window = self.aggregator.window("e2e_s")
        if cfg.hedge_percentile is None \
                or len(window) < cfg.hedge_min_samples:
            return None
        return window.percentile(cfg.hedge_percentile) * cfg.hedge_factor

    def _sweep_hedge(self):
        deadline = self._hedge_deadline()
        if deadline is None:
            return
        now = time.perf_counter()
        for req in list(self._open.values()):
            if req.hedged or not req.dispatches:
                continue
            primary = req.dispatches[0]
            if now - primary.t_s <= deadline:
                continue
            disp = self._dispatch(
                req, reason="hedge",
                exclude=tuple(d.replica for d in req.dispatches))
            if disp is not None:
                req.hedged = True

    def _sweep_shed(self):
        """The no-replicas backstop: with every replica gone and the
        replacement budget spent, open requests complete ``"shed"``
        (coded — resubmittable elsewhere) instead of hanging
        :meth:`run` forever."""
        if self.fleet.live or not self._open:
            return
        logging.error(
            "[%s] fleet has no live replicas; shedding %d open "
            "request(s)", FleetDrainedError.code, len(self._open))
        telemetry.counter("fleet/shed").inc(len(self._open))
        for req in list(self._open.values()):
            self._complete(req, "shed", None)

    # ------------------------------------------------------------------ #
    # the scheduler
    # ------------------------------------------------------------------ #
    def step(self):
        """One fleet round: health check → replica scheduler rounds
        (a crash surfaces here and is declared) → harvest/emit →
        drain + failover re-dispatch → hedging → replacement →
        drained-replica retirement."""
        self.fleet.poll_health()
        for replica in list(self.fleet.live):
            try:
                replica.step()
            except Exception as e:  # noqa: BLE001 — a replica death
                #   must never take the router down with it
                self.fleet.declare_dead(replica, reason=str(e),
                                        fault="replica_crash")
        self._harvest()
        self._sweep_drain()
        self._sweep_failover()
        self._sweep_hedge()
        for replica in list(self.fleet.replicas):
            if replica.state == "dead" and not replica.superseded:
                self.fleet.maybe_replace(replica)
        self.fleet.retire_drained()
        self._sweep_shed()
        self._emit_depth_gauges()

    def run(self) -> dict:
        """Step until every submitted request has completed; returns
        the completions this call produced (the batcher ``run()``
        contract)."""
        before = set(self.completions)
        while self._open:
            self.step()
        return {rid: c for rid, c in self.completions.items()
                if rid not in before}

    def drain_replica(self, name: str):
        """Drain one replica through the fleet and immediately re-home
        its queued dispatches."""
        self.fleet.drain(name)
        self._sweep_drain()
