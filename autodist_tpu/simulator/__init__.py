"""Strategy cost simulation and auto-selection (the working counterpart
of the reference's AutoSync stub, ``autodist/simulator/``)."""
from autodist_tpu.simulator.auto_strategy import (AutoStrategy,
                                                  default_candidates,
                                                  default_disagg_candidates,
                                                  default_fleet_candidates,
                                                  default_serving_candidates,
                                                  rank_serving)
from autodist_tpu.simulator.cost_model import (CostModel, DecodeCost,
                                               StrategyCost)
from autodist_tpu.simulator.search import (KnobConfig, SearchResult,
                                           SearchSpace, search_strategies)

__all__ = ["AutoStrategy", "CostModel", "StrategyCost", "DecodeCost",
           "default_candidates", "default_serving_candidates",
           "default_disagg_candidates",
           "default_fleet_candidates", "rank_serving", "KnobConfig",
           "SearchResult", "SearchSpace", "search_strategies"]
