"""Strategy cost simulation and auto-selection (the working counterpart
of the reference's AutoSync stub, ``autodist/simulator/``)."""
from autodist_tpu.simulator.auto_strategy import AutoStrategy, default_candidates
from autodist_tpu.simulator.cost_model import CostModel, StrategyCost

__all__ = ["AutoStrategy", "CostModel", "StrategyCost", "default_candidates"]
