"""AutoStrategy: pick the best strategy by analytic cost.

The working realization of the reference's *planned* AutoSync auto-
strategy flow (strategy → cost model → choose; the reference shipped
only the dataset stub, ``autodist/simulator/dataset/README.md``): build
every candidate strategy, score with :class:`CostModel`, take the
cheapest feasible plan.
"""
from __future__ import annotations

from typing import Optional, Sequence

from autodist_tpu.simulator.cost_model import (CostModel, SpecMeshMismatch,
                                               StrategyCost)
from autodist_tpu.strategy import builders as _builders
from autodist_tpu.strategy.base import StrategyBuilder
from autodist_tpu.utils import logging


def default_candidates() -> list[StrategyBuilder]:
    from autodist_tpu.strategy import gspmd_builders, parallel_builders

    return [
        _builders.AllReduce(),
        _builders.AllReduce(chunk_size=512),   # reference's large-model default
        _builders.AllReduce(compressor="bf16"),
        _builders.PSLoadBalancing(),
        _builders.PartitionedPS(),
        _builders.Parallax(),
        _builders.ZeRO(),
        # GSPMD family: FSDP everywhere; TP scores only when the topology
        # has a model axis (otherwise its spec is rejected by the cost
        # model and the candidate is skipped).
        gspmd_builders.FSDPSharded(),
        gspmd_builders.TensorParallel(),
        # Advanced parallelisms: score only when the topology declares
        # their mesh axis (seq / pipe / expert) — and, for Pipeline,
        # when the trainable is stage-structured, or for ExpertParallel,
        # when expert tables exist; otherwise build() raises ValueError
        # and the candidate is skipped.
        parallel_builders.SequenceParallel(),
        parallel_builders.Pipeline(num_microbatches=4),
        # Remat variant: survives the memory feasibility gate when the
        # plain pipeline's activation envelope exceeds HBM (long
        # pipelines); costs recompute FLOPs the time model doesn't see,
        # so it only wins when the plain variant is infeasible.
        parallel_builders.Pipeline(num_microbatches=4, remat=True),
        # Interleaved variant matches trainables with 2 chunks per pipe
        # device (num_stages == 2 x pipe axis); mismatches are skipped.
        parallel_builders.Pipeline(num_microbatches=4, virtual_stages=2),
        # dp×pp×tp: Megatron TP inside each pipeline stage.  Scores only
        # when the topology declares a size-2 model axis AND the stage
        # variables match the tp rule table (qkv/out/wi/wo naming);
        # otherwise build() raises ValueError and the candidate is
        # skipped — the cost model then arbitrates tp=1 vs tp=2 on the
        # per-stage activation all-reduces it prices.
        parallel_builders.Pipeline(num_microbatches=4, tensor_parallel=2),
        # Latency-hiding variant: the same dp×pp×tp composition with the
        # model-axis activation collectives decomposed into the chunked
        # collective-matmul ring; the cost model prices its Megatron
        # boundaries as max(comm, compute) instead of comm + compute,
        # so it ranks at or above the blocking variant on every link
        # profile and wins whenever chunk compute can hide hop latency.
        parallel_builders.Pipeline(num_microbatches=4, tensor_parallel=2,
                                   comm_overlap=True),
        # Vocab-parallel variant: the shared embedding/unembedding
        # shards over the model axis and the loss head runs the
        # streaming fused cross-entropy epilogue — the first candidate
        # that shrinks *memory* (embedding state, opt moments, and peak
        # logits all /tp) rather than step time, so the feasibility
        # gate can elect it when the replicated head's [B,L,V] logits
        # blow HBM.  Scores only for trainables whose prologue/loss_head
        # are vocab-parallel aware; otherwise build() raises ValueError
        # and the candidate is skipped.
        parallel_builders.Pipeline(num_microbatches=4, tensor_parallel=2,
                                   vocab_parallel=True),
        # ZeRO-3 variants: parameters stored sharded over the data axis
        # and all-gathered on demand per layer.  Wire volume matches the
        # stage-1 rs+ag pair, but the per-layer gather launches price
        # strictly above it — so these rank below replication/stage-1 on
        # step time and win through the HBM feasibility gate, exactly
        # when the replicated params+grads (or their Adam moments) blow
        # the memory budget: the second memory lever after
        # vocab_parallel, and the knob AutoStrategy arbitrates against
        # raising the tp degree.
        parallel_builders.Pipeline(num_microbatches=4, zero_stage=3),
        parallel_builders.Pipeline(num_microbatches=4, tensor_parallel=2,
                                   zero_stage=3),
        # Quantized-collective variants (the per-collective precision
        # policy, EQuARX-style): the same dp×pp×tp composition with
        # every boundary narrowed.  The cost model halves/quarters each
        # policied boundary's wire bytes and charges the calibrated
        # quantize/dequantize compute against it, so these rank above
        # their fp32 siblings exactly when the plan is comm-bound —
        # bytes saved > q/dq passes — and below them on compute-bound
        # links where narrowing buys nothing.
        parallel_builders.Pipeline(num_microbatches=4, tensor_parallel=2,
                                   collective_precision="int8"),
        parallel_builders.Pipeline(num_microbatches=4, tensor_parallel=2,
                                   vocab_parallel=True,
                                   collective_precision="int8"),
        parallel_builders.ExpertParallel(),
    ]


def default_serving_candidates(num_devices: int,
                               kv_layouts=("dense", "paged"),
                               ladder: bool = False) -> list[dict]:
    """The serving-config zoo: every (tensor_parallel, vocab_parallel,
    kv_layout) shape the serving engine can lower on ``num_devices``
    devices.  Plain dicts rather than builders — the decode program has
    no pipe axis to build a full training strategy against, and the
    keys are exactly the Strategy-IR ``parallel`` knobs the engine
    reads.

    ``ladder=True`` additionally enumerates the PR-16 throughput-ladder
    rungs on every paged shape: ``prefix_caching=True``,
    ``speculative=4``, and ``prefill_chunk`` at the calibrated
    ``flash_prefill_crossover_chunk`` with the ``flash_prefill``
    kernel elected.  Opt-in — the base zoo (and every config JSON it
    ever produced) stays byte-identical with the flag off."""
    shapes = [{"tensor_parallel": 1, "vocab_parallel": False}]
    tp = 2
    while tp <= num_devices:
        shapes.append({"tensor_parallel": tp, "vocab_parallel": False})
        shapes.append({"tensor_parallel": tp, "vocab_parallel": True})
        tp *= 2
    candidates = []
    for shape in shapes:
        for layout in kv_layouts:
            cand = dict(shape)
            if layout != "dense":
                cand["kv_layout"] = layout
            candidates.append(cand)
            if ladder and layout == "paged":
                from autodist_tpu.simulator.cost_model import \
                    KERNEL_PROFILE
                chunk = int(KERNEL_PROFILE["flash_prefill_crossover_chunk"])
                candidates.append(dict(cand, prefix_caching=True))
                candidates.append(dict(cand, speculative=4))
                candidates.append(dict(cand, prefill_chunk=chunk,
                                       kernel=("flash_prefill",)))
    return candidates


def default_fleet_candidates(num_devices: int, num_slices: int = 1,
                             kv_layouts=("dense", "paged")) -> list[dict]:
    """The fleet-shape zoo: every ``(replicas × tensor_parallel ×
    kv_layout)`` the topology admits — tp bounded by a slice's ICI
    degree (tp never crosses DCN; the cost model rejects it), replicas
    bounded by ``num_devices // tp`` (they may span slices — the
    router's dispatch hop is priced, not forbidden)."""
    per_slice = max(num_devices // max(num_slices, 1), 1)
    candidates = []
    tp = 1
    while tp <= per_slice:
        r = 1
        while r * tp <= num_devices:
            for layout in kv_layouts:
                cand = {"tensor_parallel": tp, "vocab_parallel": tp > 1}
                if r > 1:
                    cand["replicas"] = r
                if layout != "dense":
                    cand["kv_layout"] = layout
                candidates.append(cand)
            r *= 2
        tp *= 2
    return candidates


def default_disagg_candidates(num_devices: int, num_slices: int = 1,
                              kv_layouts=("paged",)) -> list[dict]:
    """The pool-split zoo: every ``(prefill_replicas × decode_replicas
    × tensor_parallel)`` split the topology admits — tp bounded by a
    slice's ICI degree (decode's per-token all-reduces never cross DCN
    — the ADT089 bound), total replicas bounded by ``num_devices //
    tp``.  Handoff rides the block table, so only paged layouts
    qualify."""
    per_slice = max(num_devices // max(num_slices, 1), 1)
    candidates = []
    tp = 1
    while tp <= per_slice:
        total = num_devices // tp
        for prefill in range(1, total):
            for layout in kv_layouts:
                candidates.append({
                    "prefill_replicas": prefill,
                    "decode_replicas": total - prefill,
                    "tensor_parallel": tp,
                    "vocab_parallel": tp > 1,
                    "kv_layout": layout,
                })
        tp *= 2
    return candidates


def rank_serving(trainable, resource_spec, candidates=None, *,
                 batch_slots: int = 1, max_len: int = 2048,
                 mean_request_len=None, mean_prompt_len=None,
                 objective: str = "latency",
                 prefix_hit_rate: float = 0.0, spec_acceptance=None,
                 ladder: bool = False, **cost_model_kwargs):
    """Rank serving configs by the cost model's serving objective —
    AutoStrategy's second objective (ROADMAP: "latency under load, not
    just training step time").

    ``candidates``: serving configs (dicts with ``tensor_parallel`` /
    ``vocab_parallel`` / ``kv_layout``) or trained :class:`Strategy`
    objects whose Strategy-IR parallel knobs describe the serving
    shape; defaults to :func:`default_serving_candidates`.

    ``objective``: ``"latency"`` ranks by per-token time
    (``DecodeCost.score`` — tp/kernel elections); ``"capacity"`` ranks
    by :attr:`~autodist_tpu.simulator.cost_model.DecodeCost
    .serve_score` — per-token time over the concurrent requests the
    HBM carries under ``mean_request_len``, the objective that elects
    ``kv_layout="paged"`` exactly when length variance makes dense
    reservation wasteful; ``"fleet"`` ranks by
    :attr:`~autodist_tpu.simulator.cost_model.DecodeCost.fleet_score`
    over the ``(replicas × tp × kv_layout)`` shapes
    (:func:`default_fleet_candidates`) — aggregate throughput for the
    traffic mix, with replicas priced across DCN and tp held within a
    slice's ICI; ``"disagg"`` ranks by
    :attr:`~autodist_tpu.simulator.cost_model.DecodeCost.disagg_score`
    over the ``(prefill_replicas × decode_replicas × tp)`` pool splits
    (:func:`default_disagg_candidates`) — the request pipeline's
    bottleneck stage under the mix's ``mean_prompt_len`` /
    ``mean_request_len``, so prefill-bound and decode-bound mixes
    elect different splits (pinned both ways on the KV handoff term).
    Returns ``[(config, DecodeCost)]`` best-first
    (feasible configs before infeasible) — the same shape as
    ``AutoStrategy.report``.

    The throughput-ladder inputs describe the TRAFFIC, not the config:
    ``prefix_hit_rate`` (fraction of a typical request's blocks shared
    with a resident prefix — measure it with ``bench.py serve
    --prompt-mix shared-prefix``) prices ``prefix_caching`` candidates
    both directions under the capacity objective;
    ``spec_acceptance`` (draft acceptance rate α — measure it with
    ``bench.py serve --speculative``) prices ``speculative``
    candidates both directions under latency.  ``ladder=True`` widens
    the default zoo with the rung candidates
    (:func:`default_serving_candidates` ``ladder=``)."""
    if objective not in ("latency", "capacity", "fleet", "disagg"):
        raise ValueError(
            f"unknown serving objective {objective!r}; expected "
            "'latency', 'capacity', 'fleet', or 'disagg'")
    cm = CostModel(resource_spec, **cost_model_kwargs)
    if candidates is None:
        num_slices = max(
            int(getattr(resource_spec, "num_slices", 1) or 1), 1)
        if objective == "fleet":
            candidates = default_fleet_candidates(
                resource_spec.num_devices(), num_slices)
        elif objective == "disagg":
            candidates = default_disagg_candidates(
                resource_spec.num_devices(), num_slices)
        else:
            candidates = default_serving_candidates(
                resource_spec.num_devices(), ladder=ladder)
    scored = []
    for cand in candidates:
        try:
            cost = cm.decode_cost(trainable, cand,
                                  batch_slots=batch_slots, max_len=max_len,
                                  mean_request_len=mean_request_len,
                                  mean_prompt_len=mean_prompt_len,
                                  prefix_hit_rate=prefix_hit_rate,
                                  spec_acceptance=spec_acceptance)
        except (ValueError, SpecMeshMismatch) as e:
            logging.info("serving candidate %s skipped: %s", cand, e)
            continue
        scored.append((cand, cost))
    key = {"capacity": lambda it: it[1].serve_score,
           "fleet": lambda it: it[1].fleet_score,
           "disagg": lambda it: it[1].disagg_score,
           "latency": lambda it: it[1].score}[objective]
    scored.sort(key=key)
    return scored


class AutoStrategy(StrategyBuilder):
    """Chooses among candidate builders with the analytic cost model
    (≙ the reference's declared AutoStrategy direction, SURVEY.md §2.3),
    optionally refined by *measurement* — the reference's AutoSync plan
    trained a simulator on measured step times
    (``autodist/simulator/dataset/README.md``); here the hardware itself
    is the simulator: compile the top-k analytic picks, time a few real
    steps each, keep the fastest.

    ``auto = AutoStrategy(); AutoDist(spec, auto).build(trainable)`` —
    after ``build``, ``auto.report`` holds the scored candidates and
    ``auto.measured`` the per-candidate step times (when enabled).

    Args:
      candidates: builder instances to choose among (default: the zoo).
      search: enumerate the topology-aware knob cross-product
        (:mod:`autodist_tpu.simulator.search`) in place of the fixed
        candidate zoo: every ``(dp-across-DCN, dp-within-ICI, pp, tp,
        vocab_parallel, zero_stage, comm_overlap,
        collective_precision, num_microbatches, compressor)`` point
        the topology admits is synthesized, dominance-pruned,
        plan-linted, and priced against the hierarchical (ICI/DCN)
        network model.  The zoo still seeds the frontier, so the
        searched winner never scores below the zoo winner; the same
        report/measure/multihost machinery applies, with searched
        candidates carrying descriptive knob-string names.  After
        ``build``, ``auto.search_result`` holds the full
        :class:`~autodist_tpu.simulator.search.SearchResult`.
      search_space: a :class:`~autodist_tpu.simulator.search.
        SearchSpace` bounding the cross-product (implies
        ``search=True``).
      measure_top_k: when > 1, lower + time this many of the analytically
        best feasible candidates and pick the measured winner.  Costs one
        compile per measured candidate.  Multihost: launch workers with
        ``Cluster.launch_clients(None, ...)`` (no strategy id) and give
        every process the same ``AutoStrategy(measure_top_k=...,
        example_batch=<local batch>)`` — all processes then time the
        candidates in lockstep over the coordination service and adopt
        the chief's measured winner (``_measure_multihost``).
      example_batch: a host batch pytree for the timed steps (required
        when ``measure_top_k > 1``).
      measure_steps: timed steps per candidate (after one compile step).
    """

    def __init__(self, candidates: Optional[Sequence[StrategyBuilder]] = None,
                 measure_top_k: int = 0, example_batch=None,
                 measure_steps: int = 3, search: bool = False,
                 search_space=None, **cost_model_kwargs):
        self.candidates = list(candidates) if candidates is not None \
            else default_candidates()
        self.search = bool(search) or search_space is not None
        self.search_space = search_space
        self.search_result = None
        if not self.candidates and not self.search:
            raise ValueError("AutoStrategy needs at least one candidate")
        if measure_top_k > 1 and example_batch is None:
            raise ValueError("measure_top_k needs an example_batch to time")
        self.measure_top_k = measure_top_k
        self.example_batch = example_batch
        self.measure_steps = measure_steps
        self.cost_model_kwargs = cost_model_kwargs
        self.report: list[tuple[str, StrategyCost]] = []
        self.measured: dict[str, float] = {}
        self._winner_runner = None
        self._winner_strategy_id = None

    def build(self, trainable, resource_spec):
        cm_kwargs = dict(self.cost_model_kwargs)
        if ("tokens_per_step" not in cm_kwargs
                and getattr(trainable, "tokens_per_step", None) is None
                and self.example_batch is not None):
            # Infer the activation-shape hint from the measurement batch:
            # a rank-2 *integer* leaf is a [B, L] token-id tensor.  Float
            # leaves (images, features) are not tokens — inferring from
            # them would price bogus activation collectives, so they
            # leave the hint unset (declare Trainable(tokens_per_step=)
            # to opt in explicitly).
            import numpy as _np

            import jax as _jax
            for leaf in _jax.tree.leaves(self.example_batch):
                if _np.ndim(leaf) == 2 and _np.issubdtype(
                        _np.asarray(leaf).dtype, _np.integer):
                    shape = _np.shape(leaf)
                    cm_kwargs["tokens_per_step"] = int(shape[0] * shape[1])
                    break
        model = CostModel(resource_spec, **cm_kwargs)
        self.measured = {}
        self._winner_runner = None
        self._winner_strategy_id = None
        if self.search:
            scored = self._search_candidates(trainable, resource_spec,
                                             model)
        else:
            scored = self._score_zoo(trainable, resource_spec, model)
        if not scored:
            raise ValueError("no AutoStrategy candidate produced a strategy")
        scored.sort(key=lambda t: (t[1].score, t[1].num_collectives))
        self.report = [(name, cost) for name, cost, _ in scored]
        for name, cost in self.report:
            logging.info(
                "auto-strategy candidate %-18s comm=%8.1fMB t=%7.3fms "
                "colls=%3d mem/dev=%6.2fGB%s", name,
                cost.comm_bytes / 1e6, cost.comm_time_s * 1e3,
                cost.num_collectives, cost.mem_bytes_per_device / 1e9,
                "" if cost.feasible else "  INFEASIBLE")
        best_name, best_cost, best_strategy = scored[0]
        if not best_cost.feasible:
            raise ValueError(
                "no candidate strategy fits in device memory "
                f"(best: {best_name} needs "
                f"{best_cost.mem_bytes_per_device / 1e9:.2f} GB/device)")
        if self.measure_top_k > 1:
            measured = self._measure(trainable, resource_spec, scored)
            if measured is not None:
                best_name, best_strategy = measured
        logging.info("auto-strategy picked %s", best_name)
        return best_strategy

    def _search_candidates(self, trainable, resource_spec, model):
        """The topology-aware cross-product frontier as the candidate
        set (same ``(name, cost, strategy)`` triples the zoo loop
        produces — report/measure/multihost machinery downstream is
        shared)."""
        import numpy as _np

        import jax as _jax

        from autodist_tpu.simulator.search import search_strategies

        global_batch = None
        if self.example_batch is not None:
            leaves = [l for l in _jax.tree.leaves(self.example_batch)
                      if _np.ndim(l) > 0]
            if leaves:
                global_batch = int(_np.shape(leaves[0])[0])
        self.search_result = search_strategies(
            trainable, resource_spec, self.search_space,
            cost_model=model, global_batch=global_batch,
            seed_builders=self.candidates)
        logging.info("auto-strategy search:\n%s",
                     self.search_result.report())
        return [(c.name, c.cost, c.strategy)
                for c in self.search_result.frontier]

    def _score_zoo(self, trainable, resource_spec, model):
        """Score the fixed candidate zoo (the pre-search path, and the
        compatibility default)."""
        import json

        scored = []
        seen_names: dict[str, int] = {}
        seen_content: set[str] = set()
        for builder in self.candidates:
            name = type(builder).__name__
            # Two configs of one builder class (e.g. AllReduce with and
            # without compression) must stay distinct in report/measured.
            seen_names[name] = seen_names.get(name, 0) + 1
            if seen_names[name] > 1:
                name = f"{name}#{seen_names[name]}"
            if (name.startswith("SequenceParallel")
                    and not getattr(trainable, "sequence_ready", False)):
                # Splitting the token dim under a model with plain local
                # attention silently changes the objective; only models
                # declaring sequence_ready are auto-considered.
                logging.debug("candidate %s skipped: trainable does not "
                              "declare sequence_ready", name)
                continue
            try:
                strategy = builder.build(trainable, resource_spec)
            except ValueError as e:
                logging.debug("candidate %s skipped: %s", name, e)
                continue
            if strategy.graph_config.lowering == "pipeline" \
                    and self.example_batch is not None:
                # Screen unbuildable pipeline configs: the schedule needs
                # the per-shard batch divisible by num_microbatches.
                import numpy as _np

                import jax as _jax
                M = int(strategy.graph_config.parallel.get(
                    "num_microbatches", 1))
                repl = max(strategy.graph_config.replicas, 1)
                leaves = [l for l in _jax.tree.leaves(self.example_batch)
                          if _np.ndim(l) > 0]
                if leaves and (_np.shape(leaves[0])[0] % (repl * M)):
                    logging.debug(
                        "candidate %s skipped: batch %d not divisible by "
                        "%d replicas x %d microbatches", name,
                        _np.shape(leaves[0])[0], repl, M)
                    continue
            # Distinct configs can emit byte-identical strategies (e.g.
            # two AllReduce chunk sizes on a model with few tensors):
            # keep only the first, so measurement slots never time the
            # same compiled program twice.
            content = json.dumps([n.to_dict() for n in strategy.node_configs]
                                 + [strategy.graph_config.to_dict()],
                                 sort_keys=True)
            if content in seen_content:
                logging.debug("candidate %s skipped: identical strategy",
                              name)
                continue
            seen_content.add(content)
            try:
                cost = model.strategy_cost(trainable, strategy)
            except SpecMeshMismatch as e:
                logging.debug("candidate %s skipped: %s", name, e)
                continue
            scored.append((name, cost, strategy))
        return scored

    def take_cached_runner(self, strategy_id: str):
        """Hand the measured winner's already-compiled runner to the
        facade (consulted by :meth:`AutoDist.build`) so the winning
        executable is not thrown away and recompiled.  State is re-
        initialized first: the measured steps must not leak into the
        returned runner (from-init numeric equality is a product
        guarantee; re-init is a placement, not a recompile)."""
        if (self._winner_runner is not None
                and self._winner_strategy_id == strategy_id):
            import jax

            runner, self._winner_runner = self._winner_runner, None
            runner.state = runner.lowered.init_state(
                trainable=runner.trainable)
            runner._host_step = 0
            # step() splits self.rng each call — restore the fresh-build
            # default so rng-consuming losses (dropout) also match a
            # from-init build exactly.
            runner.rng = jax.random.PRNGKey(0)
            return runner
        return None

    def drop_cached_runner(self):
        """Release the measured winner's compiled runner without handing
        it out (called by ``AutoDist.build`` when the cache is bypassed),
        freeing its device state instead of retaining HBM."""
        self._winner_runner = None
        self._winner_strategy_id = None

    # ------------------------------------------------------------------ #
    MEASURE_BARRIER_MS = 600_000   # per-candidate: covers a slow compile

    @staticmethod
    def _fence_metrics(metrics):
        import numpy as np
        leaf = np.asarray(next(iter(metrics.values())))
        return float(leaf if leaf.ndim == 0 else leaf[-1])

    @staticmethod
    def _fence_state(runner):
        # The donated-state update can outlive the metrics buffers and
        # its tail differs per candidate; AsyncPSRunner has no .state.
        import numpy as np
        state = getattr(runner, "state", None)
        if state is not None and "step" in state:
            float(np.asarray(state["step"]))

    def _lockstep_candidate(self, client, gen, i, P, runner_ctor,
                            steps: int):
        """One candidate's build + compile + timed steps, identical on
        chief and workers (ONE implementation — the two sides' SPMD
        programs must stay in exact step-count sync or the job deadlocks
        at a collective).  Returns the measured s/step, or ``None`` on
        barrier timeout (a peer died / never joined)."""
        import time

        if not client.barrier(f"autostrategy/{gen}/c{i}", P,
                              timeout_ms=self.MEASURE_BARRIER_MS):
            return None
        runner = runner_ctor()
        try:
            # Steps-per-loop when the runner supports it: the timed
            # window is ONE dispatch, so per-step host dispatch noise
            # cannot skew the candidate ranking.  hasattr is
            # class-determined, so chief and workers take the same
            # branch for the same strategy (the SPMD step-count
            # lockstep requirement).
            fused = hasattr(runner, "run_steps")
            if fused:
                from autodist_tpu.runner import stack_steps
                stacked = stack_steps([self.example_batch] * max(steps, 1))
                self._fence_metrics(runner.run_steps(stacked))  # compile
            else:
                self._fence_metrics(runner.step(self.example_batch))
            self._fence_state(runner)
            if not client.barrier(f"autostrategy/{gen}/c{i}/t", P,
                                  timeout_ms=self.MEASURE_BARRIER_MS):
                return None
            t0 = time.perf_counter()
            if fused:
                self._fence_metrics(runner.run_steps(stacked))
            else:
                for _ in range(steps):
                    metrics = runner.step(self.example_batch)
                self._fence_metrics(metrics)
            self._fence_state(runner)
            return (time.perf_counter() - t0) / max(steps, 1)
        finally:
            # No cross-process runner caching: every process must drop
            # HBM before the next candidate compiles.
            if hasattr(runner, "close"):
                runner.close()

    def _measure_multihost(self, trainable, resource_spec, scored):
        """Coordinated measured refinement across processes (closes the
        round-4 'measurement is single-process only' gap): the chief
        publishes the top-k candidate strategies on the coordination
        service; every process — workers join through
        ``AutoStrategy.join_measurement`` from
        ``AutoDist.build_or_load_strategy`` — builds and steps each
        candidate in lockstep (the SPMD collectives need all
        participants), the chief times its own steps (collective
        lockstep makes every process's wall clock agree up to launch
        skew, fenced by barriers) and publishes the winner for workers
        to adopt.  Requires a coordination service and workers launched
        *before* planning (``Cluster.launch_clients(None, ...)``);
        without one, or on barrier timeout (a peer died or was launched
        with a strategy id instead), falls back to analytic ranking —
        but always publishes a winner first so joined workers never
        hang.

        Candidate *step* failures are deliberately not caught: a
        candidate failing mid-collective on one process diverges the
        SPMD program — it must fail the job exactly as it would in
        training (the feasibility gate screens predictable OOMs first).
        """
        import json

        from autodist_tpu.autodist import AutoDist
        from autodist_tpu.runtime import coordination

        client = coordination.service_client()
        if client is None:
            logging.warning(
                "auto-strategy: multihost measurement needs a coordination "
                "service (AUTODIST_TPU_COORD_SERVICE); using analytic "
                "ranking")
            return None
        P = int(getattr(resource_spec, "num_processes", 1))
        top = [t for t in scored if t[1].feasible][: self.measure_top_k]
        gen = client.counter_add("autostrategy/gen")
        plan = {"steps": int(self.measure_steps),
                "candidates": [[name, strategy.to_json()]
                               for name, _, strategy in top]}
        client.put(f"autostrategy/plan/{gen}", json.dumps(plan).encode())
        # Queue (destructive pop), not a KV key: each worker consumes
        # exactly one gen announcement, so a second measured build in
        # the same coordination-service lifetime can never hand workers
        # a stale generation.
        for _ in range(max(P - 1, 0)):
            client.queue_put("autostrategy/gen_queue", str(gen).encode())

        # Analytic best is the fallback winner on ANY early exit — the
        # winner key must always appear or joined workers would hang.
        win_name, win_strategy = scored[0][0], scored[0][2]

        def publish_winner():
            client.put(f"autostrategy/{gen}/winner",
                       json.dumps([win_name,
                                   win_strategy.to_json()]).encode())

        if not client.barrier(f"autostrategy/{gen}/join", P,
                              timeout_ms=120_000):
            logging.warning(
                "auto-strategy: workers did not join the measurement "
                "rendezvous in 120s (launched with a fixed strategy id, "
                "or a peer died); using analytic ranking")
            publish_winner()
            return None

        ad = AutoDist(resource_spec, self)
        best = None
        for i, (name, _, strategy) in enumerate(top):
            dt = self._lockstep_candidate(
                client, gen, i, P,
                lambda s=strategy: ad.build(trainable, s), plan["steps"])
            if dt is None:
                logging.warning("auto-strategy: peer lost at candidate "
                                "%s; aborting measurement", name)
                publish_winner()
                return None
            self.measured[name] = dt
            logging.info("auto-strategy measured %-18s %7.3f ms/step "
                         "(multihost)", name, dt * 1e3)
            if best is None or dt < best[0]:
                best = (dt, name, strategy)
        if best is not None:
            _, win_name, win_strategy = best
        publish_winner()
        return win_name, win_strategy

    def join_measurement(self, trainable, autodist):
        """Worker-side measurement participant (called from
        ``AutoDist.build_or_load_strategy`` on non-chief processes when
        the builder is a measuring AutoStrategy): mirror the chief's
        candidate loop in lockstep, then adopt the published winner.
        Returns the winner :class:`Strategy`, or ``None`` when no plan
        appears (the chief fell back to analytic ranking before
        publishing — the caller then uses the normal strategy handoff).
        """
        import json

        from autodist_tpu.runtime import coordination
        from autodist_tpu.strategy.ir import Strategy

        client = coordination.service_client()
        if client is None or self.example_batch is None:
            return None
        raw = client.queue_get("autostrategy/gen_queue", timeout_ms=120_000)
        if raw is None:
            return None
        gen = int(raw.decode())
        plan_raw = client.get(f"autostrategy/plan/{gen}", timeout_ms=60_000)
        if plan_raw is None:
            return None
        plan = json.loads(plan_raw.decode())
        P = int(getattr(autodist.resource_spec, "num_processes", 1))
        if not client.barrier(f"autostrategy/{gen}/join", P,
                              timeout_ms=120_000):
            return None
        for i, (name, sjson) in enumerate(plan["candidates"]):
            strategy = Strategy.from_json(sjson)
            # autodist.build (not a bare DistributedRunner): the chief
            # dispatches async-PS node configs to AsyncPSRunner there,
            # and both sides must run the same runner type per
            # candidate.  The loop body is the chief's, verbatim
            # (_lockstep_candidate — ONE implementation).
            if self._lockstep_candidate(
                    client, gen, i, P,
                    lambda s=strategy: autodist.build(trainable, s),
                    int(plan["steps"])) is None:
                break
        win = client.get(f"autostrategy/{gen}/winner",
                         timeout_ms=self.MEASURE_BARRIER_MS)
        if win is None:
            return None
        win_name, win_json = json.loads(win.decode())
        logging.info("auto-strategy (worker): adopted measured winner %s",
                     win_name)
        return Strategy.from_json(win_json)

    def _measure(self, trainable, resource_spec, scored):
        """Time real steps of the analytically-best feasible candidates;
        return ``(name, strategy)`` of the measured winner, or ``None``
        when measurement is unavailable or every candidate failed to
        run.  Multihost dispatches to :meth:`_measure_multihost`.
        Single-process keeps at most two runners alive (the best-so-far
        and the one being timed) and caches the winner's runner for
        :meth:`take_cached_runner`."""
        import time

        from autodist_tpu.autodist import AutoDist

        if getattr(resource_spec, "is_multihost", False):
            return self._measure_multihost(trainable, resource_spec, scored)
        ad = AutoDist(resource_spec, self)

        # ONE fencing contract for single-process and multihost
        # measurement (a drifted copy would silently skew their relative
        # candidate timings): the Trainable contract guarantees scalar
        # metrics ([k]-stacked on the fused path), and the donated-state
        # update can outlive the metrics buffers with a per-candidate
        # tail (e.g. a PS param all-gather), so both window edges fence
        # state too.
        fence = self._fence_metrics
        fence_state = self._fence_state

        best = None   # (dt, name, strategy, runner)
        top = [t for t in scored if t[1].feasible][: self.measure_top_k]
        for name, _, strategy in top:
            runner = None
            try:
                runner = ad.build(trainable, strategy)
                if hasattr(runner, "run_steps"):
                    # One dispatch per window: per-step host dispatch
                    # noise cannot skew the ranking (AsyncPSRunner has
                    # no fused path — its host loop IS the thing being
                    # measured).
                    from autodist_tpu.runner import stack_steps
                    stacked = stack_steps(
                        [self.example_batch] * self.measure_steps)
                    fence(runner.run_steps(stacked))     # compile + warm
                    fence_state(runner)
                    t0 = time.perf_counter()
                    fence(runner.run_steps(stacked))
                    fence_state(runner)
                else:
                    fence(runner.step(self.example_batch))   # compile step
                    fence_state(runner)
                    t0 = time.perf_counter()
                    for _ in range(self.measure_steps):
                        metrics = runner.step(self.example_batch)
                    fence(metrics)
                    fence_state(runner)
                dt = (time.perf_counter() - t0) / self.measure_steps
                self.measured[name] = dt
                logging.info("auto-strategy measured %-18s %7.3f ms/step",
                             name, dt * 1e3)
                if best is None or dt < best[0]:
                    best, runner = (dt, name, strategy, runner), best and best[3]
            except Exception as e:  # a candidate that cannot run loses
                logging.warning("auto-strategy measure %s failed: %s",
                                name, e)
            finally:
                # Free the loser before the next compile; close() tears
                # down any host-side machinery (async-PS thread, in-
                # process CoordServer) that `del` would leak.
                if runner is not None and hasattr(runner, "close"):
                    runner.close()
                del runner
        if best is None:
            return None
        _, name, strategy, winner_runner = best
        if hasattr(winner_runner, "lowered"):  # resettable → cacheable
            self._winner_runner = winner_runner
            self._winner_strategy_id = strategy.id
        elif hasattr(winner_runner, "close"):  # not cacheable: tear down
            winner_runner.close()
        return name, strategy
