"""AutoStrategy: pick the best strategy by analytic cost.

The working realization of the reference's *planned* AutoSync auto-
strategy flow (strategy → cost model → choose; the reference shipped
only the dataset stub, ``autodist/simulator/dataset/README.md``): build
every candidate strategy, score with :class:`CostModel`, take the
cheapest feasible plan.
"""
from __future__ import annotations

from typing import Optional, Sequence

from autodist_tpu.simulator.cost_model import (CostModel, SpecMeshMismatch,
                                               StrategyCost)
from autodist_tpu.strategy import builders as _builders
from autodist_tpu.strategy.base import StrategyBuilder
from autodist_tpu.utils import logging


def default_candidates() -> list[StrategyBuilder]:
    from autodist_tpu.strategy import gspmd_builders, parallel_builders

    return [
        _builders.AllReduce(),
        _builders.AllReduce(chunk_size=512),   # reference's large-model default
        _builders.AllReduce(compressor="bf16"),
        _builders.PSLoadBalancing(),
        _builders.PartitionedPS(),
        _builders.Parallax(),
        _builders.ZeRO(),
        # GSPMD family: FSDP everywhere; TP scores only when the topology
        # has a model axis (otherwise its spec is rejected by the cost
        # model and the candidate is skipped).
        gspmd_builders.FSDPSharded(),
        gspmd_builders.TensorParallel(),
        # Advanced parallelisms: score only when the topology declares
        # their mesh axis (seq / pipe / expert) — and, for Pipeline,
        # when the trainable is stage-structured, or for ExpertParallel,
        # when expert tables exist; otherwise build() raises ValueError
        # and the candidate is skipped.
        parallel_builders.SequenceParallel(),
        parallel_builders.Pipeline(num_microbatches=4),
        # Remat variant: survives the memory feasibility gate when the
        # plain pipeline's activation envelope exceeds HBM (long
        # pipelines); costs recompute FLOPs the time model doesn't see,
        # so it only wins when the plain variant is infeasible.
        parallel_builders.Pipeline(num_microbatches=4, remat=True),
        # Interleaved variant matches trainables with 2 chunks per pipe
        # device (num_stages == 2 x pipe axis); mismatches are skipped.
        parallel_builders.Pipeline(num_microbatches=4, virtual_stages=2),
        parallel_builders.ExpertParallel(),
    ]


class AutoStrategy(StrategyBuilder):
    """Chooses among candidate builders with the analytic cost model
    (≙ the reference's declared AutoStrategy direction, SURVEY.md §2.3),
    optionally refined by *measurement* — the reference's AutoSync plan
    trained a simulator on measured step times
    (``autodist/simulator/dataset/README.md``); here the hardware itself
    is the simulator: compile the top-k analytic picks, time a few real
    steps each, keep the fastest.

    ``auto = AutoStrategy(); AutoDist(spec, auto).build(trainable)`` —
    after ``build``, ``auto.report`` holds the scored candidates and
    ``auto.measured`` the per-candidate step times (when enabled).

    Args:
      candidates: builder instances to choose among (default: the zoo).
      measure_top_k: when > 1, lower + time this many of the analytically
        best feasible candidates and pick the measured winner.  Costs one
        compile per measured candidate; single-process only (the chief
        plans before workers exist in multihost flows).
      example_batch: a host batch pytree for the timed steps (required
        when ``measure_top_k > 1``).
      measure_steps: timed steps per candidate (after one compile step).
    """

    def __init__(self, candidates: Optional[Sequence[StrategyBuilder]] = None,
                 measure_top_k: int = 0, example_batch=None,
                 measure_steps: int = 3, **cost_model_kwargs):
        self.candidates = list(candidates) if candidates is not None \
            else default_candidates()
        if not self.candidates:
            raise ValueError("AutoStrategy needs at least one candidate")
        if measure_top_k > 1 and example_batch is None:
            raise ValueError("measure_top_k needs an example_batch to time")
        self.measure_top_k = measure_top_k
        self.example_batch = example_batch
        self.measure_steps = measure_steps
        self.cost_model_kwargs = cost_model_kwargs
        self.report: list[tuple[str, StrategyCost]] = []
        self.measured: dict[str, float] = {}
        self._winner_runner = None
        self._winner_strategy_id = None

    def build(self, trainable, resource_spec):
        cm_kwargs = dict(self.cost_model_kwargs)
        if ("tokens_per_step" not in cm_kwargs
                and getattr(trainable, "tokens_per_step", None) is None
                and self.example_batch is not None):
            # Infer the activation-shape hint from the measurement batch:
            # a rank-2 *integer* leaf is a [B, L] token-id tensor.  Float
            # leaves (images, features) are not tokens — inferring from
            # them would price bogus activation collectives, so they
            # leave the hint unset (declare Trainable(tokens_per_step=)
            # to opt in explicitly).
            import numpy as _np

            import jax as _jax
            for leaf in _jax.tree.leaves(self.example_batch):
                if _np.ndim(leaf) == 2 and _np.issubdtype(
                        _np.asarray(leaf).dtype, _np.integer):
                    shape = _np.shape(leaf)
                    cm_kwargs["tokens_per_step"] = int(shape[0] * shape[1])
                    break
        model = CostModel(resource_spec, **cm_kwargs)
        self.measured = {}
        self._winner_runner = None
        self._winner_strategy_id = None
        import json

        scored = []
        seen_names: dict[str, int] = {}
        seen_content: set[str] = set()
        for builder in self.candidates:
            name = type(builder).__name__
            # Two configs of one builder class (e.g. AllReduce with and
            # without compression) must stay distinct in report/measured.
            seen_names[name] = seen_names.get(name, 0) + 1
            if seen_names[name] > 1:
                name = f"{name}#{seen_names[name]}"
            if (name.startswith("SequenceParallel")
                    and not getattr(trainable, "sequence_ready", False)):
                # Splitting the token dim under a model with plain local
                # attention silently changes the objective; only models
                # declaring sequence_ready are auto-considered.
                logging.debug("candidate %s skipped: trainable does not "
                              "declare sequence_ready", name)
                continue
            try:
                strategy = builder.build(trainable, resource_spec)
            except ValueError as e:
                logging.debug("candidate %s skipped: %s", name, e)
                continue
            if strategy.graph_config.lowering == "pipeline" \
                    and self.example_batch is not None:
                # Screen unbuildable pipeline configs: the schedule needs
                # the per-shard batch divisible by num_microbatches.
                import numpy as _np

                import jax as _jax
                M = int(strategy.graph_config.parallel.get(
                    "num_microbatches", 1))
                repl = max(strategy.graph_config.replicas, 1)
                leaves = [l for l in _jax.tree.leaves(self.example_batch)
                          if _np.ndim(l) > 0]
                if leaves and (_np.shape(leaves[0])[0] % (repl * M)):
                    logging.debug(
                        "candidate %s skipped: batch %d not divisible by "
                        "%d replicas x %d microbatches", name,
                        _np.shape(leaves[0])[0], repl, M)
                    continue
            # Distinct configs can emit byte-identical strategies (e.g.
            # two AllReduce chunk sizes on a model with few tensors):
            # keep only the first, so measurement slots never time the
            # same compiled program twice.
            content = json.dumps([n.to_dict() for n in strategy.node_configs]
                                 + [strategy.graph_config.to_dict()],
                                 sort_keys=True)
            if content in seen_content:
                logging.debug("candidate %s skipped: identical strategy",
                              name)
                continue
            seen_content.add(content)
            try:
                cost = model.strategy_cost(trainable, strategy)
            except SpecMeshMismatch as e:
                logging.debug("candidate %s skipped: %s", name, e)
                continue
            scored.append((name, cost, strategy))
        if not scored:
            raise ValueError("no AutoStrategy candidate produced a strategy")
        scored.sort(key=lambda t: (t[1].score, t[1].num_collectives))
        self.report = [(name, cost) for name, cost, _ in scored]
        for name, cost in self.report:
            logging.info(
                "auto-strategy candidate %-18s comm=%8.1fMB t=%7.3fms "
                "colls=%3d mem/dev=%6.2fGB%s", name,
                cost.comm_bytes / 1e6, cost.comm_time_s * 1e3,
                cost.num_collectives, cost.mem_bytes_per_device / 1e9,
                "" if cost.feasible else "  INFEASIBLE")
        best_name, best_cost, best_strategy = scored[0]
        if not best_cost.feasible:
            raise ValueError(
                "no candidate strategy fits in device memory "
                f"(best: {best_name} needs "
                f"{best_cost.mem_bytes_per_device / 1e9:.2f} GB/device)")
        if self.measure_top_k > 1:
            measured = self._measure(trainable, resource_spec, scored)
            if measured is not None:
                best_name, best_strategy = measured
        logging.info("auto-strategy picked %s", best_name)
        return best_strategy

    def take_cached_runner(self, strategy_id: str):
        """Hand the measured winner's already-compiled runner to the
        facade (consulted by :meth:`AutoDist.build`) so the winning
        executable is not thrown away and recompiled.  State is re-
        initialized first: the measured steps must not leak into the
        returned runner (from-init numeric equality is a product
        guarantee; re-init is a placement, not a recompile)."""
        if (self._winner_runner is not None
                and self._winner_strategy_id == strategy_id):
            import jax

            runner, self._winner_runner = self._winner_runner, None
            runner.state = runner.lowered.init_state(
                trainable=runner.trainable)
            runner._host_step = 0
            # step() splits self.rng each call — restore the fresh-build
            # default so rng-consuming losses (dropout) also match a
            # from-init build exactly.
            runner.rng = jax.random.PRNGKey(0)
            return runner
        return None

    def drop_cached_runner(self):
        """Release the measured winner's compiled runner without handing
        it out (called by ``AutoDist.build`` when the cache is bypassed),
        freeing its device state instead of retaining HBM."""
        self._winner_runner = None
        self._winner_strategy_id = None

    # ------------------------------------------------------------------ #
    def _measure(self, trainable, resource_spec, scored):
        """Time real steps of the analytically-best feasible candidates;
        return ``(name, strategy)`` of the measured winner, or ``None``
        when measurement is unavailable (multihost planning) or every
        candidate failed to run.  Keeps at most two runners alive (the
        best-so-far and the one being timed) and caches the winner's
        runner for :meth:`take_cached_runner`."""
        import time

        import numpy as np

        from autodist_tpu.autodist import AutoDist

        if getattr(resource_spec, "is_multihost", False):
            logging.warning("auto-strategy: measurement skipped in "
                            "multihost planning (chief plans before "
                            "workers exist); using analytic ranking")
            return None
        ad = AutoDist(resource_spec, self)

        def fence(metrics):
            # Same invariant as examples/benchmark/common.py: the
            # Trainable contract guarantees scalar metrics, not a "loss"
            # key specifically.
            return float(np.asarray(next(iter(metrics.values()))))

        def fence_state(runner):
            # The donated-state update can outlive the metrics buffers
            # (examples/benchmark/common.py:90-94) and its tail — e.g. a
            # PS param all-gather — differs per candidate, so both window
            # edges must fence state, not just metrics.
            state = getattr(runner, "state", None)
            if state is not None and "step" in state:
                float(np.asarray(state["step"]))

        best = None   # (dt, name, strategy, runner)
        top = [t for t in scored if t[1].feasible][: self.measure_top_k]
        for name, _, strategy in top:
            runner = None
            try:
                runner = ad.build(trainable, strategy)
                fence(runner.step(self.example_batch))   # compile step
                fence_state(runner)
                t0 = time.perf_counter()
                for _ in range(self.measure_steps):
                    metrics = runner.step(self.example_batch)
                fence(metrics)
                fence_state(runner)
                dt = (time.perf_counter() - t0) / self.measure_steps
                self.measured[name] = dt
                logging.info("auto-strategy measured %-18s %7.3f ms/step",
                             name, dt * 1e3)
                if best is None or dt < best[0]:
                    best, runner = (dt, name, strategy, runner), best and best[3]
            except Exception as e:  # a candidate that cannot run loses
                logging.warning("auto-strategy measure %s failed: %s",
                                name, e)
            finally:
                # Free the loser before the next compile; close() tears
                # down any host-side machinery (async-PS thread, in-
                # process CoordServer) that `del` would leak.
                if runner is not None and hasattr(runner, "close"):
                    runner.close()
                del runner
        if best is None:
            return None
        _, name, strategy, winner_runner = best
        if hasattr(winner_runner, "lowered"):  # resettable → cacheable
            self._winner_runner = winner_runner
            self._winner_strategy_id = strategy.id
        elif hasattr(winner_runner, "close"):  # not cacheable: tear down
            winner_runner.close()
        return name, strategy
