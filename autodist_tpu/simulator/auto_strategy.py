"""AutoStrategy: pick the best strategy by analytic cost.

The working realization of the reference's *planned* AutoSync auto-
strategy flow (strategy → cost model → choose; the reference shipped
only the dataset stub, ``autodist/simulator/dataset/README.md``): build
every candidate strategy, score with :class:`CostModel`, take the
cheapest feasible plan.
"""
from __future__ import annotations

from typing import Optional, Sequence

from autodist_tpu.simulator.cost_model import (CostModel, SpecMeshMismatch,
                                               StrategyCost)
from autodist_tpu.strategy import builders as _builders
from autodist_tpu.strategy.base import StrategyBuilder
from autodist_tpu.utils import logging


def default_candidates() -> list[StrategyBuilder]:
    from autodist_tpu.strategy import gspmd_builders

    return [
        _builders.AllReduce(),
        _builders.AllReduce(compressor="bf16"),
        _builders.PSLoadBalancing(),
        _builders.PartitionedPS(),
        _builders.Parallax(),
        _builders.ZeRO(),
        # GSPMD family: FSDP everywhere; TP scores only when the topology
        # has a model axis (otherwise its spec is rejected by the cost
        # model and the candidate is skipped).
        gspmd_builders.FSDPSharded(),
        gspmd_builders.TensorParallel(),
    ]


class AutoStrategy(StrategyBuilder):
    """Chooses among candidate builders with the analytic cost model
    (≙ the reference's declared AutoStrategy direction, SURVEY.md §2.3).

    ``auto = AutoStrategy(); AutoDist(spec, auto).build(trainable)`` —
    after ``build``, ``auto.report`` holds the scored candidates.
    """

    def __init__(self, candidates: Optional[Sequence[StrategyBuilder]] = None,
                 **cost_model_kwargs):
        self.candidates = list(candidates) if candidates is not None \
            else default_candidates()
        if not self.candidates:
            raise ValueError("AutoStrategy needs at least one candidate")
        self.cost_model_kwargs = cost_model_kwargs
        self.report: list[tuple[str, StrategyCost]] = []

    def build(self, trainable, resource_spec):
        model = CostModel(resource_spec, **self.cost_model_kwargs)
        scored = []
        for builder in self.candidates:
            name = type(builder).__name__
            try:
                strategy = builder.build(trainable, resource_spec)
            except ValueError as e:
                logging.debug("candidate %s skipped: %s", name, e)
                continue
            try:
                cost = model.strategy_cost(trainable, strategy)
            except SpecMeshMismatch as e:
                logging.debug("candidate %s skipped: %s", name, e)
                continue
            scored.append((name, cost, strategy))
        if not scored:
            raise ValueError("no AutoStrategy candidate produced a strategy")
        scored.sort(key=lambda t: (t[1].score, t[1].num_collectives))
        self.report = [(name, cost) for name, cost, _ in scored]
        for name, cost in self.report:
            logging.info(
                "auto-strategy candidate %-18s comm=%8.1fMB t=%7.3fms "
                "colls=%3d mem/dev=%6.2fGB%s", name,
                cost.comm_bytes / 1e6, cost.comm_time_s * 1e3,
                cost.num_collectives, cost.mem_bytes_per_device / 1e9,
                "" if cost.feasible else "  INFEASIBLE")
        best_name, best_cost, best_strategy = scored[0]
        if not best_cost.feasible:
            raise ValueError(
                "no candidate strategy fits in device memory "
                f"(best: {best_name} needs "
                f"{best_cost.mem_bytes_per_device / 1e9:.2f} GB/device)")
        logging.info("auto-strategy picked %s", best_name)
        return best_strategy
