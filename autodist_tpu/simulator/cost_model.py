"""Analytic strategy cost model.

The reference's AutoSync simulator was a *stub* — an empty package plus
the dataset README describing per-(model, strategy, resource) runtime
records for training a learned cost model
(``autodist/simulator/dataset/README.md:1-94``).  This module supplies
the working equivalent analytically: per-variable communication volume,
collective-launch latency, and per-device memory for a candidate
strategy on a given TPU topology, using the per-generation hardware
constants in :mod:`autodist_tpu.resource`.

Costs are *relative* ranks, not wall-clock predictions: compute time is
strategy-invariant for the data-parallel family, so strategies are
ordered by communication time plus a memory-feasibility gate.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

from autodist_tpu.capture import Trainable
from autodist_tpu.resource import ResourceSpec
from autodist_tpu.strategy.ir import Strategy

# Per-collective launch overhead (seconds).  ICI collectives are
# microsecond-scale to start; the exact constant only needs to penalize
# many-small-collective plans relative to bucketed ones.
COLLECTIVE_ALPHA = 5e-6

# Payload scale factors per compressor (grad bytes on the wire).
COMPRESSOR_FACTOR = {
    "none": 1.0,
    "fp16": 0.5, "bf16": 0.5,
    "fp16_ef": 0.5, "bf16_ef": 0.5,
    # int8_ef quantizes to int8 levels but its psum rides an fp16 wire;
    # int8_ring is the true-int8-wire ring.
    "int8_ef": 0.5,
    "int8_ring": 0.25,
    # (n + m)·r vs n·m bytes, ~2r/sqrt(total): a static stand-in for a
    # data-dependent ratio; at BERT-scale buckets it is ≲ 0.01.
    "powersgd": 0.02,
}


class SpecMeshMismatch(ValueError):
    """A GSPMD sharding spec names a mesh axis the topology lacks —
    the candidate is invalid for this resource spec (AutoStrategy skips
    it), as opposed to a genuine cost-model error."""


@dataclasses.dataclass
class StrategyCost:
    """Breakdown for one (trainable, strategy, topology) triple."""

    comm_bytes: float          # total collective payload per step
    comm_time_s: float         # bandwidth term + per-collective latency
    num_collectives: int
    mem_bytes_per_device: float
    feasible: bool             # fits in HBM (with headroom)

    @property
    def score(self) -> float:
        """Lower is better; infeasible plans rank last."""
        return self.comm_time_s if self.feasible else math.inf


class CostModel:
    """Scores strategies against a resource spec's topology constants."""

    def __init__(self, resource_spec: ResourceSpec, *,
                 sparsity_fraction: float = 0.05,
                 opt_state_multiplier: float = 2.0,
                 hbm_headroom: float = 0.6):
        """``sparsity_fraction``: expected fraction of embedding rows
        touched per step (drives the sparse gather/scatter volume).
        ``opt_state_multiplier``: optimizer slots per parameter byte
        (2.0 = adam m+v).  ``hbm_headroom``: fraction of HBM the model
        state may occupy (the rest is activations/workspace)."""
        self.spec = resource_spec
        self.chip = resource_spec.chip
        self.sparsity_fraction = sparsity_fraction
        self.opt_state_multiplier = opt_state_multiplier
        self.hbm_headroom = hbm_headroom

    @staticmethod
    def _gspmd_shards(node, mesh) -> tuple[int, bool]:
        """(device count the node's spec shards one variable over, whether
        the data axis is among its sharding axes); raises
        :class:`SpecMeshMismatch` when the spec names an axis the
        topology lacks."""
        from autodist_tpu import const

        part = node.partitioner
        shards, uses_data = 1, False
        spec = part.spec if part is not None and part.spec is not None \
            else None
        if spec is None:
            if part is not None and part.num_shards > 1:
                shards = part.num_shards
            return shards, uses_data
        for axis in spec:
            for a in (axis if isinstance(axis, (list, tuple)) else [axis]):
                if a is None:
                    continue
                if a not in mesh:
                    raise SpecMeshMismatch(
                        f"{node.var_name}: spec names mesh axis {a!r} "
                        f"absent from topology {mesh}")
                shards *= mesh[a]
                uses_data |= a == const.DATA_AXIS
        return shards, uses_data

    def _gspmd_cost(self, trainable, strategy) -> StrategyCost:
        """Pricing for gspmd-lowered strategies.

        * data-axis-sharded (FSDP layout): state at 1/shards; per step the
          grads reduce-scatter and the params all-gather over the data
          axis — ring-equivalent *full* tensor volume, same as the
          collective path's sharded branch.
        * model-axis-sharded (TP): each device permanently owns its
          slice; only the slice's gradient syncs over the data axis.
          Activation collectives on the model axis depend on batch shape
          the cost model cannot see — they appear in the per-collective
          latency term only (documented limitation).
        * replicated: the DP grad allreduce.
        """
        mesh = self.spec.resolved_mesh_shape()
        n = max(strategy.graph_config.replicas, 1)
        infos = {v.name: v for v in trainable.var_infos()}
        ring = 2.0 * (n - 1) / n if n > 1 else 0.0
        total_devices = 1
        for v in mesh.values():
            total_devices *= v
        comm_bytes = mem_bytes = 0.0
        num_collectives = 0
        for node in strategy.node_configs:
            info = infos.get(node.var_name)
            if info is None:
                continue
            bytes_ = float(info.byte_size)
            shards, uses_data = self._gspmd_shards(node, mesh)
            if shards > 1:
                mem_bytes += bytes_ * (2.0 + self.opt_state_multiplier) \
                    / shards
                comm_bytes += ring * (bytes_ if uses_data
                                      else bytes_ / shards)
                num_collectives += 2
            else:
                mem_bytes += bytes_ * (2.0 + self.opt_state_multiplier)
                comm_bytes += ring * bytes_
                num_collectives += 1
        bw = self.chip.ici_gbps * 1e9
        comm_time = comm_bytes / bw \
            + COLLECTIVE_ALPHA * num_collectives * (1 if total_devices > 1
                                                    else 0)
        hbm = self.chip.hbm_gb * 1e9 * self.hbm_headroom
        return StrategyCost(comm_bytes=comm_bytes, comm_time_s=comm_time,
                            num_collectives=num_collectives,
                            mem_bytes_per_device=mem_bytes,
                            feasible=mem_bytes <= hbm)

    def strategy_cost(self, trainable: Trainable,
                      strategy: Strategy) -> StrategyCost:
        if strategy.graph_config.lowering == "gspmd":
            return self._gspmd_cost(trainable, strategy)
        n = max(strategy.graph_config.replicas, 1)
        infos = {v.name: v for v in trainable.var_infos()}
        ring = 2.0 * (n - 1) / n if n > 1 else 0.0

        comm_bytes = 0.0
        mem_bytes = 0.0
        groups: set = set()
        num_collectives = 0
        for node in strategy.node_configs:
            info = infos.get(node.var_name)
            if info is None:
                continue
            bytes_ = float(info.byte_size)
            sharded = node.partitioner is not None
            sync = node.synchronizer
            factor = COMPRESSOR_FACTOR.get(
                (getattr(sync, "compressor", "none") or "none")
                .partition(":")[0], 1.0)
            # Touched-rows pricing only applies when the lowering actually
            # takes the sparse path: PS + vocab(axis-0) partitioning
            # (lowering.py make_plan's sparse_lookup gate).
            sparse_fast = (
                node.is_sparse and sync.kind == "ps" and sharded
                and node.partitioner.num_shards > 1
                and max(node.partitioner.split_axis, 0) == 0)

            if sparse_fast:
                # Sparse sharded path: only touched rows move (gather of
                # params + scatter of grads), ≙ the reference's sparse
                # PS push/pull (ps_synchronizer.py:476-535).
                comm_bytes += 2.0 * self.sparsity_fraction * bytes_
                num_collectives += 2
                mem_bytes += (bytes_ / n) * (1.0 + self.opt_state_multiplier) \
                    + self.sparsity_fraction * bytes_  # gathered activations
            elif sharded:
                # Sharded-state (PartitionedPS/ZeRO): reduce_scatter grads
                # + all_gather params — ring-equivalent volume, two
                # launches, optimizer state sharded 1/n.
                comm_bytes += ring * bytes_ * factor
                num_collectives += 2
                mem_bytes += bytes_ \
                    + bytes_ * factor \
                    + (bytes_ * self.opt_state_multiplier) / n
            elif sync.kind == "ps":
                # Dense unpartitioned PS ⇒ ZeRO-1 U_FLAT lowering
                # (lowering.py:150-152): params + grads replicated,
                # reduce_scatter grads + all_gather params (ring-equivalent
                # volume), optimizer state sharded 1/n.
                comm_bytes += ring * bytes_
                num_collectives += 2
                mem_bytes += 2.0 * bytes_ \
                    + (bytes_ * self.opt_state_multiplier) / n
            else:
                # Replicated DP allreduce: bucketed collectives count once
                # per group (≙ ScopedAllocator merging, runner.py:40-46).
                comm_bytes += ring * bytes_ * factor
                group = getattr(sync, "group", None)
                if group is not None:
                    groups.add(group)
                else:
                    num_collectives += 1
                mem_bytes += bytes_ * (2.0 + self.opt_state_multiplier)

        num_collectives += len(groups)
        bw = self.chip.ici_gbps * 1e9  # bytes/s
        comm_time = (comm_bytes / bw if n > 1 else 0.0) \
            + COLLECTIVE_ALPHA * num_collectives * (1 if n > 1 else 0)
        hbm = self.chip.hbm_gb * 1e9 * self.hbm_headroom
        return StrategyCost(
            comm_bytes=comm_bytes,
            comm_time_s=comm_time,
            num_collectives=num_collectives,
            mem_bytes_per_device=mem_bytes,
            feasible=mem_bytes <= hbm,
        )
