"""Analytic strategy cost model.

The reference's AutoSync simulator was a *stub* — an empty package plus
the dataset README describing per-(model, strategy, resource) runtime
records for training a learned cost model
(``autodist/simulator/dataset/README.md:1-94``).  This module supplies
the working equivalent analytically: per-variable communication volume,
collective-launch latency, and per-device memory for a candidate
strategy on a given TPU topology, using the per-generation hardware
constants in :mod:`autodist_tpu.resource`.

Costs are *relative* ranks, not wall-clock predictions: compute time is
strategy-invariant for the data-parallel family, so strategies are
ordered by communication time plus a memory-feasibility gate.
"""
from __future__ import annotations

import dataclasses
import math
import re
from typing import Optional

from autodist_tpu.capture import Trainable
from autodist_tpu.resource import ResourceSpec
from autodist_tpu.strategy.ir import Strategy

# Per-collective launch overhead (seconds).  ICI collectives are
# microsecond-scale to start; the exact constant only needs to penalize
# many-small-collective plans relative to bucketed ones.
COLLECTIVE_ALPHA = 5e-6

# Payload scale factors per compressor (grad bytes on the wire).
# Analytic defaults; :func:`load_calibration` / the
# ``tools/calibrate_compressors.py`` driver replace them with measured
# wall-clock ratios (int8_ring's p-1 sequential ppermute hops and
# PowerSGD's per-step Gram-Schmidt are NOT free — a byte count alone
# overstates both).
COMPRESSOR_FACTOR = {
    "none": 1.0,
    "fp16": 0.5, "bf16": 0.5,
    "fp16_ef": 0.5, "bf16_ef": 0.5,
    # int8_ef quantizes to int8 levels but its psum rides an fp16 wire;
    # int8_ring is the true-int8-wire ring.
    "int8_ef": 0.5,
    "int8_ring": 0.25,
    # (n + m)·r vs n·m bytes, ~2r/sqrt(total): a static stand-in for a
    # data-dependent ratio; at BERT-scale buckets it is ≲ 0.01.
    "powersgd": 0.02,
}

# Activation bytes per element on the wire/in HBM (bf16 activations).
_ACT_BYTES = 2.0

# The tied-table naming the pipeline vocab rules key on
# (parallel_builders.PIPELINE_VOCAB_RULES): used to identify the
# unembedding among replicated shared variables when no partitioner
# spec marks it.
_VOCAB_NAME_RE = re.compile(r"(^|/)embedding$")

# Link-pricing constants for the overlap-aware model (the pipeline/TP
# path): effective per-link bandwidth, per-hop launch latency, and the
# matmul efficiency that converts chunk FLOPs into the compute time a
# hop can hide behind.  Analytic defaults come from the chip table
# (resource.ChipSpec) / COLLECTIVE_ALPHA; a ``"link"`` section in
# calibration.json (or an explicit ``CostModel(link_profile=...)``)
# replaces them with measured values.  Keys: ``ici_gbps``,
# ``hop_alpha_s``, ``mxu_efficiency`` — and, for the cross-slice (DCN)
# level of the hierarchical network model, ``dcn_gbps`` /
# ``dcn_alpha_s`` (merged from calibration exactly like ``ici_gbps``;
# the drift report proposes both).
LINK_PROFILE: dict = {}

# Fraction of peak matmul throughput a pipeline-stage chunk sustains —
# only the *ratio* of chunk-compute to hop-transfer time matters for
# ranking overlapped vs blocking plans.
_DEFAULT_MXU_EFFICIENCY = 0.4

# Per-collective precision pricing (the Strategy IR policy, PR 8).
# Wire factors per boundary mechanism: a *summing* collective carries
# int8 levels on an fp16 wire (kernel/quantize.py), so int8 and bf16
# both halve psum bytes; a *gather* never sums and rides a TRUE s8
# wire — the full 4x.
PSUM_WIRE_FACTOR = {"fp32": 1.0, "bf16": 0.5, "int8": 0.5}
GATHER_WIRE_FACTOR = {"fp32": 1.0, "bf16": 0.5, "int8": 0.25}

# Quantize/dequantize compute per payload element (seconds) — the term
# byte counts miss: narrowing only wins when the bytes saved outweigh
# these passes.  Analytic defaults (a cast is one memory-bound pass;
# int8 adds the abs-max reduction and round/clip); a ``"quant"`` section
# in calibration.json (written by ``tools/calibrate_compressors.py``)
# replaces them with measured values, exactly like the ``"link"``
# constants.
QUANT_PROFILE: dict = {
    "bf16_s_per_elem": 2e-11,
    "int8_s_per_elem": 1e-10,
}

# Fused-kernel tier pricing (the Strategy IR ``kernel`` slot, PR 13) —
# analytic defaults; a ``"kernel"`` section in calibration.json
# (written mechanically from ``tools/flash_crossover.py --decode`` /
# ``bench.py flash`` measurements) replaces them like ``"link"`` and
# ``"quant"``:
#
# * ``quant_ring_wire_factor`` — the EQuARX ring's TRUE-s8 wire vs the
#   composed int8 psum's fp16-levels wire (0.25 vs PSUM_WIRE_FACTOR's
#   0.5): the ring halves the bytes again.
# * ``quant_ring_qdq_factor`` — the q/dq passes the per-hop fused
#   requantization costs relative to the composed sandwich's one
#   quantize + one dequantize (each hop re-quantizes, so ~2x at tp=2
#   and growing with hops; the fused VMEM pass keeps it near the byte
#   count rather than 2(n-1) full passes).
# * ``fused_hop_alpha_s`` — per-hop launch overhead of the fused
#   collective-matmul ring step (one kernel issues the hop's
#   accumulate+matmul, and on silicon its RDMA): the composed ring
#   pays the full ``hop_alpha_s`` per hop.
# * ``flash_decode_crossover_len`` / ``flash_decode_speedup`` /
#   ``flash_decode_short_penalty`` — the decode einsum-vs-flash
#   crossover: past the crossover length flash divides the attention
#   term by the measured speedup; below it the kernel's fixed overhead
#   *loses* to einsum by the penalty factor (the round-3 verdict's
#   measured shape), so the search elects flash exactly when the cache
#   length favors it.
KERNEL_PROFILE: dict = {
    "quant_ring_wire_factor": 0.25,
    "quant_ring_qdq_factor": 2.0,
    "fused_hop_alpha_s": 1e-6,
    "flash_decode_crossover_len": 1024,
    "flash_decode_speedup": 1.6,
    "flash_decode_short_penalty": 0.8,
    # Paged-KV table indirection: the attention term's multiplier under
    # kv_layout="paged" (block-table gathers / per-block DMA setup vs
    # the dense contiguous lane).  Strictly > 1 so dense wins whenever
    # the request-length distribution gives paged no capacity edge —
    # the both-ways election contract.
    "paged_attention_overhead": 1.05,
    # Throughput-ladder constants (PR 16), calibratable like the rest:
    #
    # * ``flash_prefill_crossover_chunk`` / ``flash_prefill_speedup`` /
    #   ``flash_prefill_short_penalty`` — the chunked-prefill
    #   einsum-vs-flash crossover over CHUNK size (``tools/
    #   flash_crossover.py --prefill`` measures it): wide chunks
    #   amortize the kernel's scalar-prefetch setup, narrow ones lose
    #   to the composed gather path.
    # * ``prefix_caching_overhead`` — hash/admission bookkeeping plus
    #   the occasional copy-on-write, as an attention-term multiplier.
    #   Strictly > 1 so a traffic mix with NO shared prefixes elects
    #   plain paged — the hit rate must pay for the knob both ways.
    # * ``spec_draft_flops_frac`` — draft-model cost per proposed token
    #   relative to a target decode step (a ~7x-smaller draft).
    # * ``spec_marginal_token_cost`` — the verify window's marginal
    #   cost per extra token relative to a full decode step: the k+1
    #   tokens share one weights read and one dispatch, so each extra
    #   token costs well under a step (the whole point of verifying a
    #   window at once).
    # * ``spec_acceptance_default`` — the acceptance rate assumed when
    #   the caller has not measured one (``bench.py serve
    #   --speculative`` measures; the recipe in ROADMAP.md records it).
    "flash_prefill_crossover_chunk": 128,
    "flash_prefill_speedup": 1.5,
    "flash_prefill_short_penalty": 0.85,
    "prefix_caching_overhead": 1.02,
    "spec_draft_flops_frac": 0.15,
    "spec_marginal_token_cost": 0.35,
    "spec_acceptance_default": 0.7,
    # MoE dispatch/combine ring (``a2a_ring``, the quant_ring
    # generalized from reduce to permute).  Unlike the reduce ring, the
    # composed int8 all_to_all ALREADY ships true s8 (a permute never
    # sums, so there is no fp16-levels headroom wire to beat) — the
    # analytic wire factor therefore matches GATHER_WIRE_FACTOR's int8
    # 0.25 and the election crossover lives in the q/dq term: the fused
    # hop quantizes/dequantizes in VMEM (``a2a_ring_qdq_factor`` < 1 vs
    # the composed sandwich's HBM-shaped converts) but pays 2(n-1) hop
    # launches per dispatch+combine pair where the monolithic collective
    # pays 2 — so the ring wins exactly when the payload is large enough
    # that the q/dq saving clears the extra alphas (``bench.py moe``
    # measures both on silicon).
    "a2a_ring_wire_factor": 0.25,
    "a2a_ring_qdq_factor": 0.5,
}

# The grad slot's realization: which EF compressor a bf16/int8 gradient
# policy elects (mirrors lower_pipeline_ir / build_replicated_spmd).
_GRAD_PRECISION_COMPRESSOR = {"bf16": "bf16_ef", "int8": "int8_ef"}


def _qdq_s_per_elem(profile: dict, precision: str) -> float:
    if precision == "fp32":
        return 0.0
    return float(profile.get(f"{precision}_s_per_elem",
                             QUANT_PROFILE.get(f"{precision}_s_per_elem",
                                               0.0)))


def load_calibration(path: Optional[str] = None) -> dict:
    """Merge measured compressor factors into :data:`COMPRESSOR_FACTOR`.

    ``tools/calibrate_compressors.py`` times each compressor's allreduce
    against the uncompressed one on the real chip and writes
    ``{"compressor_factor": {name: measured_ratio}, ...}``; loading it
    turns the cost model's byte-count guesses into wall-clock ratios.
    An optional ``"link"`` section (``ici_gbps`` / ``hop_alpha_s`` /
    ``mxu_efficiency``) merges into :data:`LINK_PROFILE` the same way —
    the constants the overlap-aware pipeline pricing uses in place of
    the chip-table defaults.  Default path: ``calibration.json`` at the
    repo root, then the ``AUTODIST_TPU_CALIBRATION`` env var.  Returns
    the compressor factors applied (empty when no file exists).
    """
    import json
    import os

    candidates = [path] if path else [
        os.environ.get("AUTODIST_TPU_CALIBRATION", ""),
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), "calibration.json"),
    ]
    for p in candidates:
        if p and os.path.exists(p):
            with open(p) as f:
                data = json.load(f)
            meta = data.get("meta")
            backend = meta.get("backend") if isinstance(meta, dict) else None
            if path is None and backend == "cpu":
                # A dev-smoke artifact (tools/calibrate_compressors.py on
                # a CPU mesh) measures compute overhead with no real wire
                # and would silently skew accelerator planning; auto-load
                # skips it.  An explicit ``path`` argument overrides.
                from autodist_tpu.utils import logging
                logging.warning(
                    "ignoring CPU-provenance calibration file %s "
                    "(pass the path explicitly to force)", p)
                continue
            factors = dict(data.get("compressor_factor", {}))
            COMPRESSOR_FACTOR.update(factors)
            LINK_PROFILE.update(dict(data.get("link", {})))
            # Measured quantize/dequantize per-element costs (the
            # ``"quant"`` section ``tools/calibrate_compressors.py``
            # emits) replace the analytic q/dq defaults the same way.
            QUANT_PROFILE.update(dict(data.get("quant", {})))
            # Measured fused-kernel constants (``tools/flash_crossover
            # .py --decode`` / ``bench.py flash``) replace the kernel
            # tier's analytic defaults the same way.
            KERNEL_PROFILE.update(dict(data.get("kernel", {})))
            return factors
    return {}


_calibration_loaded = False


def _ensure_calibration():
    global _calibration_loaded
    if not _calibration_loaded:
        _calibration_loaded = True
        applied = load_calibration()
        if applied:
            from autodist_tpu.utils import logging
            logging.info("cost model using measured compressor factors: %s",
                         applied)


class SpecMeshMismatch(ValueError):
    """A GSPMD sharding spec names a mesh axis the topology lacks —
    the candidate is invalid for this resource spec (AutoStrategy skips
    it), as opposed to a genuine cost-model error."""


@dataclasses.dataclass
class StrategyCost:
    """Breakdown for one (trainable, strategy, topology) triple."""

    comm_bytes: float          # total collective payload per step
    comm_time_s: float         # bandwidth term + per-collective latency
    num_collectives: int
    mem_bytes_per_device: float
    feasible: bool             # fits in HBM (with headroom)
    # Exposed (un-hidden) time of latency-hiding decompositions, already
    # included in comm_time_s; broken out so the telemetry drift report
    # can show comm vs exposed-overlap per term.
    overlap_time_s: float = 0.0
    # Peak loss-head logits buffer (pipeline lowering, priced only with
    # a tokens hint), already included in mem_bytes_per_device; broken
    # out because it is the term vocab parallelism divides by tp — the
    # drift report joins it against measured HBM and telemetry gauges it.
    peak_logits_bytes: float = 0.0
    # Predicted per-device parameter-storage and gradient bytes after
    # sharding (parallel lowerings), already included in
    # mem_bytes_per_device; broken out like peak_logits_bytes because
    # they are the terms the ZeRO stages divide — stage 2 shards the
    # gradient term by the data-replica count, stage 3 the parameter
    # term too — so the drift report can attribute an HBM delta between
    # stages to the right term.
    param_shard_bytes: float = 0.0
    grad_shard_bytes: float = 0.0
    # Per-collective precision policy terms: bytes the narrowed wire
    # saves vs the same plan at fp32 (already reflected in comm_bytes/
    # comm_time_s — broken out so the drift report can show the
    # predicted bytes-on-wire delta), and the quantize/dequantize
    # compute charged against it (also already inside comm_time_s): a
    # narrowed candidate outranks fp32 exactly when saved wire time
    # outweighs this term.
    wire_bytes_saved: float = 0.0
    quant_dq_time_s: float = 0.0
    # Per-level breakdown of the hierarchical network model: the bytes
    # and time of the cross-slice (DCN) exchanges, already included in
    # comm_bytes / comm_time_s.  A collective spanning the dcn axis
    # decomposes into intra-slice reduce + cross-slice exchange +
    # intra-slice broadcast (arxiv 2110.10548); this is the cross-slice
    # term, priced at the dcn_gbps/dcn_alpha_s constants — broken out
    # so the drift report can fit dcn_gbps independently of ici_gbps
    # and the search report can show per-level comm per candidate.
    dcn_bytes: float = 0.0
    dcn_time_s: float = 0.0
    # Expert-parallel all_to_all term (MoE dispatch + combine, forward
    # and backward), already included in comm_bytes / comm_time_s (or
    # the dcn terms when the expert axis spans slices) — broken out so
    # the drift report can join the predicted dispatch/combine wire
    # against the measured step and the search report can show the
    # placement trade (within-slice ICI vs across-DCN) per candidate.
    a2a_bytes: float = 0.0
    a2a_time_s: float = 0.0

    @property
    def score(self) -> float:
        """Lower is better; infeasible plans rank last."""
        return self.comm_time_s if self.feasible else math.inf


@dataclasses.dataclass
class DecodeCost:
    """Per-token decode latency breakdown for one serving config — the
    cost model's second objective (latency under load, not training
    step time).  ``token_time_s = compute + comm``: raising the tp
    degree divides the per-device matmul work but adds the per-layer
    Megatron boundary all-reduces, so tp=2 ranks above tp=1 exactly
    when the per-token comm cost is under the compute win."""

    token_time_s: float        # comm + compute, per decoded token
    comm_time_s: float         # model-axis boundary collectives
    compute_time_s: float      # per-device matmul passes
    kv_bytes_per_device: float     # the TP-sharded cache's footprint
    mem_bytes_per_device: float    # params (sharded) + KV cache
    feasible: bool
    tensor_parallel: int = 1
    vocab_parallel: bool = False
    # Attention-over-cache share of compute_time_s (already included):
    # the term the flash_decode kernel divides by its calibrated
    # speedup past the crossover length — broken out so the election
    # report can show why flash won (or lost) at this cache length.
    attn_time_s: float = 0.0
    kernel: tuple = ()
    # The capacity side of the serving objective (PR 14): the KV-cache
    # layout this config serves with, and the expected number of
    # concurrent requests the post-params HBM supports under the
    # request-length distribution — dense reserves a full max_len lane
    # per request; paged reserves only the mean length rounded up to a
    # block, so length variance below max_len multiplies capacity.
    kv_layout: str = "dense"
    request_capacity: float = 0.0
    # The fleet shape (PR 15): dp replicas of the tp group behind one
    # router.  Replicas multiply capacity without touching per-token
    # latency; a fleet spanning slices pays the router's cross-slice
    # dispatch hop (priced at DCN constants, amortized per token) —
    # replicas ride DCN, tp never does (the serving ADT060 analog,
    # rejected at pricing time).
    replicas: int = 1
    dispatch_time_s: float = 0.0
    # The throughput ladder (PR 16): which rungs this config runs, and
    # the traffic facts they were priced under.  ``spec_acceptance`` is
    # the acceptance rate the speculative term used (0 when off);
    # ``prefix_hit_rate`` the shared-prefix block fraction the capacity
    # term used (0 when off).
    prefill_chunk: Optional[int] = None
    prefix_caching: bool = False
    prefix_hit_rate: float = 0.0
    speculative: Optional[int] = None
    spec_acceptance: float = 0.0
    # Disaggregated serving (PR 17): the prefill/decode pool split and
    # its per-request stage times.  ``prefill_time_s`` is one request's
    # prompt pass on one prefill replica; ``decode_time_s`` its decode
    # tail on one decode replica; ``handoff_time_s`` the KV prefix
    # transfer between them (ICI when the pools share a slice, DCN when
    # the split spans slices) — the term that makes a split with too
    # little decode capacity pay for every handoff it absorbs.
    prefill_replicas: int = 0
    decode_replicas: int = 0
    prefill_time_s: float = 0.0
    decode_time_s: float = 0.0
    handoff_time_s: float = 0.0

    @property
    def score(self) -> float:
        """Lower is better; infeasible configs rank last."""
        return self.token_time_s if self.feasible else math.inf

    @property
    def serve_score(self) -> float:
        """The capacity-aware objective: per-token latency divided by
        the concurrent requests the HBM carries — ~1/aggregate
        throughput under load.  Paged outranks dense on it exactly when
        the capacity multiplier beats the table-indirection overhead
        (i.e. when length variance makes dense reservation wasteful);
        at mean length == max_len the capacities tie and the overhead
        makes dense win — pinned both ways."""
        if not self.feasible or self.request_capacity <= 0:
            return math.inf
        return self.token_time_s / self.request_capacity

    @property
    def fleet_score(self) -> float:
        """The fleet objective: per-token latency (+ the amortized
        cross-slice dispatch hop) over the requests the WHOLE fleet
        carries (``replicas × request_capacity``) — ~1/aggregate fleet
        throughput for the traffic mix.  Elects the
        (replicas × tp × kv_layout) shape: replicas multiply the
        denominator for free until the device budget binds, tp trades
        per-token comm against the compute win within a slice, and the
        kv layout moves ``request_capacity`` exactly as in
        :attr:`serve_score`."""
        if not self.feasible or self.request_capacity <= 0:
            return math.inf
        return (self.token_time_s + self.dispatch_time_s) \
            / (max(self.replicas, 1) * self.request_capacity)

    @property
    def disagg_score(self) -> float:
        """The disaggregation objective: a request pipeline's
        bottleneck stage time — prefill work spread over the prefill
        pool vs (handoff + decode) work spread over the decode pool.
        Lower is better (~1/aggregate request throughput at the
        bottleneck).  A prefill-bound mix (long prompts, short decode
        tails) elects a split with more prefill replicas; a
        decode-bound mix the reverse — and every handoff the decode
        pool absorbs is charged to ITS stage, so starving decode never
        looks free (both directions pinned)."""
        if not self.feasible or self.prefill_replicas < 1 \
                or self.decode_replicas < 1:
            return math.inf
        prefill = self.prefill_time_s / self.prefill_replicas
        decode = (self.decode_time_s + self.handoff_time_s) \
            / self.decode_replicas
        return max(prefill, decode)


class CostModel:
    """Scores strategies against a resource spec's topology constants."""

    def __init__(self, resource_spec: ResourceSpec, *,
                 sparsity_fraction: float = 0.05,
                 opt_state_multiplier: float = 2.0,
                 hbm_headroom: float = 0.6,
                 tokens_per_step: Optional[int] = None,
                 act_bytes_per_token: Optional[float] = None,
                 link_profile: Optional[dict] = None,
                 quant_profile: Optional[dict] = None,
                 kernel_profile: Optional[dict] = None):
        """``sparsity_fraction``: expected fraction of embedding rows
        touched per step (drives the sparse gather/scatter volume).
        ``opt_state_multiplier``: optimizer slots per parameter byte
        (2.0 = adam m+v).  ``hbm_headroom``: fraction of HBM the model
        state may occupy (the rest is activations/workspace).
        ``tokens_per_step`` / ``act_bytes_per_token``: activation-shape
        hints (override the trainable's own) enabling activation-
        collective and activation-memory pricing — see
        :class:`~autodist_tpu.capture.Trainable`.
        ``link_profile``: per-link constants for the overlap-aware
        pricing (keys ``ici_gbps``/``hop_alpha_s``/``mxu_efficiency``);
        overrides the calibration-file :data:`LINK_PROFILE`, which
        overrides the chip-table defaults.
        ``quant_profile``: quantize/dequantize per-element costs for the
        precision-policy pricing (keys ``bf16_s_per_elem`` /
        ``int8_s_per_elem``); same override chain as ``link_profile``
        against :data:`QUANT_PROFILE`.
        ``kernel_profile``: fused-kernel tier constants (see
        :data:`KERNEL_PROFILE`); same override chain."""
        _ensure_calibration()
        self.spec = resource_spec
        self.chip = resource_spec.chip
        self.sparsity_fraction = sparsity_fraction
        self.opt_state_multiplier = opt_state_multiplier
        self.hbm_headroom = hbm_headroom
        self.tokens_per_step = tokens_per_step
        self.act_bytes_per_token = act_bytes_per_token
        self.link_profile = dict(LINK_PROFILE)
        if link_profile:
            self.link_profile.update(link_profile)
        self.quant_profile = dict(QUANT_PROFILE)
        if quant_profile:
            self.quant_profile.update(quant_profile)
        self.kernel_profile = dict(KERNEL_PROFILE)
        if kernel_profile:
            self.kernel_profile.update(kernel_profile)

    # ------------------------------------------------------------------ #
    def with_spec(self, resource_spec: ResourceSpec) -> "CostModel":
        """The same pricing constants bound to a different resource
        spec — how the topology-aware search prices each candidate
        against its *own* mesh factorization (the mesh is read from
        ``self.spec``, so pricing a re-factored candidate with the
        original model would silently ignore its pp/tp/dcn degrees)."""
        return CostModel(resource_spec,
                         sparsity_fraction=self.sparsity_fraction,
                         opt_state_multiplier=self.opt_state_multiplier,
                         hbm_headroom=self.hbm_headroom,
                         tokens_per_step=self.tokens_per_step,
                         act_bytes_per_token=self.act_bytes_per_token,
                         link_profile=self.link_profile,
                         quant_profile=self.quant_profile,
                         kernel_profile=self.kernel_profile)

    def _dcn_link(self) -> tuple[float, float]:
        """(bytes/s, launch alpha) of the cross-slice DCN level —
        calibrated ``"link"`` ``dcn_*`` constants over the chip-table
        defaults, the same override chain as ``ici_gbps``."""
        bw = float(self.link_profile.get(
            "dcn_gbps", getattr(self.chip, "dcn_gbps", 5.0))) * 1e9
        alpha = float(self.link_profile.get(
            "dcn_alpha_s", getattr(self.chip, "dcn_alpha_s", 1e-4)))
        return bw, alpha

    def _dcn_degree(self, mesh: dict) -> int:
        """Slice count the replica sync crosses: the mesh's ``dcn``
        axis — or, when an explicit mesh omits it on a declared
        multi-slice topology, ``num_slices`` (the data axis still
        physically crosses slices whether or not the user named the
        level; pricing it flat would be exactly the mispricing the
        hierarchical model exists to fix)."""
        from autodist_tpu import const

        n_dcn = max(int(mesh.get(const.DCN_AXIS, 1) or 1), 1)
        if n_dcn == 1:
            n_dcn = max(int(getattr(self.spec, "num_slices", 1) or 1), 1)
        return n_dcn

    @staticmethod
    def _split_ring(n_sync: int, n_dcn: int) -> tuple[float, float]:
        """Hierarchical ring factors for a replica-sync group of
        ``n_sync`` members of which ``n_dcn`` cross slices: intra-slice
        reduce-scatter + broadcast at ICI rates plus a cross-slice
        exchange of the intra-slice shard at DCN rates (the two-level
        reduction shape of arxiv 2110.10548).  Returns ``(ici_factor,
        dcn_factor)`` — multiply each by the payload bytes and price at
        its level's bandwidth.  Pure-ICI groups (``n_dcn == 1``) keep
        today's exact single-level factor, so single-slice pricing is
        byte-identical to the flat model."""
        def ring(k: int) -> float:
            return 2.0 * (k - 1) / k if k > 1 else 0.0

        if n_dcn <= 1 or n_sync % n_dcn:
            return ring(n_sync), 0.0
        g = n_sync // n_dcn
        return ring(g), ring(n_dcn) / max(g, 1)

    def _hints(self, trainable) -> tuple[Optional[int], Optional[float]]:
        tokens = self.tokens_per_step if self.tokens_per_step is not None \
            else getattr(trainable, "tokens_per_step", None)
        act = self.act_bytes_per_token if self.act_bytes_per_token is not None \
            else getattr(trainable, "act_bytes_per_token", None)
        return tokens, act

    @staticmethod
    def _hidden_dim(trainable) -> int:
        """Activation width estimate: the largest 'matmul contraction'
        dim, i.e. max over rank>=2 variables of their smallest dim
        (embedding [V, H] and square projections [H, H] both yield H)."""
        dims = [min(v.shape) for v in trainable.var_infos()
                if len(v.shape) >= 2]
        return max(dims) if dims else 1

    @staticmethod
    def _gspmd_shards(node, mesh) -> tuple[int, bool]:
        """(device count the node's spec shards one variable over, whether
        the data axis is among its sharding axes); raises
        :class:`SpecMeshMismatch` when the spec names an axis the
        topology lacks."""
        from autodist_tpu import const

        part = node.partitioner
        shards, uses_data = 1, False
        spec = part.spec if part is not None and part.spec is not None \
            else None
        if spec is None:
            if part is not None and part.num_shards > 1:
                shards = part.num_shards
            return shards, uses_data
        for axis in spec:
            for a in (axis if isinstance(axis, (list, tuple)) else [axis]):
                if a is None:
                    continue
                if a not in mesh:
                    raise SpecMeshMismatch(
                        f"{node.var_name}: spec names mesh axis {a!r} "
                        f"absent from topology {mesh}")
                shards *= mesh[a]
                uses_data |= a == const.DATA_AXIS
        return shards, uses_data

    def _gspmd_cost(self, trainable, strategy) -> StrategyCost:
        """Pricing for gspmd-lowered strategies.

        * data-axis-sharded (FSDP layout): state at 1/shards; per step the
          grads reduce-scatter and the params all-gather over the data
          axis — ring-equivalent *full* tensor volume, same as the
          collective path's sharded branch.
        * model-axis-sharded (TP): each device permanently owns its
          slice; only the slice's gradient syncs over the data axis.
          With a ``tokens_per_step`` hint, activation collectives on the
          model axis are priced Megatron-style: each *row-parallel*
          variable (dim 0 sharded on the model axis, e.g. the out-proj /
          mlp-down matmul) implies one fwd activation allreduce of
          ``tokens x out_features`` over its TP group, mirrored in the
          backward at its column-parallel partner — 2x the fwd volume,
          charged on the row var to avoid double counting.  Without the
          hint they appear in the per-collective latency term only.
        * replicated: the DP grad allreduce.
        """
        from autodist_tpu import const

        mesh = self.spec.resolved_mesh_shape()
        n = max(strategy.graph_config.replicas, 1)
        infos = {v.name: v for v in trainable.var_infos()}
        # The replica group spans data x dcn; dcn-crossing sync
        # decomposes per level (intra-slice at ICI + cross-slice shard
        # exchange at DCN) instead of pricing everything at ici_gbps.
        ring, dcn_factor = self._split_ring(n, self._dcn_degree(mesh))
        bw_dcn, dcn_alpha = self._dcn_link()
        dcn_bytes = dcn_time = 0.0
        dcn_colls = 0
        total_devices = 1
        for v in mesh.values():
            total_devices *= v
        tokens, act_hint = self._hints(trainable)
        m = mesh.get(const.MODEL_AXIS, 1)
        ring_m = 2.0 * (m - 1) / m if m > 1 else 0.0
        tokens_per_group = (tokens / n) if tokens else 0.0
        comm_bytes = mem_bytes = 0.0
        num_collectives = 0
        # Iterate var_infos: variables a hand-edited strategy omitted a
        # node config for still train replicated — price them too.
        nodes_by_name = {nc.var_name: nc for nc in strategy.node_configs}
        _no_node = type("_NoNode", (), {"partitioner": None,
                                        "synchronizer": None})()
        for info in infos.values():
            node = nodes_by_name.get(info.name, _no_node)
            bytes_ = float(info.byte_size)
            shards, uses_data = self._gspmd_shards(node, mesh)
            is_ps = getattr(node.synchronizer, "kind", "") == "ps"
            if shards > 1:
                # PS on a TP-sharded var: kernel/gspmd.py additionally
                # shards the state's dim 0 over the data axes when it
                # divides — a further 1/n on the opt term.
                opt_div = shards
                if is_ps and n > 1 and info.shape \
                        and info.shape[0] % (shards * n) == 0:
                    opt_div = shards * n
                mem_bytes += bytes_ * 2.0 / shards \
                    + bytes_ * self.opt_state_multiplier / opt_div
                payload = bytes_ if uses_data else bytes_ / shards
                comm_bytes += ring * payload
                num_collectives += 2
                if dcn_factor:
                    dcn_bytes += dcn_factor * payload
                    dcn_colls += 2
                # Row-parallel on the model axis: fwd+bwd activation
                # allreduce of tokens x shape[1] over the TP group.
                part = node.partitioner
                spec0 = part.spec[0] if part is not None \
                    and part.spec else None
                row_parallel = (
                    ring_m > 0.0 and tokens and len(info.shape) >= 2
                    and (const.MODEL_AXIS == spec0
                         or (isinstance(spec0, (list, tuple))
                             and const.MODEL_AXIS in spec0)))
                if row_parallel:
                    # Output width = the last (non-contracted) dim: H for
                    # out-proj [heads, head_dim, H], wo [mlp, H], and the
                    # vocab-sharded embedding [V, H] (partial-sum lookup).
                    comm_bytes += 2.0 * ring_m * tokens_per_group \
                        * info.shape[-1] * _ACT_BYTES
                    num_collectives += 2
            else:
                # PS(sync=True) under gspmd = GSPMD ZeRO-1 (opt state's
                # leading dim shards over the data axes, kernel/gspmd.py);
                # reduce-scatter + all-gather replace the allreduce at
                # ring-equivalent volume.
                opt_div = n if (is_ps and n > 1) else 1
                mem_bytes += bytes_ * 2.0 \
                    + bytes_ * self.opt_state_multiplier / opt_div
                comm_bytes += ring * bytes_
                num_collectives += 2 if opt_div > 1 else 1
                if dcn_factor:
                    dcn_bytes += dcn_factor * bytes_
                    dcn_colls += 2 if opt_div > 1 else 1
        if tokens and act_hint:
            # Activations divide by the number of batch shards (the data
            # axis), not all devices: a TP group processes the same
            # tokens on every member (the residual stream is unsharded —
            # conservative; some TP intermediates do shard).
            mem_bytes += act_hint * tokens / n
        bw = self.chip.ici_gbps * 1e9
        comm_time = comm_bytes / bw \
            + COLLECTIVE_ALPHA * num_collectives * (1 if total_devices > 1
                                                    else 0)
        if dcn_bytes:
            dcn_time = dcn_bytes / bw_dcn + dcn_alpha * dcn_colls
            comm_time += dcn_time
        hbm = self.chip.hbm_gb * 1e9 * self.hbm_headroom
        return StrategyCost(comm_bytes=comm_bytes + dcn_bytes,
                            comm_time_s=comm_time,
                            num_collectives=num_collectives + dcn_colls,
                            mem_bytes_per_device=mem_bytes,
                            feasible=mem_bytes <= hbm,
                            dcn_bytes=dcn_bytes, dcn_time_s=dcn_time)

    def _parallel_cost(self, trainable, strategy) -> StrategyCost:
        """Pricing for the sequence / pipeline / expert lowerings.

        Uses the activation hints where collective volume is activation-
        shaped (ring-attention k/v rotation, pipeline activation hops,
        MoE all_to_all); without hints those appear only in the latency
        term — same documented degradation as TP.
        """
        from autodist_tpu import const

        mesh = self.spec.resolved_mesh_shape()
        kind = strategy.graph_config.lowering
        tokens, act_hint = self._hints(trainable)
        hidden = self._hidden_dim(trainable)
        n_data = mesh.get(const.DATA_AXIS, 1) * mesh.get(const.DCN_AXIS, 1)
        total_devices = 1
        for v in mesh.values():
            total_devices *= v
        infos = list(trainable.var_infos())
        opt_mult = self.opt_state_multiplier
        comm = 0.0
        colls = 0
        mem = 0.0
        tokens_per_dev = (tokens / total_devices) if tokens else 0.0
        # Link constants for the overlap-aware pricing (and this branch's
        # final bytes→time conversion, so overlapped and blocking
        # variants are ranked against ONE set of constants): calibrated
        # values beat the chip table.
        bw_link = float(self.link_profile.get(
            "ici_gbps", self.chip.ici_gbps)) * 1e9
        hop_alpha = float(self.link_profile.get(
            "hop_alpha_s", COLLECTIVE_ALPHA))
        mxu_eff = float(self.link_profile.get(
            "mxu_efficiency", _DEFAULT_MXU_EFFICIENCY))
        flops_rate = self.chip.peak_bf16_tflops * 1e12 * mxu_eff
        # Hierarchical network model: any sync group spanning the dcn
        # axis decomposes into an intra-slice part (priced through the
        # ICI `comm` pool below) and a cross-slice shard exchange priced
        # at the DCN constants here — never at ici_gbps.
        n_dcn = self._dcn_degree(mesh)
        bw_dcn, dcn_alpha = self._dcn_link()
        dcn_b = 0.0      # cross-slice wire bytes
        dcn_t = 0.0      # cross-slice time, launch alphas included
        dcn_colls = 0
        # Overlapped collectives are priced in *seconds* directly (their
        # per-hop alphas included), with their wire bytes and launch
        # counts reported but not re-charged through the bytes/bw + alpha
        # terms below.
        overlap_s = 0.0
        hidden_bytes = 0.0
        extra_colls = 0
        peak_logits = 0.0
        # Expert dispatch/combine breakout (bytes ride the comm or dcn
        # pools above; the time share is re-derived for the report).
        a2a_b = 0.0
        a2a_t = 0.0

        # Per-collective precision policy (PR 8): wire factors shrink
        # each policied boundary's bytes; the q/dq compute term charges
        # the quantize/dequantize passes against the saving — a narrowed
        # plan outranks fp32 exactly when the saved wire time exceeds it.
        from autodist_tpu.strategy.ir import (normalize_kernel,
                                              normalize_precision)
        policy = normalize_precision(strategy.graph_config.precision)
        # Fused-kernel tier (PR 13): the quant_ring kernel trades the
        # composed int8 psum's fp16-levels wire for TRUE s8 at the cost
        # of per-hop requantization; the fused collective-matmul ring
        # shrinks the per-hop launch overhead.  Priced from the
        # calibratable KERNEL_PROFILE so the search elects each kernel
        # exactly when its crossover favors it.
        kern_cfg = normalize_kernel(
            getattr(strategy.graph_config, "kernel", None))
        ring_kernel = "quant_ring" in kern_cfg
        fused_mm = "collective_matmul" in kern_cfg
        kp = self.kernel_profile
        tp_prec = policy.get("tp_psum", "fp32")
        stats_prec = policy.get("vocab_stats", "fp32")
        z3_prec = policy.get("zero3_gather", "fp32")
        grad_prec = policy.get("grad", "fp32")
        qdq_s = 0.0
        saved_bytes = 0.0

        def qdq(elems: float, prec: str) -> float:
            return elems * _qdq_s_per_elem(self.quant_profile, prec)

        def ring(k: int) -> float:
            return 2.0 * (k - 1) / k if k > 1 else 0.0

        def split_ring(n_sync: int) -> tuple[float, float]:
            """(ici factor, dcn factor) of a replica sync group — see
            :meth:`_split_ring`; the dcn factor's bytes are priced at
            the DCN constants via :func:`dcn_sync` below."""
            return self._split_ring(n_sync, n_dcn)

        def dcn_sync(node, full_bytes: float, launches: int = 1):
            """One grad-sync boundary's cross-slice exchange: wire
            bytes after the node's compressor/grad-policy factor,
            priced at DCN bandwidth plus launch alphas."""
            nonlocal dcn_b, dcn_t, dcn_colls
            b = grad_bytes(node, full_bytes)
            dcn_b += b
            dcn_t += b / bw_dcn + dcn_alpha * launches
            dcn_colls += launches

        # Iterate var_infos (not node_configs): a hand-edited strategy
        # omitting node configs for some variables still trains them
        # (the lowerings default missing nodes to plain AllReduce), so
        # the pricing must cover every variable.
        nodes_by_name = {nc.var_name: nc for nc in strategy.node_configs}

        def node_factor(node) -> float:
            """Compressor wire factor (AllReduce nodes only; PS reduces
            at full precision).  A non-fp32 ``grad`` precision slot
            elects the matching EF compressor on every AllReduce node
            without an explicit one — exactly what the lowerings do."""
            sync = getattr(node, "synchronizer", None)
            if sync is None or getattr(sync, "kind", "allreduce") == "ps":
                return 1.0
            comp = (getattr(sync, "compressor", "none") or "none") \
                .partition(":")[0]
            if comp == "none" and grad_prec != "fp32":
                comp = _GRAD_PRECISION_COMPRESSOR[grad_prec]
            return COMPRESSOR_FACTOR.get(comp, 1.0)

        def grad_bytes(node, full_bytes: float) -> float:
            """Grad-sync bytes after the compressor/grad-policy factor,
            recording the policy's saving (not an explicit compressor's
            — that narrowing predates the policy and has no fp32
            sibling to diff against)."""
            nonlocal saved_bytes
            scaled = full_bytes * node_factor(node)
            sync = getattr(node, "synchronizer", None)
            if (grad_prec != "fp32" and sync is not None
                    and getattr(sync, "kind", "allreduce") != "ps"
                    and (getattr(sync, "compressor", "none") or "none")
                    == "none"):
                saved_bytes += full_bytes - scaled
            return scaled

        def node_is_ps(node) -> bool:
            return getattr(getattr(node, "synchronizer", None),
                           "kind", "") == "ps"

        def zero_divisors(node, group: int):
            """(stage, param_div, grad_div, opt_div) of a PS node over a
            ``group``-device replica set: stage 1 shards optimizer state,
            stage 2 additionally accounts the gradients sharded (same
            reduce-scatter program), stage 3 stores the parameters
            sharded too (all-gathered on demand per layer)."""
            if not node_is_ps(node) or group <= 1:
                return 0, 1, 1, 1
            stage = int(getattr(getattr(node, "synchronizer", None),
                                "zero_stage", 1) or 1)
            return (stage, group if stage >= 3 else 1,
                    group if stage >= 2 else 1, group)

        accum = max(int(strategy.graph_config.accum_steps or 1), 1)
        param_b = grad_b = 0.0   # per-device param/grad bytes (sharded)

        if kind == "sequence":
            S = mesh.get(const.SEQ_AXIS, 1)
            n_sync = n_data * S
            # params replicated; per-var sync over data x seq.  PS ->
            # ZeRO (parallel/_spmd.py): same ring-equivalent volume, opt
            # state at 1/n_sync (stage 2 accounts grads sharded, stage 3
            # stores params sharded); compressors scale the wire bytes.
            for info in infos:
                node = nodes_by_name.get(info.name)
                bytes_ = float(info.byte_size)
                stage, p_div, g_div, opt_div = zero_divisors(node, n_sync)
                param_b += bytes_ / p_div
                grad_b += bytes_ / g_div
                mem += bytes_ / p_div + bytes_ / g_div \
                    + bytes_ * opt_mult / opt_div
                f_ici, f_dcn = split_ring(n_sync)
                mult = accum if stage >= 3 else 1
                comm += grad_bytes(node, mult * f_ici * bytes_)
                if f_dcn:
                    dcn_sync(node, mult * f_dcn * bytes_,
                             2 * accum if stage >= 3
                             else 2 if opt_div > 1 else 1)
                colls += (2 * accum if stage >= 3
                          else 2 if opt_div > 1 else 1)
            if tokens:
                # ring attention: each device rotates its local k/v
                # (2 tensors of tokens_local x hidden) S-1 hops forward,
                # mirrored in the backward.
                comm += 2.0 * 2.0 * tokens_per_dev * hidden * _ACT_BYTES \
                    * (S - 1)
                colls += 2 * max(S - 1, 0)
            if tokens and act_hint:
                mem += act_hint * tokens_per_dev  # seq divides activations
        elif kind == "pipeline":
            S = mesh.get(const.PIPE_AXIS, 1)
            tp = mesh.get(const.MODEL_AXIS, 1)
            M = max(int(strategy.graph_config.parallel.get(
                "num_microbatches", 1)), 1)
            V = max(int(strategy.graph_config.parallel.get(
                "virtual_stages", 1)), 1)
            # Mode resolution mirrors lower_pipeline_ir exactly (graph
            # knob wins, per-variable fields fill in when it's unset,
            # aliases canonicalized) — the price must describe the
            # program that would actually be built.
            from autodist_tpu.parallel.tensor import normalize_comm_overlap
            overlap_cfg = normalize_comm_overlap(
                strategy.graph_config.parallel.get("comm_overlap"))
            tokens_local = tokens / max(n_data, 1) if tokens else 0.0
            emb_var = None      # ((priority, bytes), V, H, vocab shards)
            # V chunks of C = S*V total live per device -> stage
            # params/opt at 1/S, grads sync over the data axis; shared
            # (embedding/unembedding) vars replicate and sync over
            # pipe x data.  PS -> ZeRO-1: stage state at 1/(S*n_data),
            # shared state at 1/(S*n_data) too (pipe x data joint shard).
            # Tensor parallelism inside stages (dp×pp×tp): model-axis
            # entries in a stage var's spec further divide its state by
            # tp; each *row*-parallel var (model on the first per-stage
            # dim: the attention out-proj, mlp wo) adds the Megatron
            # activation all-reduce over the tp group per chunk
            # execution, fwd + bwd.
            for info in infos:
                node = nodes_by_name.get(info.name)
                bytes_ = float(info.byte_size)
                part = node.partitioner if node is not None else None
                is_stage = part is not None and (
                    (part.spec is not None
                     and const.PIPE_AXIS in part.spec)
                    or (part.spec is None
                        and part.mesh_axis == const.PIPE_AXIS
                        and part.num_shards > 1))
                if is_stage:
                    spec_tail = (part.spec[1:] if part.spec else [])
                    tail_axes = {a for e in spec_tail
                                 for a in (e if isinstance(e, (list, tuple))
                                           else [e]) if a}
                    tp_over_dcn = const.DCN_AXIS in tail_axes
                    tp_sharded = const.MODEL_AXIS in tail_axes \
                        or tp_over_dcn
                    # The boundary group of this var's model-parallel
                    # collectives: the model axis, times the dcn axis
                    # when a (mis-)edited plan shards across slices —
                    # those boundaries are priced at DCN below, so such
                    # plans rank strictly worse than the same degree
                    # kept within a slice (and ADT060 flags them).
                    tp_group = (tp if const.MODEL_AXIS in tail_axes
                                else 1) * (n_dcn if tp_over_dcn else 1)
                    per_dev = bytes_ / (S * (tp_group if tp_sharded
                                             else 1))
                    # ZeRO on a tp-sharded var degrades (state shards
                    # with the parameter — recorded on the lowered plan).
                    stage, p_div, g_div, opt_div = (
                        zero_divisors(node, n_data) if not tp_sharded
                        else (0, 1, 1, 1))
                    param_b += per_dev / p_div
                    grad_b += per_dev / g_div
                    mem += per_dev / p_div + per_dev / g_div \
                        + per_dev * opt_mult / opt_div
                    if stage >= 3:
                        # Stage 3: the backward grad reduce-scatter
                        # keeps the blocking wire term; the per-layer
                        # forward all-gathers (V per leaf, once per
                        # accumulation slice) are overlap-capped like
                        # the PR 2 envelope — exposed time is what the
                        # prefetched layer's own compute cannot hide,
                        # never more than the blocking gather.  The
                        # total is FLOORED at the stage-1 rs+ag pair:
                        # replication's grad all-reduce hides behind
                        # backprop just as well (XLA's scheduler, not
                        # modeled here), so crediting only stage 3 with
                        # overlap would elect it as a phantom *speed*
                        # lever on token-hinted models — it must win
                        # through the memory gate alone (the
                        # auto_strategy zoo contract, pinned by
                        # test_zero_stage_ladder_memory_and_election).
                        # The zero3_gather precision slot narrows both
                        # directions: the forward gathers ride the
                        # gather wire (true s8 at int8 — 4x), the
                        # backward cotangent reduce-scatter the summing
                        # wire (fp16 levels — 2x); q/dq passes charge
                        # against the saving.  The stage-1 floor below
                        # stays at fp32 on purpose: stage 1 is PS sync
                        # (full precision), so z3 narrowing is a wire-
                        # volume lever for the drift report, not a step-
                        # time lever past the floor.
                        f_ici, f_dcn = split_ring(n_data)
                        half = f_ici / 2.0
                        rs_bytes = accum * half * per_dev \
                            * PSUM_WIRE_FACTOR[z3_prec]
                        ag_bytes = accum * half * per_dev \
                            * GATHER_WIRE_FACTOR[z3_prec]
                        saved_bytes += 2.0 * accum * half * per_dev \
                            - rs_bytes - ag_bytes
                        qdq_s += qdq(2.0 * accum * half * per_dev / 4.0,
                                     z3_prec)
                        comm += rs_bytes
                        colls += accum   # backward grad reduce-scatters
                        t_ag = ag_bytes / bw_link
                        alpha_floor = hop_alpha * accum * V
                        t_hide = 0.0
                        if tokens:
                            # the step's matmul passes over this leaf's
                            # weights hide the next layer's gathers
                            # (elems ~ bytes/4; tokens_local is the
                            # whole step's share, accum slices included)
                            t_hide = 2.0 * tokens_local \
                                * (per_dev / 4.0) / flops_rate
                        exposed = alpha_floor + max(0.0, t_ag - t_hide)
                        stage1_pair = f_ici * per_dev / bw_link \
                            + 2.0 * hop_alpha
                        already = rs_bytes / bw_link + hop_alpha * accum
                        overlap_s += max(exposed,
                                         stage1_pair - already)
                        hidden_bytes += ag_bytes
                        extra_colls += accum * 2 * V
                        if f_dcn:
                            # Cross-slice half of the rs/ag pair: the
                            # intra-slice shard exchanged at DCN rates;
                            # never overlap-credited (no hiding modeled
                            # across the slow level).
                            rs_d = accum * (f_dcn / 2.0) * per_dev \
                                * PSUM_WIRE_FACTOR[z3_prec]
                            ag_d = accum * (f_dcn / 2.0) * per_dev \
                                * GATHER_WIRE_FACTOR[z3_prec]
                            saved_bytes += accum * f_dcn * per_dev \
                                - rs_d - ag_d
                            dcn_b += rs_d + ag_d
                            dcn_t += (rs_d + ag_d) / bw_dcn \
                                + dcn_alpha * 2 * accum
                            dcn_colls += 2 * accum
                    else:
                        f_ici, f_dcn = split_ring(n_data)
                        comm += grad_bytes(node, f_ici * per_dev)
                        if f_dcn:
                            dcn_sync(node, f_dcn * per_dev,
                                     2 if opt_div > 1 else 1)
                        colls += 2 if opt_div > 1 else 1
                    # rank >= 2 gates out the column-parallel biases
                    # (spec tail ['model']), which shard but never
                    # all-reduce activations.
                    head = spec_tail[0] if spec_tail else None
                    head_axes = {a for a in (head if isinstance(
                        head, (list, tuple)) else [head]) if a}
                    row_parallel = (len(spec_tail) >= 2 and bool(
                        head_axes & {const.MODEL_AXIS, const.DCN_AXIS}))
                    if row_parallel and tp_group > 1 and tokens:
                        width = info.shape[-1]
                        act_bytes = 2.0 * ring(tp_group) * V \
                            * tokens_local * width * _ACT_BYTES
                        mode = overlap_cfg or normalize_comm_overlap(
                            getattr(part, "comm_overlap", None))
                        # Boundary precision: the graph policy's tp_psum
                        # slot, or the per-variable partitioner record a
                        # hand-edited strategy carries (the adoption
                        # rule lower_pipeline_ir applies).
                        prec_b = tp_prec if tp_prec != "fp32" else \
                            (getattr(part, "precision", None) or "fp32")
                        act_factor = PSUM_WIRE_FACTOR[prec_b]
                        use_ring = (ring_kernel and prec_b == "int8"
                                    and mode is None and not tp_over_dcn)
                        if use_ring:
                            # EQuARX ring: TRUE s8 chunks on every hop
                            # (vs int8 levels on an fp16 wire), paid for
                            # with per-hop fused requantization passes.
                            act_factor = float(
                                kp["quant_ring_wire_factor"])
                        if prec_b != "fp32":
                            # fwd + bwd payload elements per step, each
                            # quantized before / dequantized after its
                            # collective (the ring requantizes per hop —
                            # the calibratable factor).
                            qdq_s += qdq(2.0 * V * tokens_local * width,
                                         prec_b) \
                                * (float(kp["quant_ring_qdq_factor"])
                                   if use_ring else 1.0)
                        if tp_over_dcn:
                            # Megatron boundary spanning slices: the
                            # whole per-execution payload crosses DCN
                            # every microbatch and is never overlap-
                            # credited — exactly why the search keeps
                            # tp within a slice and ADT060 flags plans
                            # that don't.
                            wired = act_bytes * act_factor
                            saved_bytes += act_bytes - wired
                            dcn_b += wired
                            dcn_t += wired / bw_dcn \
                                + dcn_alpha * 2 * M * V
                            dcn_colls += 2 * M * V
                        elif mode is None:
                            comm += act_bytes * act_factor
                            saved_bytes += act_bytes * (1.0 - act_factor)
                            # The ring pays 2(n-1) hop launches per
                            # boundary where the monolithic collective
                            # pays one — part of the crossover the
                            # election trades against the wire saving.
                            colls += 2 * M * V * (
                                2 * (tp_group - 1) if use_ring else 1)
                        else:
                            # Latency-hiding decomposition: price the
                            # Megatron boundary as max(comm, compute)
                            # instead of comm + compute.  Per chunk
                            # execution and direction, the blocking
                            # envelope is the ring all-reduce
                            #   t_blk = 2(tp-1)·t_wire + α
                            # (t_wire = one chunk's hop transfer).  The
                            # collective matmul exposes only what chunk
                            # compute cannot hide:
                            #   t_mm = (tp-1)·(max(0, t_hop − t_chunk)
                            #           + t_hop)
                            # (rs-phase hops hidden behind per-chunk
                            # matmuls; the closing ag-phase is bare),
                            # and the rs+ag pair exposes
                            #   t_rsag = max(α, 2(tp-1)·t_hop
                            #            − tp·t_chunk)
                            # (whole-layer overlap via XLA's async
                            # scheduler).  Each is capped at t_blk —
                            # the lowering can always fall back to the
                            # fused all-reduce, so a decomposed plan
                            # never prices above the blocking one.
                            execs = M * V
                            tok_e = tokens_local / max(M, 1)
                            contract = float(math.prod(
                                info.shape[1:-1])) or 1.0
                            t_chunk = 2.0 * tok_e * (contract / tp) \
                                * (width / tp) / flops_rate
                            t_wire = tok_e * (width / tp) * _ACT_BYTES \
                                * act_factor / bw_link
                            # The fused collective-matmul kernel issues
                            # each hop's accumulate+matmul (and, on
                            # silicon, its RDMA) as ONE op — the per-hop
                            # launch overhead drops to the calibratable
                            # fused constant.
                            mm_alpha = (float(kp["fused_hop_alpha_s"])
                                        if fused_mm and mode == "matmul"
                                        else hop_alpha)
                            t_hop = t_wire + hop_alpha
                            t_hop_mm = t_wire + mm_alpha
                            t_blk = 2.0 * (tp - 1) * t_wire + hop_alpha
                            t_rsag = max(hop_alpha,
                                         2.0 * (tp - 1) * t_hop
                                         - tp * t_chunk)
                            t_mm = (tp - 1) * (
                                max(0.0, t_hop_mm - t_chunk) + t_hop_mm)
                            fwd_t = min(t_mm if mode == "matmul"
                                        else t_rsag, t_blk)
                            # The column partner's backward cotangent
                            # reduction decomposes as rs+ag in either
                            # mode (no matmul of its own to hide
                            # behind); charged here like the blocking
                            # model charges its 2x on the row var.
                            bwd_t = min(t_rsag, t_blk)
                            overlap_s += execs * (fwd_t + bwd_t)
                            hidden_bytes += act_bytes * act_factor
                            saved_bytes += act_bytes * (1.0 - act_factor)
                            extra_colls += execs * (
                                (tp + 1 if mode == "matmul" else 2) + 2)
                else:
                    # Shared (non-stage) variable.  Vocab parallelism
                    # (model axis in a shared var's spec) stores the tied
                    # embedding at 1/tp per device — params, grads, AND
                    # optimizer state all shrink — and the pipe x data
                    # grad sync moves 1/tp the bytes.  ZeRO on the
                    # model-sharded table shards its optimizer state
                    # *additionally* over pipe x data (state at
                    # 1/(tp·pipe·data)); its params/grads stay 1/tp
                    # (a stage-3 request degrades to this form).  A
                    # model-replicated shared var takes the full stage
                    # ladder over pipe x data.
                    v_sharded = (part is not None and part.spec
                                 and const.MODEL_AXIS in part.spec)
                    vsh = tp if v_sharded else 1
                    per_dev = bytes_ / vsh
                    n_pd = S * n_data
                    stage, p_div, g_div, opt_div = zero_divisors(node, n_pd)
                    if v_sharded:
                        p_div = g_div = 1   # param already 1/tp-stored
                    param_b += per_dev / p_div
                    grad_b += per_dev / g_div
                    mem += per_dev / p_div + per_dev / g_div \
                        + per_dev * opt_mult / opt_div
                    if stage >= 3 and not v_sharded:
                        f_ici, f_dcn = split_ring(n_pd)
                        half = f_ici / 2.0
                        rs_sh = accum * half * per_dev \
                            * PSUM_WIRE_FACTOR[z3_prec]
                        ag_sh = accum * half * per_dev \
                            * GATHER_WIRE_FACTOR[z3_prec]
                        saved_bytes += 2.0 * accum * half * per_dev \
                            - rs_sh - ag_sh
                        qdq_s += qdq(2.0 * accum * half * per_dev / 4.0,
                                     z3_prec)
                        comm += rs_sh
                        colls += accum   # backward grad reduce-scatters
                        t_ag = ag_sh / bw_link
                        overlap_s += t_ag + hop_alpha * accum
                        hidden_bytes += ag_sh
                        extra_colls += accum * 2
                        if f_dcn:
                            rs_d = accum * (f_dcn / 2.0) * per_dev \
                                * PSUM_WIRE_FACTOR[z3_prec]
                            ag_d = accum * (f_dcn / 2.0) * per_dev \
                                * GATHER_WIRE_FACTOR[z3_prec]
                            saved_bytes += accum * f_dcn * per_dev \
                                - rs_d - ag_d
                            dcn_b += rs_d + ag_d
                            dcn_t += (rs_d + ag_d) / bw_dcn \
                                + dcn_alpha * 2 * accum
                            dcn_colls += 2 * accum
                    else:
                        f_ici, f_dcn = split_ring(n_pd)
                        comm += grad_bytes(node, f_ici * per_dev)
                        if f_dcn:
                            dcn_sync(node, f_dcn * per_dev,
                                     2 if opt_div > 1 else 1)
                        colls += 2 if opt_div > 1 else 1
                    # Track the unembedding for the loss-head epilogue
                    # pricing below.  Identification priority: a
                    # model-sharded spec (the strategy SAYS which var is
                    # the vocab table), then the vocab-rule naming
                    # (…/embedding — so the replicated baseline of a
                    # small-vocab long-context model doesn't mistake
                    # pos_embed for the unembedding), then largest
                    # rank-2 shared var; bytes break ties within a tier.
                    if len(info.shape) == 2:
                        prio = (2 if v_sharded else
                                1 if _VOCAB_NAME_RE.search(info.name)
                                else 0)
                        if emb_var is None or (prio, bytes_) > emb_var[0]:
                            emb_var = ((prio, bytes_), info.shape[0],
                                       info.shape[1], vsh)
            if tokens and emb_var is not None:
                # Loss-head epilogue: the [tokens_local, V] fp32 logits
                # buffer dominates HBM as vocab grows; vocab parallelism
                # bounds it at 1/tp (the streaming chunked epilogue never
                # materializes more than its local shard), replacing the
                # replicated [B,L,H]x[H,V] matmul with a sharded one plus
                # psums: the prologue lookup psum + 3 token-shaped stat
                # psums (max, sum-exp, target logit) forward, one hidden-
                # state cotangent psum backward.
                _, V_dim, width, vsh = emb_var
                tokens_local = tokens / max(n_data, 1)
                # 1/vsh is an upper bound for the sharded case: the
                # streaming epilogue further bounds the live buffer to
                # [B, chunk, V/tp], but the model only knows tokens
                # (B x L fused), not the B/L split the chunk bound
                # needs — so it prices the conservative full-sequence
                # shard.  Safe direction for the feasibility gate: it
                # can under-elect vocab parallelism, never over-elect.
                peak_logits = tokens_local * V_dim * 4.0 / vsh
                mem += peak_logits
                if vsh > 1:
                    # The prologue lookup psum rides the tp_psum slot
                    # (it IS a sum_partials boundary); the stat psums
                    # and backward hidden-cotangent psum ride
                    # vocab_stats.
                    lk_bytes = ring(tp) * tokens_local * width * 4.0
                    st_bytes = ring(tp) * tokens_local \
                        * (width + 3.0) * 4.0
                    lk_f = PSUM_WIRE_FACTOR[tp_prec]
                    st_f = PSUM_WIRE_FACTOR[stats_prec]
                    comm += lk_bytes * lk_f + st_bytes * st_f
                    saved_bytes += lk_bytes * (1.0 - lk_f) \
                        + st_bytes * (1.0 - st_f)
                    qdq_s += qdq(tokens_local * width, tp_prec) \
                        + qdq(tokens_local * (width + 3.0), stats_prec)
                    colls += 6
            if tokens:
                # activation hop per schedule tick (ppermute ring), fwd +
                # transposed bwd; T = M*V + S - 1 ticks of a microbatch
                # activation (tokens_local/M x hidden) — interleaving
                # trades V-fold more (smaller) hops for a ~V-fold smaller
                # bubble, which only measurement can arbitrate.
                tokens_local = tokens / max(n_data, 1)
                T = M * V + S - 1
                comm += 2.0 * T * (tokens_local / M) * hidden * _ACT_BYTES
                colls += 2 * T
                # The [M, B/M, hidden] output buffer rides the tick scan
                # on every device regardless of remat.
                mem += tokens_local * hidden * _ACT_BYTES
                remat = bool(strategy.graph_config.parallel.get(
                    "remat", False))
                if act_hint:
                    if remat:
                        # jax.checkpoint around each chunk: only the
                        # chunk boundary inputs stay live across the
                        # schedule — M*V executions x (tokens_local/M)
                        # boundary tokens x hidden.
                        mem += V * tokens_local * hidden * _ACT_BYTES
                    else:
                        # AD through the tick scan keeps every chunk
                        # execution's residuals: M*V executions, each
                        # holding its 1/(S*V) share of the per-token
                        # fwd+bwd footprint -> act_hint*tokens_local/S.
                        mem += act_hint * tokens_local / S
        else:  # expert
            E = mesh.get(const.EXPERT_AXIS, 1)
            # dense params replicate + sync over data x expert (PS ->
            # ZeRO-1 over both); expert tables live 1/E and sync over
            # data only (PS degrades to plain there — state already
            # sharded with the table).
            for info in infos:
                node = nodes_by_name.get(info.name)
                bytes_ = float(info.byte_size)
                part = node.partitioner if node is not None else None
                is_expert = part is not None and (
                    (part.spec is not None and const.EXPERT_AXIS in part.spec)
                    or part.mesh_axis == const.EXPERT_AXIS)
                if is_expert:
                    mem += bytes_ * (2.0 + opt_mult) / E
                    param_b += bytes_ / E
                    grad_b += bytes_ / E
                    f_ici, f_dcn = split_ring(n_data)
                    comm += grad_bytes(node, f_ici * (bytes_ / E))
                    if f_dcn:
                        dcn_sync(node, f_dcn * (bytes_ / E))
                    colls += 1
                else:
                    n_sync = n_data * E
                    stage, p_div, g_div, opt_div = zero_divisors(node,
                                                                 n_sync)
                    param_b += bytes_ / p_div
                    grad_b += bytes_ / g_div
                    mem += bytes_ / p_div + bytes_ / g_div \
                        + bytes_ * opt_mult / opt_div
                    f_ici, f_dcn = split_ring(n_sync)
                    mult = accum if stage >= 3 else 1
                    comm += grad_bytes(node, mult * f_ici * bytes_)
                    if f_dcn:
                        dcn_sync(node, mult * f_dcn * bytes_,
                                 2 * accum if stage >= 3
                                 else 2 if opt_div > 1 else 1)
                    colls += (2 * accum if stage >= 3
                              else 2 if opt_div > 1 else 1)
            if tokens and E > 1:
                # Hierarchical all_to_all term: dispatch + combine, fwd
                # + bwd — 4 passes of the capacity-padded routed slots.
                # Top-2 routing fills E x C = 2 x cf x G slots, so the
                # [E, C, M] payload is (2 x capacity_factor) local token
                # activations, (E-1)/E of it leaving the device.
                knobs = strategy.graph_config.parallel
                cap_f = float(knobs.get("capacity_factor", 2.0))
                over_dcn = bool(knobs.get("expert_over_dcn", False))
                a2a_prec = policy.get("moe_a2a", "fp32")
                payload = 4.0 * (2.0 * cap_f) * tokens_per_dev * hidden \
                    * _ACT_BYTES * (E - 1) / E
                # Permute-shaped: the wire narrows like a gather (true
                # s8 at int8 — no summing, no fp16-levels headroom).
                factor = GATHER_WIRE_FACTOR[a2a_prec]
                a2a_kernel = ("a2a_ring" in kern_cfg
                              and a2a_prec == "int8" and not over_dcn)
                if a2a_kernel:
                    factor = float(kp["a2a_ring_wire_factor"])
                wired = payload * factor
                saved_bytes += payload - wired
                if a2a_prec != "fp32":
                    # whole payload quantized before / dequantized after
                    # each pass; the fused ring does both inside the hop
                    # (the calibratable VMEM-vs-HBM factor).
                    qdq_a2a = qdq(payload / _ACT_BYTES, a2a_prec) \
                        * (float(kp["a2a_ring_qdq_factor"])
                           if a2a_kernel else 1.0)
                    qdq_s += qdq_a2a
                    a2a_t += qdq_a2a
                # The ring decomposes each all_to_all into E-1 ppermute
                # hops (2(E-1) per dispatch+combine pair — the ADT120
                # wire signature); the monolithic collective is one
                # launch per pass.
                a2a_launches = 4 * (E - 1) if a2a_kernel else 4
                if over_dcn:
                    # Expert axis spanning slices: every routed slot
                    # crosses DCN each pass, never overlap-credited —
                    # exactly why the search keeps experts within a
                    # slice (ADT061 flags plans that don't) unless the
                    # topology's link constants invert the trade.
                    dcn_b += wired
                    t = wired / bw_dcn + dcn_alpha * a2a_launches
                    dcn_t += t
                    a2a_t += t
                    dcn_colls += a2a_launches
                elif a2a_kernel:
                    # Fused ring: the kernel issues each hop's ppermute
                    # (and on silicon its RDMA), so the 4(E-1) launches
                    # are priced at the calibratable fused alpha — the
                    # composed monolithic collective pays the full
                    # hop_alpha per pass.  This launch trade (against
                    # the halved q/dq above) is the ring-vs-composed
                    # crossover the search arbitrates.
                    comm += wired
                    extra_colls += a2a_launches
                    t_launch = float(kp["fused_hop_alpha_s"]) \
                        * a2a_launches
                    overlap_s += t_launch
                    a2a_t += wired / bw_link + t_launch
                else:
                    comm += wired
                    colls += a2a_launches
                    a2a_t += wired / bw_link + hop_alpha * a2a_launches
                a2a_b += wired
            if tokens and act_hint:
                mem += act_hint * tokens_per_dev
        comm_time = ((comm / bw_link + hop_alpha * colls + overlap_s
                      + qdq_s + dcn_t)
                     if total_devices > 1 else 0.0)
        hbm = self.chip.hbm_gb * 1e9 * self.hbm_headroom
        return StrategyCost(comm_bytes=comm + hidden_bytes + dcn_b,
                            comm_time_s=comm_time,
                            num_collectives=colls + extra_colls
                            + dcn_colls,
                            mem_bytes_per_device=mem,
                            feasible=mem <= hbm,
                            overlap_time_s=(overlap_s
                                            if total_devices > 1 else 0.0),
                            peak_logits_bytes=(peak_logits
                                               if kind == "pipeline"
                                               else 0.0),
                            param_shard_bytes=param_b,
                            grad_shard_bytes=grad_b,
                            wire_bytes_saved=saved_bytes,
                            quant_dq_time_s=(qdq_s if total_devices > 1
                                             else 0.0),
                            dcn_bytes=dcn_b,
                            dcn_time_s=(dcn_t if total_devices > 1
                                        else 0.0),
                            a2a_bytes=a2a_b,
                            a2a_time_s=(a2a_t if total_devices > 1
                                        else 0.0))

    # ------------------------------------------------------------------ #
    # Serving: per-token decode latency
    # ------------------------------------------------------------------ #
    def decode_cost(self, trainable: Trainable, config,
                    *, batch_slots: int = 1, max_len: int = 2048,
                    kv_bytes_per_elem: float = _ACT_BYTES,
                    mean_request_len: Optional[float] = None,
                    mean_prompt_len: Optional[float] = None,
                    kv_block_len: int = 16,
                    prefix_hit_rate: float = 0.0,
                    spec_acceptance: Optional[float] = None) -> DecodeCost:
        """Per-token decode latency for one serving config.

        ``config`` is either a training :class:`Strategy` (its Strategy-
        IR parallel knobs seed the serving shape — the same IR answers
        both objectives) or a plain dict with ``tensor_parallel`` /
        ``vocab_parallel`` / ``kv_layout`` keys.  The model:

        * **compute** — a decode token's matmul passes touch every
          parameter once (2 FLOPs/element), divided across the tp group
          for the vars the Megatron/vocab rule tables shard (the same
          tables the serving engine shards by);
        * **comm** — per layer, the row-parallel boundary all-reduces of
          the ``[B, H]`` activations (attention out-proj + mlp ``wo``,
          forward only — decode has no backward), plus the
          vocab-parallel epilogue's lookup psum and greedy pmax/pmin;
        * **memory** — sharded parameters + the TP-sharded KV cache
          (``2·layers·H·max_len·slots/tp`` elements; paged: the mean
          request length rounded up to ``kv_block_len`` per slot),
          gated against HBM headroom like the training costs;
        * **capacity** — ``request_capacity``: concurrent requests the
          post-params HBM supports under ``mean_request_len`` (default:
          every request fills ``max_len`` — the no-variance worst
          case).  Dense reserves a full ``max_len`` lane per request;
          paged reserves ``ceil(mean/block)·block`` positions and pays
          the calibratable ``paged_attention_overhead`` on the
          attention term — so :attr:`DecodeCost.serve_score` elects
          paged exactly when length variance makes dense reservation
          wasteful, and dense when it doesn't (both directions pinned);
        * **fleet** — a ``replicas`` key prices the router's shape: the
          tp group must fit a slice's ICI (rejected otherwise — the
          serving ADT060 analog), ``replicas × tp`` must fit the
          topology, and a fleet spanning slices pays the per-request
          DCN dispatch hop amortized per token
          (:attr:`DecodeCost.dispatch_time_s`) —
          :attr:`DecodeCost.fleet_score` then ranks aggregate
          throughput for the mix.
        * **the throughput ladder (PR 16)** — ``prefix_caching``
          divides the capacity term's per-request residency by the
          traffic's ``prefix_hit_rate`` (the shared leading blocks cost
          the pool nothing) and pays the calibratable
          ``prefix_caching_overhead`` on attention, so the capacity
          objective elects it exactly when the mix actually shares
          prefixes; ``speculative=k`` prices the window — draft
          proposes ``k`` at ``spec_draft_flops_frac``, one verify
          dispatch scores ``k+1`` at ``spec_marginal_token_cost`` per
          extra token — divided by the expected emissions
          ``(1 - α^{k+1}) / (1 - α)`` under acceptance rate
          ``spec_acceptance`` (default: the profile's
          ``spec_acceptance_default``), so the latency objective elects
          speculation exactly when α clears the draft+verify overhead
          — both directions pinned.
        * **disaggregation (PR 17)** — ``prefill_replicas`` +
          ``decode_replicas`` keys price a prefill/decode pool split:
          a request's prompt pass runs on the prefill pool, its KV
          prefix is handed to the decode pool (ICI within a slice, DCN
          when the split spans slices — the handoff term), and its
          decode tail runs there.  ``mean_prompt_len`` splits the
          traffic's ``mean_request_len`` into prompt vs decoded tokens
          (default: half) — :attr:`DecodeCost.disagg_score` then ranks
          splits by the bottleneck stage, so prefill-bound and
          decode-bound mixes elect different splits (both directions
          pinned on the handoff term).
        """
        from autodist_tpu.strategy.ir import (normalize_kernel,
                                              normalize_kv_layout,
                                              normalize_prefill_chunk,
                                              normalize_prefix_caching,
                                              normalize_speculative)

        if isinstance(config, Strategy):
            par = config.graph_config.parallel or {}
            kern = normalize_kernel(
                getattr(config.graph_config, "kernel", None))
        else:
            par = config
            kern = normalize_kernel(config.get("kernel"))
        tp = int(par.get("tensor_parallel", 1) or 1)
        vocab_parallel = bool(par.get("vocab_parallel", False))
        kv_layout = normalize_kv_layout(par.get("kv_layout"))
        replicas = int(par.get("replicas", 1) or 1)
        prefill_chunk = normalize_prefill_chunk(par.get("prefill_chunk"))
        prefix_caching = normalize_prefix_caching(
            par.get("prefix_caching", False))
        spec_k = normalize_speculative(par.get("speculative"))
        prefill_r = int(par.get("prefill_replicas", 0) or 0)
        decode_r = int(par.get("decode_replicas", 0) or 0)
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if bool(prefill_r) != bool(decode_r):
            raise ValueError(
                "a disaggregated split names BOTH pools: got "
                f"prefill_replicas={prefill_r}, "
                f"decode_replicas={decode_r}")
        if prefill_r and replicas > 1:
            raise ValueError(
                "replicas and a prefill/decode pool split are exclusive "
                "shapes — the pool split IS the fleet shape")
        if prefill_r and kv_layout != "paged":
            raise ValueError(
                "the prefill->decode KV handoff moves block-table "
                "prefixes — a pool split requires kv_layout='paged'")
        if (prefill_chunk is not None or prefix_caching) \
                and kv_layout != "paged":
            raise ValueError(
                "prefill_chunk/prefix_caching ride the block table — "
                "they require kv_layout='paged'")
        if not 0.0 <= float(prefix_hit_rate) <= 1.0:
            raise ValueError(
                f"prefix_hit_rate must be in [0, 1], got "
                f"{prefix_hit_rate}")
        # The fleet placement contract (arxiv 2110.10548's hierarchy,
        # serving-side): tp's per-layer boundary all-reduces live on
        # every decoded token, so the tp group must stay within a
        # slice's ICI; only the router's per-REQUEST dispatch may cross
        # DCN — replicas spread across slices, tp never does.
        num_devices = self.spec.num_devices()
        num_slices = max(int(getattr(self.spec, "num_slices", 1) or 1), 1)
        per_slice = num_devices // num_slices
        if tp > per_slice:
            raise ValueError(
                f"tensor_parallel={tp} exceeds the {per_slice} devices "
                f"a slice's ICI connects ({num_slices} slice(s) of "
                f"{per_slice}); tp must stay within a slice — spread "
                "replicas across slices instead")
        if replicas * tp > num_devices:
            raise ValueError(
                f"replicas={replicas} x tensor_parallel={tp} needs "
                f"{replicas * tp} devices; the topology has "
                f"{num_devices}")
        if prefill_r and (prefill_r + decode_r) * tp > num_devices:
            raise ValueError(
                f"pool split prefill={prefill_r} + decode={decode_r} "
                f"at tensor_parallel={tp} needs "
                f"{(prefill_r + decode_r) * tp} devices; the topology "
                f"has {num_devices} (the ADT089 bound)")
        flash = "flash_decode" in kern
        from autodist_tpu.strategy.parallel_builders import (
            PIPELINE_TP_RULES, PIPELINE_VOCAB_RULES)

        tp_res = [re.compile(p) for p, _ in PIPELINE_TP_RULES]
        v_res = [re.compile(p) for p, _ in PIPELINE_VOCAB_RULES]
        hidden = self._hidden_dim(trainable)
        layers = getattr(trainable, "num_stages", None)
        if layers is None:
            # Fallback for non-stage-structured trainables: the most
            # common leading dim among rank>=3 vars (a stacked layer
            # stack's shared leading extent).  Rank-2 tables are
            # excluded on purpose — a [V, H] embedding's vocab dim
            # would otherwise masquerade as a layer count and inflate
            # every term by orders of magnitude.
            import collections as _collections

            leads = _collections.Counter(
                i.shape[0] for i in trainable.var_infos()
                if len(i.shape) >= 3)
            layers = leads.most_common(1)[0][0] if leads else 1
        layers = int(layers)
        elems = bytes_ = 0.0
        for info in trainable.var_infos():
            shard = 1
            if tp > 1:
                name = info.name
                short = name.split("/", 1)[1] if "/" in name else name
                if any(p.search(name) for p in tp_res):
                    shard = tp
                elif vocab_parallel and any(p.search(short)
                                            for p in v_res):
                    shard = tp
            elems += info.size / shard
            bytes_ += info.byte_size / shard
        mxu_eff = float(self.link_profile.get(
            "mxu_efficiency", _DEFAULT_MXU_EFFICIENCY))
        flops_rate = self.chip.peak_bf16_tflops * 1e12 * mxu_eff
        compute = 2.0 * elems * batch_slots / flops_rate
        # Attention over the cache: per token, each layer contracts the
        # query against its [heads/tp, max_len, head_dim] cache slice
        # twice (scores + values) — the term that grows with occupancy
        # and the one the flash_decode kernel moves.  Past the
        # calibrated crossover length flash divides it by the measured
        # speedup; below it the kernel's fixed overhead loses to plain
        # einsum (the short penalty < 1), so the election flips exactly
        # at the crossover.
        attn = 4.0 * layers * hidden * max_len * batch_slots \
            / max(tp, 1) / flops_rate
        if flash:
            kp = self.kernel_profile
            if max_len >= float(kp["flash_decode_crossover_len"]):
                attn /= float(kp["flash_decode_speedup"])
            else:
                attn /= float(kp["flash_decode_short_penalty"])
        if kv_layout == "paged":
            # The block-table indirection: gathers (composed) or
            # per-block DMA setup (the paged flash kernel) vs the
            # dense contiguous lane.
            attn *= float(self.kernel_profile.get(
                "paged_attention_overhead",
                KERNEL_PROFILE["paged_attention_overhead"]))
        if prefix_caching:
            # CoW bookkeeping on the gather path: refcount checks plus
            # the occasional copy-before-write.  Strictly > 1 so a mix
            # with no sharing (hit rate 0) never elects the rung for
            # free — the hit rate has to buy the overhead back through
            # the capacity term (both directions pinned).
            attn *= float(self.kernel_profile.get(
                "prefix_caching_overhead",
                KERNEL_PROFILE["prefix_caching_overhead"]))
        compute += attn

        bw_link = float(self.link_profile.get(
            "ici_gbps", self.chip.ici_gbps)) * 1e9
        hop_alpha = float(self.link_profile.get(
            "hop_alpha_s", COLLECTIVE_ALPHA))
        ring_m = 2.0 * (tp - 1) / tp if tp > 1 else 0.0
        comm = 0.0
        if tp > 1:
            boundaries = 2 * layers + (1 if vocab_parallel else 0)
            comm = ring_m * boundaries * batch_slots * hidden * _ACT_BYTES \
                / bw_link + hop_alpha * (boundaries
                                         + (2 if vocab_parallel else 0))
        # Speculative decoding reprices the whole window: one target
        # step becomes draft-proposes-k (a draft forward costs
        # spec_draft_flops_frac of the target's) plus one verify
        # dispatch scoring k+1 positions (each extra position costs
        # spec_marginal_token_cost of a full step — the matmuls batch,
        # only attention and the epilogue grow).  The window emits
        # E = (1 - α^{k+1}) / (1 - α) tokens in expectation under
        # acceptance rate α, so every per-token term divides by E.
        # α below the break-even leaves token_time_s WORSE than
        # vanilla — the ladder rung loses the election, as it should.
        spec_alpha = 0.0
        if spec_acceptance is not None \
                and not 0.0 <= float(spec_acceptance) <= 1.0:
            raise ValueError(
                f"spec_acceptance must be in [0, 1], got "
                f"{spec_acceptance}")
        if spec_k is not None:
            kp = self.kernel_profile
            alpha = float(kp.get(
                "spec_acceptance_default",
                KERNEL_PROFILE["spec_acceptance_default"])
                if spec_acceptance is None else spec_acceptance)
            spec_alpha = alpha
            k = int(spec_k)
            expected = (float(k + 1) if alpha >= 1.0
                        else (1.0 - alpha ** (k + 1)) / (1.0 - alpha))
            marginal = float(kp.get(
                "spec_marginal_token_cost",
                KERNEL_PROFILE["spec_marginal_token_cost"]))
            draft_frac = float(kp.get(
                "spec_draft_flops_frac",
                KERNEL_PROFILE["spec_draft_flops_frac"]))
            window_scale = (1.0 + k * marginal + k * draft_frac) \
                / expected
            compute *= window_scale
            attn *= window_scale
            # The verify dispatch is ONE program — its tp boundary
            # all-reduces fire once per window, not once per token.
            comm /= expected
        # Per-request cache residency: dense reserves the full max_len
        # lane whatever the request's length; paged reserves the mean
        # length rounded up to a block.
        mean_len = float(max_len if mean_request_len is None
                         else min(mean_request_len, max_len))
        bl = max(int(kv_block_len), 1)
        resident = (float(-(-int(math.ceil(mean_len)) // bl) * bl)
                    if kv_layout == "paged" else float(max_len))
        lane_bytes = 2.0 * layers * hidden * kv_bytes_per_elem \
            / max(tp, 1)
        kv = lane_bytes * resident * batch_slots
        mem = bytes_ + kv
        if spec_k is not None:
            # The draft rides along: its params + its full-capacity
            # block pool cost spec_draft_flops_frac of the target's.
            draft_frac = float(self.kernel_profile.get(
                "spec_draft_flops_frac",
                KERNEL_PROFILE["spec_draft_flops_frac"]))
            mem += draft_frac * (bytes_ + kv)
        hbm = self.chip.hbm_gb * 1e9 * self.hbm_headroom
        # Prefix caching: the shared leading run of a request's blocks
        # is refcounted, not duplicated — at hit rate h each admission
        # charges the pool only the novel (1 - h) suffix (floored at
        # one block: the CoW-protected partial tail is always
        # physically owned somewhere).
        resident_eff = resident
        if prefix_caching:
            resident_eff = max(resident * (1.0 - float(prefix_hit_rate)),
                               float(bl))
        capacity = max(hbm - bytes_, 0.0) / max(lane_bytes * resident_eff,
                                                1e-30)
        if spec_k is not None:
            capacity /= 1.0 + draft_frac
        # Router dispatch across DCN: a fleet too big for one slice
        # spreads replicas across slices, and a request routed to a
        # remote-slice replica ships its prompt over DCN once —
        # amortized over the tokens it then decodes locally.  A fleet
        # that fits one slice pays nothing (the both-ways pin: replicas
        # are PRICED across DCN, never free, never forbidden).
        dispatch = 0.0
        if replicas > 1 and replicas * tp > per_slice:
            bw_dcn, dcn_alpha = self._dcn_link()
            remote_frac = (num_slices - 1) / num_slices
            prompt_bytes = mean_len * 4.0   # token ids on the wire
            dispatch = remote_frac * (dcn_alpha
                                      + prompt_bytes / bw_dcn) \
                / max(mean_len, 1.0)
        # Disaggregation: split the mix's mean request into its prompt
        # (prefill-pool work) and decoded tail (decode-pool work), and
        # price the per-request KV prefix handoff between the pools —
        # ICI when the whole split fits one slice, DCN when it spans
        # slices.  The handoff lands on the DECODE stage (its pool
        # absorbs the ingest), so a split that starves decode pays for
        # every handoff it forces — the term disagg_score pins on.
        prefill_t = decode_t = handoff = 0.0
        if prefill_r >= 1 and decode_r >= 1:
            prompt_len = float(mean_len / 2.0 if mean_prompt_len is None
                               else min(mean_prompt_len, mean_len))
            if prompt_len < 0:
                raise ValueError(
                    f"mean_prompt_len must be >= 0, got {prompt_len}")
            decode_tokens = max(mean_len - prompt_len, 1.0)
            prefill_t = 2.0 * elems * prompt_len / flops_rate
            decode_t = (compute + comm) * decode_tokens
            hand_bytes = lane_bytes * prompt_len
            if (prefill_r + decode_r) * tp > per_slice:
                bw_dcn, dcn_alpha = self._dcn_link()
                handoff = dcn_alpha + hand_bytes / bw_dcn
            else:
                handoff = hop_alpha + hand_bytes / bw_link
        return DecodeCost(token_time_s=compute + comm, comm_time_s=comm,
                          compute_time_s=compute, kv_bytes_per_device=kv,
                          mem_bytes_per_device=mem, feasible=mem <= hbm,
                          tensor_parallel=tp, vocab_parallel=vocab_parallel,
                          attn_time_s=attn, kernel=tuple(sorted(kern)),
                          kv_layout=kv_layout,
                          request_capacity=capacity,
                          replicas=replicas, dispatch_time_s=dispatch,
                          prefill_chunk=prefill_chunk,
                          prefix_caching=prefix_caching,
                          prefix_hit_rate=(float(prefix_hit_rate)
                                           if prefix_caching else 0.0),
                          speculative=spec_k,
                          spec_acceptance=spec_alpha,
                          prefill_replicas=prefill_r,
                          decode_replicas=decode_r,
                          prefill_time_s=prefill_t,
                          decode_time_s=decode_t,
                          handoff_time_s=handoff)

    def strategy_cost(self, trainable: Trainable,
                      strategy: Strategy) -> StrategyCost:
        if strategy.graph_config.lowering == "gspmd":
            return self._gspmd_cost(trainable, strategy)
        if strategy.graph_config.lowering in ("sequence", "pipeline",
                                              "expert"):
            return self._parallel_cost(trainable, strategy)
        n = max(strategy.graph_config.replicas, 1)
        infos = {v.name: v for v in trainable.var_infos()}
        # Hierarchical split of the replica sync: the dcn-crossing part
        # of every collective is priced at DCN constants, never at
        # ici_gbps (pure-ICI topologies keep today's exact factors).
        try:
            n_dcn = self._dcn_degree(self.spec.resolved_mesh_shape())
        except (ValueError, RuntimeError):
            n_dcn = max(int(getattr(self.spec, "num_slices", 1) or 1), 1)
        if n % max(n_dcn, 1):
            n_dcn = 1
        ring, dcn_factor = self._split_ring(n, n_dcn)
        bw_dcn, dcn_alpha = self._dcn_link()
        sparse_frac = (n_dcn - 1) / n_dcn if n_dcn > 1 else 0.0
        dcn_bytes = dcn_time = 0.0
        dcn_colls = 0

        comm_bytes = 0.0
        mem_bytes = 0.0
        groups: set = set()
        num_collectives = 0
        for node in strategy.node_configs:
            info = infos.get(node.var_name)
            if info is None:
                continue
            bytes_ = float(info.byte_size)
            sharded = node.partitioner is not None
            sync = node.synchronizer
            factor = COMPRESSOR_FACTOR.get(
                (getattr(sync, "compressor", "none") or "none")
                .partition(":")[0], 1.0)
            # Touched-rows pricing only applies when the lowering actually
            # takes the sparse path: PS + vocab(axis-0) partitioning
            # (lowering.py make_plan's sparse_lookup gate).
            sparse_fast = (
                node.is_sparse and sync.kind == "ps" and sharded
                and node.partitioner.num_shards > 1
                and max(node.partitioner.split_axis, 0) == 0)

            if sparse_fast:
                # Sparse sharded path: only touched rows move (gather of
                # params + scatter of grads), ≙ the reference's sparse
                # PS push/pull (ps_synchronizer.py:476-535).  The cross-
                # slice share of the shard owners is priced at DCN.
                sp = 2.0 * self.sparsity_fraction * bytes_
                comm_bytes += sp * (1.0 - sparse_frac)
                dcn_bytes += sp * sparse_frac
                num_collectives += 2
                if sparse_frac:
                    dcn_colls += 2
                mem_bytes += (bytes_ / n) * (1.0 + self.opt_state_multiplier) \
                    + self.sparsity_fraction * bytes_  # gathered activations
            elif sharded:
                # Sharded-state (PartitionedPS/ZeRO): reduce_scatter grads
                # + all_gather params — ring-equivalent volume, two
                # launches, optimizer state sharded 1/n.
                comm_bytes += ring * bytes_ * factor
                num_collectives += 2
                if dcn_factor:
                    dcn_bytes += dcn_factor * bytes_ * factor
                    dcn_colls += 2
                mem_bytes += bytes_ \
                    + bytes_ * factor \
                    + (bytes_ * self.opt_state_multiplier) / n
            elif sync.kind == "ps":
                # Dense unpartitioned PS ⇒ ZeRO-1 U_FLAT lowering
                # (lowering.py:150-152): params + grads replicated,
                # reduce_scatter grads + all_gather params (ring-equivalent
                # volume), optimizer state sharded 1/n.
                comm_bytes += ring * bytes_
                num_collectives += 2
                if dcn_factor:
                    dcn_bytes += dcn_factor * bytes_
                    dcn_colls += 2
                mem_bytes += 2.0 * bytes_ \
                    + (bytes_ * self.opt_state_multiplier) / n
            else:
                # Replicated DP allreduce: bucketed collectives count once
                # per group (≙ ScopedAllocator merging, runner.py:40-46).
                comm_bytes += ring * bytes_ * factor
                if dcn_factor:
                    dcn_bytes += dcn_factor * bytes_ * factor
                group = getattr(sync, "group", None)
                if group is not None:
                    groups.add(group)
                else:
                    num_collectives += 1
                    if dcn_factor:
                        dcn_colls += 1
                mem_bytes += bytes_ * (2.0 + self.opt_state_multiplier)

        num_collectives += len(groups)
        if dcn_factor:
            dcn_colls += len(groups)
        tokens, act_hint = self._hints(trainable)
        if tokens and act_hint:
            mem_bytes += act_hint * tokens / n
        bw = self.chip.ici_gbps * 1e9  # bytes/s
        comm_time = (comm_bytes / bw if n > 1 else 0.0) \
            + COLLECTIVE_ALPHA * num_collectives * (1 if n > 1 else 0)
        if dcn_bytes and n > 1:
            dcn_time = dcn_bytes / bw_dcn + dcn_alpha * dcn_colls
            comm_time += dcn_time
        hbm = self.chip.hbm_gb * 1e9 * self.hbm_headroom
        return StrategyCost(
            comm_bytes=comm_bytes + dcn_bytes,
            comm_time_s=comm_time,
            num_collectives=num_collectives + dcn_colls,
            mem_bytes_per_device=mem_bytes,
            feasible=mem_bytes <= hbm,
            dcn_bytes=dcn_bytes,
            dcn_time_s=dcn_time,
        )
