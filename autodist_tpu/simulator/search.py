"""Topology-aware strategy search: the knob cross-product replaces the
hand-enumerated zoo.

The AutoStrategy zoo (:func:`~autodist_tpu.simulator.auto_strategy.
default_candidates`) ranks a fixed ~20-candidate list — every
``(dp, pp, tp, …)`` point it did not anticipate is simply never
considered.  This module enumerates the full cross-product of

    dp-across-DCN × dp-within-ICI × pp × tp × vocab_parallel ×
    zero_stage × comm_overlap × collective_precision ×
    num_microbatches × compressor

for the *given* topology and trainable (the cross-product-vs-two-level-
network-model search of arxiv 2110.10548), prunes it down, and prices
the survivors with the same hierarchical :class:`~autodist_tpu.
simulator.cost_model.CostModel` every zoo candidate is scored by:

1. **enumerate** — mesh factorizations keep tensor/pipeline parallelism
   strictly *within* a slice: only data parallelism ever rides the
   ``dcn`` axis (a model-axis collective crossing DCN pays orders of
   magnitude more per byte — the cost model prices exactly that, and
   plan lint ADT060 flags hand-made violations).  Unbuildable points
   (no TP rule match, stage count mismatch, batch indivisible) are
   skipped and counted, like AutoStrategy's own candidate loop.
2. **dominance-prune** — within each mesh factorization, a config whose
   cheap closed-form proxies (comm bytes, compute overhead, memory) are
   all no better — and at least one strictly worse — than a surviving
   sibling's is dropped before pricing.  The proxies model only the
   knob effects the cost model itself guarantees monotone (the ZeRO
   accounting ladder, wire-precision byte factors + q/dq passes,
   microbatch hop/bubble trade, overlap never pricing above blocking),
   so dominance can never drop a point the cost model would have
   ranked first.
3. **plan-lint** — every synthesized candidate runs
   :func:`autodist_tpu.analysis.lint_plan` before it is priced; a lint
   ERROR prunes the candidate, counted and reported per code — never
   silently.  (PR 9's linter is the correctness backbone that makes a
   thousands-of-configs search safe.)
4. **price** — survivors are scored by ``CostModel.strategy_cost``
   (per-level ICI/DCN comm terms, HBM feasibility gate) and sorted
   best-first; the zoo seeds the frontier by default so the searched
   winner can never rank below the zoo winner.

``tools/lint_strategy.py --search`` sweeps the frontier in CI and
program-lints the winner; ``AutoStrategy(search=True)`` uses the
frontier in place of the zoo with the same report/measure/multihost
machinery.  See ``docs/usage/performance.md`` ("Topology-aware
search").
"""
from __future__ import annotations

import dataclasses
import json
from typing import Optional, Sequence

from autodist_tpu import const
from autodist_tpu.capture import Trainable
from autodist_tpu.resource import ResourceSpec
from autodist_tpu.simulator.cost_model import (COLLECTIVE_ALPHA, CostModel,
                                               SpecMeshMismatch,
                                               StrategyCost)
from autodist_tpu.strategy.builders import builder_from_knobs
from autodist_tpu.utils import logging


# --------------------------------------------------------------------------- #
# One point of the cross-product
# --------------------------------------------------------------------------- #
@dataclasses.dataclass(frozen=True)
class KnobConfig:
    """One candidate: a mesh factorization of the topology plus the
    serializable strategy knobs.  ``dp_dcn`` is always the full slice
    count — data parallelism is the only axis that rides DCN."""

    dp_dcn: int = 1
    dp_ici: int = 1
    pp: int = 1
    tp: int = 1
    virtual_stages: int = 1
    num_microbatches: int = 1
    vocab_parallel: bool = False
    zero_stage: int = 0
    comm_overlap: Optional[str] = None
    collective_precision: Optional[str] = None
    compressor: str = "none"
    # Fused-kernel tier election: "fused" enables every Pallas kernel
    # this knob point's enabling knobs admit (builder_from_knobs
    # resolves the set); None keeps the composed lowerings.
    kernel: Optional[str] = None
    pipeline: bool = True      # stage-structured (Pipeline) vs generic
    # Expert-parallel (MoE) family, PR 18.  ``expert`` > 0 marks the
    # candidate as the expert lowering with that expert-axis degree
    # (1 = the dense point: experts replicated, no all_to_all);
    # ``num_experts``/``capacity_factor`` are copied from the
    # trainable's declared MoE shape (they change the *objective*, so
    # the search records — never sweeps — them); ``expert_over_dcn``
    # is the placement knob of arxiv 2110.10548's sharpest trade: the
    # expert axis spans slices (mesh drops the separate dcn axis, the
    # a2a pays DCN rates, ADT061 flags it) — emitted only so inverted
    # link constants can elect it.
    expert: int = 0
    num_experts: int = 0
    capacity_factor: float = 2.0
    expert_over_dcn: bool = False

    def mesh(self) -> dict:
        """The candidate's mesh factorization — dcn outermost (slice
        boundaries), model/expert innermost (they ride the shortest
        links — unless ``expert_over_dcn`` deliberately crosses)."""
        shape: dict = {}
        if self.expert:
            if not self.expert_over_dcn and self.dp_dcn > 1:
                shape[const.DCN_AXIS] = self.dp_dcn
            shape[const.DATA_AXIS] = self.dp_ici
            shape[const.EXPERT_AXIS] = self.expert
            return shape
        if self.dp_dcn > 1:
            shape[const.DCN_AXIS] = self.dp_dcn
        if self.dp_ici > 1 or not self.pipeline:
            shape[const.DATA_AXIS] = self.dp_ici
        if self.pipeline:
            shape[const.PIPE_AXIS] = self.pp
        if self.tp > 1:
            shape[const.MODEL_AXIS] = self.tp
        return shape

    def mesh_key(self) -> tuple:
        """Sibling group for dominance pruning: one mesh factorization,
        split by kernel election.  The fused collective-matmul proxy is
        one-sidedly better than its composed sibling (a launch credit
        with no offsetting proxy term), so weak dominance inside one
        group would delete the composed sibling before the REAL cost
        model — where calibration can disfavor fusion
        (``fused_hop_alpha_s`` at or above the measured ``hop_alpha``)
        — ever prices it.  The kernel-vs-composed election must always
        reach pricing, in both directions.  Expert candidates group by
        their expert degree + placement for the same reason: the
        within-slice-vs-across-DCN election is the cost model's call."""
        return (self.dp_dcn, self.dp_ici, self.pp, self.tp,
                bool(self.kernel), self.expert, self.expert_over_dcn)

    def knob_string(self) -> str:
        """Descriptive candidate name, e.g.
        ``dcn2_dp1_pp2_tp2_mb2_z3_vp_int8_ov-matmul``."""
        parts = []
        if self.dp_dcn > 1:
            parts.append(f"dcn{self.dp_dcn}")
        parts += [f"dp{self.dp_ici}", f"pp{self.pp}", f"tp{self.tp}"]
        if self.pipeline:
            parts.append(f"mb{self.num_microbatches}")
            if self.virtual_stages > 1:
                parts.append(f"vs{self.virtual_stages}")
        if self.zero_stage:
            parts.append(f"z{self.zero_stage}")
        if self.vocab_parallel:
            parts.append("vp")
        if self.collective_precision:
            parts.append(self.collective_precision)
        if self.comm_overlap:
            parts.append(f"ov-{self.comm_overlap}")
        if self.compressor != "none":
            parts.append(self.compressor)
        if self.kernel:
            parts.append("kern")
        if self.expert:
            parts.append(f"ex{self.expert}"
                         + ("xdcn" if self.expert_over_dcn else ""))
            parts.append(f"cf{self.capacity_factor:g}")
        return "_".join(parts)

    def knobs(self) -> dict:
        return {"pp": self.pp, "tp": self.tp,
                "virtual_stages": self.virtual_stages,
                "num_microbatches": self.num_microbatches,
                "vocab_parallel": self.vocab_parallel,
                "zero_stage": self.zero_stage,
                "comm_overlap": self.comm_overlap,
                "collective_precision": self.collective_precision,
                "compressor": self.compressor,
                "kernel": self.kernel,
                "expert": self.expert,
                "num_experts": self.num_experts,
                "capacity_factor": self.capacity_factor,
                "expert_over_dcn": self.expert_over_dcn}


@dataclasses.dataclass
class SearchSpace:
    """Bounds of the cross-product.  ``None`` degree lists derive from
    the topology (every divisor that keeps tp/pp within a slice);
    shrink any field to bound the search, e.g.
    ``SearchSpace(tp=(1, 2), num_microbatches=(4,))``."""

    pp: Optional[Sequence[int]] = None
    tp: Optional[Sequence[int]] = None
    num_microbatches: Sequence[int] = (1, 2, 4)
    vocab_parallel: Sequence[bool] = (False, True)
    zero_stage: Sequence[int] = (0, 1, 2, 3)
    comm_overlap: Sequence[Optional[str]] = (None, "matmul")
    collective_precision: Sequence[Optional[str]] = (None, "bf16", "int8")
    compressor: Sequence[str] = ("none", "bf16_ef")
    # The fused-kernel tier: "fused" points are emitted only where an
    # enabling knob admits a kernel (int8 tp_psum for quant_ring,
    # matmul overlap for the fused ring step), so the kernel column
    # never multiplies the whole space.
    kernel: Sequence[Optional[str]] = (None, "fused")
    # Merge the hand-enumerated zoo into the frontier as seeds, so the
    # searched winner can never score below the zoo winner.
    seed_zoo: bool = True


@dataclasses.dataclass
class Candidate:
    """One synthesized candidate through the pipeline stages."""

    name: str
    config: Optional[KnobConfig]       # None for zoo seeds
    strategy: object
    spec: ResourceSpec                 # the derived (re-factored) spec
    cost: Optional[StrategyCost] = None
    lint_codes: tuple = ()


@dataclasses.dataclass
class SearchResult:
    """Everything the search did, with no silent caps: every pruned
    config is counted, lint prunes carry their codes."""

    topology: dict
    raw_configs: int = 0
    skipped_unbuildable: int = 0
    deduped: int = 0
    pruned_dominated: int = 0
    pruned_lint: int = 0
    priced: int = 0
    lint_pruned: list = dataclasses.field(default_factory=list)
    frontier: list = dataclasses.field(default_factory=list)  # Candidate,
    # best-first (feasible before infeasible, then comm time)

    @property
    def winner(self) -> Optional[Candidate]:
        return self.frontier[0] if self.frontier else None

    def counts(self) -> dict:
        return {"raw_configs": self.raw_configs,
                "skipped_unbuildable": self.skipped_unbuildable,
                "deduped": self.deduped,
                "pruned_dominated": self.pruned_dominated,
                "pruned_lint": self.pruned_lint,
                "priced": self.priced}

    def report(self, top: int = 10) -> str:
        """The search report: enumeration/prune/price counts, the
        frontier top-``top`` with per-level comm breakdown, and the
        winner's knob string."""
        lines = [
            f"search over {self.topology}: {self.raw_configs} raw "
            f"configs, {self.skipped_unbuildable} unbuildable, "
            f"{self.deduped} duplicate, {self.pruned_dominated} "
            f"pruned by dominance, {self.pruned_lint} pruned by lint, "
            f"{self.priced} priced"]
        for name, codes in self.lint_pruned:
            lines.append(f"  lint-pruned {name}: {', '.join(codes)}")
        lines.append(
            f"{'candidate':<34} {'t_ms':>8} {'ici_MB':>8} {'dcn_MB':>8} "
            f"{'dcn_ms':>7} {'mem_GB':>7}  feasible")
        for cand in self.frontier[:top]:
            c = cand.cost
            lines.append(
                f"{cand.name:<34} {c.comm_time_s * 1e3:>8.3f} "
                f"{(c.comm_bytes - c.dcn_bytes) / 1e6:>8.2f} "
                f"{c.dcn_bytes / 1e6:>8.2f} {c.dcn_time_s * 1e3:>7.3f} "
                f"{c.mem_bytes_per_device / 1e9:>7.2f}  "
                f"{'yes' if c.feasible else 'NO'}")
        if self.winner is not None:
            lines.append(f"winner: {self.winner.name}")
        return "\n".join(lines)


# --------------------------------------------------------------------------- #
# Enumeration
# --------------------------------------------------------------------------- #
def _divisors(n: int) -> list[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_configs(trainable: Trainable, resource_spec: ResourceSpec,
                      space: Optional[SearchSpace] = None
                      ) -> list[KnobConfig]:
    """The raw cross-product for this (topology, trainable) pair.

    Structural constraints applied here (not silent prunes — these
    points can never lower at all):

    * tp and pp never span slices: both factor the *within-slice*
      device count; the dcn axis carries only data parallelism.
    * stage-structured trainables take pp from the divisors of the
      stage count (``virtual_stages`` absorbing the remainder);
      generic trainables get the collective/GSPMD families (pp = 1).
    * knobs with no boundary in a given point (vocab/overlap at tp=1,
      a compressor under ZeRO) are not emitted — the plan linter would
      flag each as a silent no-op or conflict.
    """
    space = space or SearchSpace()
    shape = resource_spec.resolved_mesh_shape()
    n = resource_spec.num_devices()
    n_dcn = shape.get(const.DCN_AXIS,
                      max(int(getattr(resource_spec, "num_slices", 1)), 1))
    n_ici = n // max(n_dcn, 1)
    stage_structured = getattr(trainable, "num_stages", None) is not None
    num_stages = getattr(trainable, "num_stages", None)
    has_shared = bool(getattr(trainable, "has_shared", False))

    if stage_structured:
        pp_choices = [p for p in (space.pp or _divisors(n_ici))
                      if n_ici % p == 0 and num_stages % p == 0]
    else:
        pp_choices = [1]
    if not stage_structured and int(getattr(trainable, "num_experts", 0)
                                    or 0) > 1:
        # An expert-sharded trainable's loss binds the ``expert`` mesh
        # axis at trace time: only the expert family (degree 1 = the
        # dense point) can lower it, so the generic dp/tp/zero families
        # are not emitted at all.
        pp_choices = []

    configs = []
    for pp in pp_choices:
        tp_choices = [t for t in (space.tp or _divisors(n_ici // pp))
                      if (n_ici // pp) % t == 0]
        for tp in tp_choices:
            dp_ici = n_ici // (pp * tp)
            base = dict(dp_dcn=n_dcn, dp_ici=dp_ici, pp=pp, tp=tp,
                        pipeline=stage_structured)
            if stage_structured:
                base["virtual_stages"] = num_stages // pp
            mb_choices = (space.num_microbatches if stage_structured
                          else (1,))
            for M in mb_choices:
                for vp in space.vocab_parallel:
                    if vp and (tp <= 1 or not has_shared
                               or not stage_structured):
                        continue
                    for zero in space.zero_stage:
                        if not stage_structured and zero > 1 and tp > 1:
                            continue
                        for ov in space.comm_overlap:
                            if ov and (tp <= 1 or not stage_structured):
                                continue
                            for prec in space.collective_precision:
                                for comp in space.compressor:
                                    if comp != "none" and (
                                            zero or prec
                                            or not stage_structured
                                            and tp > 1):
                                        continue
                                    if prec and tp <= 1 and zero != 3 \
                                            and not (zero == 0
                                                     and comp == "none"):
                                        continue
                                    if prec and not stage_structured:
                                        continue
                                    for kern in space.kernel:
                                        if kern and not (
                                                stage_structured
                                                and tp > 1
                                                and ((prec == "int8"
                                                      and ov is None)
                                                     or ov == "matmul")):
                                            # No enabling knob — the
                                            # point would be the ADT090
                                            # no-op contradiction.
                                            continue
                                        configs.append(KnobConfig(
                                            num_microbatches=M,
                                            vocab_parallel=vp,
                                            zero_stage=zero,
                                            compressor=comp,
                                            collective_precision=prec,
                                            comm_overlap=ov,
                                            kernel=kern, **base))

    # ---- expert-parallel family (PR 18) ------------------------------- #
    # A generic trainable that declares its MoE shape (``num_experts``
    # attribute — make_moe_lm_trainable sets it) additionally gets the
    # expert-lowering family: every within-slice expert degree that
    # divides both the slice and the expert count (degree 1 is the
    # dense point — experts replicated, no all_to_all — so
    # dense-vs-MoE is the cost model's election, decided by the a2a
    # term vs. the replicated tables' memory + sync), plus the
    # across-DCN placements when the topology is multi-slice (emitted
    # despite ADT061's warning so inverted link constants can elect
    # them).  The moe_a2a wire precision and the a2a_ring kernel ride
    # the same precision/kernel columns as every other boundary.
    num_experts = int(getattr(trainable, "num_experts", 0) or 0)
    if not stage_structured and num_experts > 1:
        cap_f = float(getattr(trainable, "capacity_factor", 2.0) or 2.0)
        moe = dict(num_experts=num_experts, capacity_factor=cap_f,
                   pipeline=False)
        placements = []
        for e_ici in _divisors(n_ici):
            if num_experts % e_ici:
                continue
            placements.append((e_ici, n_dcn, n_ici // e_ici, False))
            if n_dcn > 1 and num_experts % (n_dcn * e_ici) == 0:
                placements.append((n_dcn * e_ici, 1, n_ici // e_ici,
                                   True))
        for e, dcn, dp_ici, over in placements:
            for zero in space.zero_stage:
                for prec in space.collective_precision:
                    for kern in space.kernel:
                        if kern and not (prec == "int8" and e > 1
                                         and not over):
                            # a2a_ring needs the int8 moe_a2a wire and
                            # an actual within-slice ring to fuse.
                            continue
                        if prec and e <= 1:
                            # degree-1 expert axis has no a2a boundary
                            # for the wire policy to narrow (the ADT020
                            # orphan-slot contradiction).
                            continue
                        configs.append(KnobConfig(
                            dp_dcn=dcn, dp_ici=dp_ici, pp=1, tp=1,
                            zero_stage=zero, collective_precision=prec,
                            kernel=kern, expert=e, expert_over_dcn=over,
                            **moe))
    return configs


# --------------------------------------------------------------------------- #
# Dominance proxies
# --------------------------------------------------------------------------- #
@dataclasses.dataclass
class _Stats:
    stage_bytes: float
    shared_bytes: float
    hidden: int
    tokens: Optional[int]
    vocab_rows: int
    n_leaves: int
    dcn_penalty: float     # ici_gbps / dcn_gbps — DCN bytes in
    # ici-equivalent units for the comm proxy
    flops_rate: float


def _stats(trainable, cm: CostModel) -> _Stats:
    infos = list(trainable.var_infos())
    shared = sum(i.byte_size for i in infos
                 if i.name.startswith("shared/"))
    total = sum(i.byte_size for i in infos)
    vocab_rows = max((i.shape[0] for i in infos if len(i.shape) == 2),
                     default=1)
    bw_dcn, _ = cm._dcn_link()
    return _Stats(
        stage_bytes=float(total - shared), shared_bytes=float(shared),
        hidden=cm._hidden_dim(trainable),
        tokens=cm._hints(trainable)[0],
        vocab_rows=int(vocab_rows), n_leaves=len(infos),
        dcn_penalty=max(cm.chip.ici_gbps * 1e9 / max(bw_dcn, 1.0), 1.0),
        flops_rate=cm.chip.peak_bf16_tflops * 1e12 * 0.4)


def _proxies(cfg: KnobConfig, st: _Stats) -> tuple[float, float, float]:
    """(comm-bytes, compute-seconds, memory-bytes) dominance proxies —
    a coarse closed-form model used ONLY to drop points that are
    pointwise no better than a sibling on the SAME mesh factorization;
    ranking always comes from the real cost model.  DCN bytes count at
    the ici/dcn bandwidth ratio so a cross-slice byte is never cheap."""
    def ring(k: int) -> float:
        return 2.0 * (k - 1) / k if k > 1 else 0.0

    dp = cfg.dp_ici * cfg.dp_dcn
    M, V = cfg.num_microbatches, cfg.virtual_stages
    stage_dev = st.stage_bytes / (cfg.pp * cfg.tp)
    shared_dev = st.shared_bytes / (cfg.tp if cfg.vocab_parallel else 1)
    per_dev = stage_dev + shared_dev

    grad_f = {"none": 1.0, "bf16_ef": 0.5, "int8_ef": 0.5,
              "int8_ring": 0.25, "powersgd": 0.02}.get(cfg.compressor, 1.0)
    if cfg.collective_precision and cfg.zero_stage == 0 \
            and cfg.compressor == "none":
        grad_f = 0.5
    wire_f = 0.5 if cfg.collective_precision else 1.0
    # quant_ring: TRUE s8 chunks on the tp boundary wire (less comm)
    # at more q/dq compute — mirrors the cost model's monotone trade so
    # a kernel point and its composed sibling never dominate each other.
    ring_kern = (cfg.kernel and cfg.collective_precision == "int8"
                 and cfg.comm_overlap is None and cfg.tp > 1)
    if ring_kern:
        wire_f = 0.25

    sync_f = ring(cfg.dp_ici) + st.dcn_penalty * ring(cfg.dp_dcn) \
        / max(cfg.dp_ici, 1)
    comm = grad_f * sync_f * per_dev
    tokens_local = (st.tokens / dp) if st.tokens else 0.0
    if cfg.tp > 1 and tokens_local:
        comm += 2.0 * ring(cfg.tp) * V * tokens_local * st.hidden * 2.0 \
            * wire_f
        if cfg.vocab_parallel:
            comm += 2.0 * ring(cfg.tp) * tokens_local \
                * (st.hidden + 3.0) * 4.0 * wire_f
    if cfg.pipeline and tokens_local and cfg.pp > 1:
        T = M * V + cfg.pp - 1
        comm += 2.0 * T * (tokens_local / M) * st.hidden * 2.0
    if cfg.expert > 1 and tokens_local:
        # MoE dispatch/combine payload (capacity-padded, 4 passes),
        # narrowed by the moe_a2a wire factor; DCN-placed a2a counts at
        # the bandwidth-ratio penalty like every cross-slice byte.  The
        # q/dq passes charge compute — mirrors the cost model's
        # monotone precision trade so a narrowed candidate and its
        # fp32 sibling never dominate each other.
        a2a_f = {None: 1.0, "bf16": 0.5, "int8": 0.25}.get(
            cfg.collective_precision, 1.0)
        a2a = 4.0 * 2.0 * cfg.capacity_factor * tokens_local \
            * st.hidden * 2.0 * (cfg.expert - 1) / cfg.expert * a2a_f
        comm += a2a * (st.dcn_penalty if cfg.expert_over_dcn else 1.0)

    launches = 2.0
    if cfg.zero_stage >= 3:
        launches += st.n_leaves * V
    if cfg.tp > 1:
        launches += 2.0 * M * V
    if cfg.pipeline and cfg.pp > 1:
        launches += 2.0 * (M * V + cfg.pp - 1)
    if cfg.kernel and cfg.comm_overlap == "matmul" and cfg.tp > 1:
        # The fused ring step shrinks per-hop launch overhead.
        launches -= 2.0 * M * V * 0.8
    compute = COLLECTIVE_ALPHA * launches
    if cfg.collective_precision and cfg.tp > 1 and tokens_local:
        compute += 2.0 * V * tokens_local * st.hidden * 1e-10 \
            * (2.0 if ring_kern else 1.0)
    if cfg.expert > 1 and tokens_local and cfg.collective_precision:
        # the moe_a2a q/dq passes (the offsetting term of the a2a wire
        # saving above)
        compute += 4.0 * 2.0 * cfg.capacity_factor * tokens_local \
            * st.hidden * 1e-10
    if cfg.pipeline and cfg.pp > 1 and st.tokens:
        bubble = (cfg.pp - 1) / (M * V + cfg.pp - 1)
        model_elems = (st.stage_bytes + st.shared_bytes) / 4.0
        compute += bubble * 2.0 * st.tokens * model_elems \
            / (dp * cfg.pp * cfg.tp) / st.flops_rate

    opt_div = dp if cfg.zero_stage >= 1 else 1
    grad_div = dp if cfg.zero_stage >= 2 else 1
    param_div = dp if cfg.zero_stage >= 3 else 1
    mem = per_dev * (1.0 / param_div + 1.0 / grad_div + 2.0 / opt_div)
    if tokens_local:
        mem += tokens_local * st.vocab_rows * 4.0 \
            / (cfg.tp if cfg.vocab_parallel else 1)
    return comm, compute, mem


def _dominated(a: tuple, b: tuple) -> bool:
    """True when ``a`` is (weakly) Pareto-dominated by ``b``."""
    return all(y <= x for x, y in zip(a, b)) \
        and any(y < x for x, y in zip(a, b))


def cm_key(spec: ResourceSpec) -> tuple:
    """Cache key for per-mesh cost models: one factorization, one
    model."""
    return tuple(sorted(spec.mesh_shape.items()))


# --------------------------------------------------------------------------- #
# The search
# --------------------------------------------------------------------------- #
def search_strategies(trainable: Trainable,
                      resource_spec: ResourceSpec,
                      space: Optional[SearchSpace] = None, *,
                      cost_model: Optional[CostModel] = None,
                      global_batch: Optional[int] = None,
                      seed_builders: Optional[Sequence] = None,
                      **cost_model_kwargs) -> SearchResult:
    """Run the full enumerate → dominance-prune → lint → price pipeline
    for one (trainable, topology) pair; see the module docstring.

    ``global_batch`` (when known, e.g. from AutoStrategy's
    ``example_batch``) screens pipeline points whose
    ``replicas × num_microbatches`` does not divide the batch — the
    same screen AutoStrategy applies to the zoo.

    ``seed_builders`` replaces :func:`default_candidates` as the seed
    list when ``space.seed_zoo`` is on (how ``AutoStrategy(search=True,
    candidates=[...])`` keeps honoring an explicit candidate list).

    Returns a :class:`SearchResult` whose frontier is best-first; the
    winner's strategy carries its mesh factorization in
    ``graph_config.mesh_axes``, which ``AutoDist`` honors at lowering.
    """
    if not isinstance(resource_spec, ResourceSpec):
        resource_spec = ResourceSpec(resource_spec)
    space = space or SearchSpace()
    cm = cost_model or CostModel(resource_spec, **cost_model_kwargs)
    stage_structured = getattr(trainable, "num_stages", None) is not None

    configs = enumerate_configs(trainable, resource_spec, space)
    result = SearchResult(topology=dict(resource_spec.resolved_mesh_shape()),
                          raw_configs=len(configs))

    # ---- build ------------------------------------------------------- #
    built: list[Candidate] = []
    seen_content: set = set()
    for cfg in configs:
        if global_batch is not None and cfg.pipeline:
            repl = cfg.dp_dcn * cfg.dp_ici
            if global_batch % max(repl * cfg.num_microbatches, 1):
                result.skipped_unbuildable += 1
                continue
        try:
            derived = resource_spec.with_mesh(cfg.mesh())
            builder = builder_from_knobs(cfg.knobs(),
                                         stage_structured=stage_structured)
            strategy = builder.build(trainable, derived)
        except ValueError as e:
            logging.debug("search config %s skipped: %s",
                          cfg.knob_string(), e)
            result.skipped_unbuildable += 1
            continue
        if not stage_structured and cfg.tp > 1 and not any(
                nc.partitioner is not None and nc.partitioner.spec
                and any(const.MODEL_AXIS in (e if isinstance(
                    e, (list, tuple)) else [e])
                        for e in nc.partitioner.spec)
                for nc in strategy.node_configs):
            # No variable matched the TP rule table: the "tp" plan is a
            # degenerate replicas=1 replication that idles every device
            # off the model axis yet prices near-zero comm — the
            # Pipeline builder raises for the stage analog; synthesized
            # GSPMD candidates get the same structural rejection here.
            logging.debug("search config %s skipped: no variable "
                          "matched the TP rules", cfg.knob_string())
            result.skipped_unbuildable += 1
            continue
        content = json.dumps([n.to_dict() for n in strategy.node_configs]
                             + [strategy.graph_config.to_dict()],
                             sort_keys=True)
        if content in seen_content:
            result.deduped += 1
            continue
        seen_content.add(content)
        built.append(Candidate(name=cfg.knob_string(), config=cfg,
                               strategy=strategy, spec=derived))

    # ---- dominance prune (within one mesh factorization) ------------- #
    # Deliberately AFTER building: only a config that actually builds
    # may dominate (an unbuildable dominator would orphan a buildable
    # point).  The build pass is cheap (no compiles; ~1ms/config), so
    # correctness wins over pruning earlier.
    st = _stats(trainable, cm)
    by_mesh: dict = {}
    for cand in built:
        by_mesh.setdefault(cand.config.mesh_key(), []).append(cand)
    survivors: list[Candidate] = []
    for group in by_mesh.values():
        proxies = [_proxies(c.config, st) for c in group]
        for i, cand in enumerate(group):
            if any(j != i and _dominated(proxies[i], proxies[j])
                   for j in range(len(group))):
                result.pruned_dominated += 1
            else:
                survivors.append(cand)

    # ---- zoo seeds --------------------------------------------------- #
    if space.seed_zoo:
        from autodist_tpu.simulator.auto_strategy import default_candidates

        builders = (list(seed_builders) if seed_builders is not None
                    else default_candidates())
        seen_names: dict = {}
        for builder in builders:
            name = type(builder).__name__
            seen_names[name] = seen_names.get(name, 0) + 1
            if seen_names[name] > 1:
                name = f"{name}#{seen_names[name]}"
            if name.startswith("SequenceParallel") \
                    and not getattr(trainable, "sequence_ready", False):
                continue   # AutoStrategy's own zoo screen
            try:
                strategy = builder.build(trainable, resource_spec)
            except ValueError:
                continue
            if stage_structured != (strategy.graph_config.lowering
                                    == "pipeline"):
                # A stage-structured trainable lowers through the
                # pipeline backend only (and a generic one never does);
                # a seed that cannot lower must not reach the frontier.
                continue
            if int(getattr(trainable, "num_experts", 0) or 0) > 1 \
                    and strategy.graph_config.lowering != "expert":
                # An expert-sharded loss binds the ``expert`` mesh axis
                # at trace time; only expert-lowering seeds can run it.
                continue
            axes = set(strategy.graph_config.mesh_axes or {})
            if axes and any(
                    a and a not in axes
                    for nc in strategy.node_configs
                    if nc.partitioner is not None
                    for entry in (nc.partitioner.spec or [])
                    for a in (entry if isinstance(entry, (list, tuple))
                              else [entry])):
                # A seed whose variable specs name a mesh axis this
                # topology lacks (e.g. gspmd TensorParallel on a spec
                # with no model axis) cannot lower here — the same
                # does-not-fit screen as a build-time ValueError.
                continue
            if (global_batch is not None
                    and strategy.graph_config.lowering == "pipeline"):
                M = int(strategy.graph_config.parallel.get(
                    "num_microbatches", 1))
                repl = max(strategy.graph_config.replicas, 1)
                if global_batch % max(repl * M, 1):
                    continue
            content = json.dumps(
                [n.to_dict() for n in strategy.node_configs]
                + [strategy.graph_config.to_dict()], sort_keys=True)
            if content in seen_content:
                result.deduped += 1
                continue
            seen_content.add(content)
            survivors.append(Candidate(name=f"zoo:{name}", config=None,
                                       strategy=strategy,
                                       spec=resource_spec))

    # ---- plan lint (ERROR ⇒ pruned, counted, reported) ---------------- #
    from autodist_tpu.analysis import lint_plan

    linted: list[Candidate] = []
    for cand in survivors:
        report = lint_plan(cand.strategy, resource_spec=cand.spec,
                           trainable=trainable)
        if report.errors:
            codes = sorted({d.code for d in report.errors})
            result.pruned_lint += 1
            result.lint_pruned.append((cand.name, codes))
            logging.warning("search candidate %s pruned by plan lint: %s",
                            cand.name, codes)
            continue
        cand.lint_codes = tuple(sorted(report.codes()))
        linted.append(cand)

    # ---- price ------------------------------------------------------- #
    # Each candidate prices against a model bound to its OWN mesh
    # factorization (the cost model reads pp/tp/dcn from its spec, not
    # from the strategy): pricing a re-factored candidate with the
    # original spec's model would silently ignore its degrees.  One
    # model per distinct mesh, cached.
    models: dict = {cm_key(resource_spec): cm}
    for cand in linted:
        key = cm_key(cand.spec)
        if key not in models:
            models[key] = cm.with_spec(cand.spec)
        try:
            cand.cost = models[key].strategy_cost(trainable,
                                                  cand.strategy)
        except SpecMeshMismatch as e:
            logging.debug("search candidate %s unpriceable: %s",
                          cand.name, e)
            result.skipped_unbuildable += 1
            continue
        result.priced += 1
        result.frontier.append(cand)
    result.frontier.sort(
        key=lambda c: (c.cost.score, c.cost.num_collectives))
    return result


def program_lint_winner(result: SearchResult, trainable: Trainable,
                        batch=None, vocab_size: Optional[int] = None
                        ) -> "object":
    """Lower + compile the searched winner on its own mesh and run the
    program linter with the rule set its Strategy IR implies — the
    same gate ``tools/lint_strategy.py --zoo`` applies to every zoo
    candidate.  Returns the :class:`~autodist_tpu.analysis.diagnostics.
    LintReport` (callers gate on ``report.errors``)."""
    import jax

    from autodist_tpu.analysis import lint_program, rules_for_strategy
    from autodist_tpu.analysis.facts import compiled_text
    from autodist_tpu.autodist import AutoDist

    winner = result.winner
    if winner is None:
        raise ValueError("search produced no priced candidate")
    runner = AutoDist(winner.spec, "AllReduce").build(trainable,
                                                      winner.strategy)
    try:
        text = compiled_text(runner.lowered.step_fn, runner.state,
                             runner._place_batch(batch),
                             jax.random.PRNGKey(0))
    finally:
        runner.close()
    rules = rules_for_strategy(winner.strategy, vocab_size=vocab_size)
    return lint_program(text, rules, where=winner.name)
