"""StrategyBuilder base (≙ reference ``autodist/strategy/base.py``).

``StrategyBuilder.build(trainable, resource_spec) -> Strategy`` mirrors
``StrategyBuilder.build(graph_item, resource_spec)`` (reference
``strategy/base.py:102-117``).  Compilation (device resolution) lives in
``kernel.lowering.make_plan`` — the mesh is the resolved device set.
"""
from __future__ import annotations

import abc

from autodist_tpu import const
from autodist_tpu.capture import Trainable
from autodist_tpu.resource import ResourceSpec
from autodist_tpu.strategy.ir import GraphConfig, Strategy


class StrategyBuilder(abc.ABC):
    """Base for all strategy builders."""

    @abc.abstractmethod
    def build(self, trainable: Trainable, resource_spec: ResourceSpec) -> Strategy:
        ...

    @staticmethod
    def num_replicas(resource_spec: ResourceSpec) -> int:
        """Data-parallel replica count: the data axis times the DCN
        (cross-slice) axis on multi-slice topologies."""
        shape = resource_spec.resolved_mesh_shape()
        return shape.get(const.DATA_AXIS, 1) * shape.get(const.DCN_AXIS, 1)

    def _graph_config(self, resource_spec: ResourceSpec) -> GraphConfig:
        shape = resource_spec.resolved_mesh_shape()
        return GraphConfig(replicas=self.num_replicas(resource_spec),
                           mesh_axes=dict(shape))


def byte_size_load_fn(var_info) -> int:
    """Load function for greedy placement: variable byte size.

    Port of the pure planning logic of the reference
    (``ps_lb_strategy.py:96-117`` — itself adapted from TF's
    ``byte_size_load_fn``); unknown dims charged at 64 bytes/element is
    irrelevant here since JAX shapes are static.
    """
    return max(var_info.byte_size, 1)


def greedy_assign(infos, num_bins: int, load_fn=byte_size_load_fn):
    """Greedy bin packing: largest first onto least-loaded bin
    (≙ the reference's PS load balancer loop, ``ps_lb_strategy.py:42-62``).
    Returns {var_name: bin_index}."""
    loads = [0] * max(num_bins, 1)
    assignment = {}
    for info in sorted(infos, key=load_fn, reverse=True):
        i = loads.index(min(loads))
        assignment[info.name] = i
        loads[i] += load_fn(info)
    return assignment
