"""The strategy builder catalog.

One builder per reference strategy (``autodist/strategy/``), each emitting
the TPU-native Strategy IR.  The reference's GPU/PS placement decisions map
onto mesh-sharding decisions:

==========================  =================================================
reference builder           TPU-native realization
==========================  =================================================
PS                          ZeRO-1: every param's optimizer update runs on a
                            flat 1/N shard (grads reduce-scattered ≙ PS
                            accumulators), params re-gathered (≙ pull).
PSLoadBalancing             same lowering; the greedy byte-size bin packing
                            is retained as serialized provenance metadata
                            (``reduction_destination`` tags).  The ZeRO-1
                            lowering spreads optimizer state evenly over the
                            mesh regardless — strictly better balance than
                            the reference's greedy packing.
PartitionedPS               FSDP/ZeRO-3: params stored sharded on the
                            partition axis, gathered on use.
UnevenPartitionedPS         identical lowering; uneven shards become padding
                            (GSPMD-style), kept for API parity.
AllReduce                   bucketed (≙ chunk_size groups / ScopedAllocator)
                            pmean with optional compression.
PartitionedAR               ZeRO-2: grads reduce-scattered along axis 0,
                            sharded update, all-gather params.
RandomAxisPartitionAR       same with a seeded random partition axis.
Parallax                    hybrid: dense → AllReduce; sparse/embedding →
                            vocab-axis-sharded PS (FSDP on the table).
==========================  =================================================
"""
from __future__ import annotations

import hashlib

from autodist_tpu.capture import Trainable, VarInfo
from autodist_tpu.resource import ResourceSpec
from autodist_tpu.strategy.base import StrategyBuilder, greedy_assign
from autodist_tpu.strategy.ir import (AllReduceSynchronizer, NodeConfig,
                                      PartitionerConfig, PSSynchronizer,
                                      Strategy)


def _partition_str(shape, axis: int, num_shards: int) -> str:
    parts = ["1"] * max(len(shape), 1)
    parts[axis] = str(num_shards)
    return ",".join(parts)


class PS(StrategyBuilder):
    """All variables synchronized PS-style (reference
    ``ps_strategy.py:21-77``)."""

    def __init__(self, local_proxy_variable=False, sync=True, staleness=0):
        self.local_proxy_variable = local_proxy_variable
        self.sync = sync
        self.staleness = staleness

    def _node(self, info: VarInfo, dest: str = "") -> NodeConfig:
        return NodeConfig(
            var_name=info.name,
            synchronizer=PSSynchronizer(
                reduction_destination=dest,
                local_replication=self.local_proxy_variable,
                sync=self.sync, staleness=self.staleness),
            is_sparse=info.is_sparse)

    def build(self, trainable, resource_spec):
        nodes = [self._node(i) for i in trainable.var_infos()]
        return Strategy(node_configs=nodes,
                        graph_config=self._graph_config(resource_spec))


class PSLoadBalancing(PS):
    """PS with greedy byte-size load balancing (reference
    ``ps_lb_strategy.py:23-117``).  The bin index becomes the
    ``reduction_destination`` shard tag — serialized *metadata only*
    (strategy provenance / parity with the reference's placement
    decisions): the ZeRO-1 lowering spreads optimizer state uniformly
    over the mesh, which strictly dominates greedy packing, so the tags
    are not consumed by any execution path."""

    def build(self, trainable, resource_spec):
        infos = trainable.var_infos()
        bins = self.num_replicas(resource_spec)
        assignment = greedy_assign(infos, bins)
        nodes = [self._node(i, dest=f"shard:{assignment[i.name]}")
                 for i in infos]
        return Strategy(node_configs=nodes,
                        graph_config=self._graph_config(resource_spec))


class PartitionedPS(PSLoadBalancing):
    """Axis-partitioned PS ⇒ FSDP (reference
    ``partitioned_ps_strategy.py:28-135``).  Variables whose dim-0 can be
    split are stored sharded; the rest fall back to flat PS."""

    def __init__(self, local_proxy_variable=False, sync=True, staleness=0,
                 split_axis=0):
        super().__init__(local_proxy_variable, sync, staleness)
        self.split_axis = split_axis

    def num_shards(self, info: VarInfo, n: int) -> int:
        """Shard count for one variable.  The reference used the smallest
        divisor ≥2 of dim0 (``partitioned_ps_strategy.py:125-135``) to
        spread shards over PS nodes; on a mesh the natural count is the
        data-axis size (padding covers non-divisibility)."""
        if not info.shape or len(info.shape) <= self.split_axis:
            return 1
        if info.shape[self.split_axis] < 2:
            return 1
        return n

    def build(self, trainable, resource_spec):
        n = self.num_replicas(resource_spec)
        infos = trainable.var_infos()
        assignment = greedy_assign(infos, n)
        nodes = []
        for info in infos:
            node = self._node(info, dest=f"shard:{assignment[info.name]}")
            shards = self.num_shards(info, n)
            if shards > 1:
                node.partitioner = PartitionerConfig(
                    partition_str=_partition_str(
                        info.shape, self.split_axis, shards))
            nodes.append(node)
        return Strategy(node_configs=nodes,
                        graph_config=self._graph_config(resource_spec))


class UnevenPartitionedPS(PartitionedPS):
    """Uneven-shard variant: the reference's ``get_num_shards`` picks the
    *smallest non-divisor* ≥ 2 of dim0 so shards come out unequal
    (``uneven_partition_ps_strategy.py:126-135``); that count is emitted
    into the strategy IR for serialization parity.  At lowering time the
    mesh resolver maps any shard count onto the mesh axis (≙ the
    reference compiler overriding device strings,
    ``strategy/base.py:120-168``), where non-divisible dims are realized
    as a padded last shard — the TPU form of unevenness."""

    def num_shards(self, info: VarInfo, n: int) -> int:
        if not info.shape or len(info.shape) <= self.split_axis:
            return 1
        dim = info.shape[self.split_axis]
        if dim < 2:
            return 1
        for i in range(2, dim):
            if dim % i:
                return i
        return dim


class AllReduce(StrategyBuilder):
    """Dense allreduce with bucketing + compression (reference
    ``all_reduce_strategy.py:21-91``)."""

    def __init__(self, chunk_size=128, compressor="none"):
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        self.chunk_size = chunk_size
        self.compressor = compressor

    def build(self, trainable, resource_spec):
        nodes = []
        for idx, info in enumerate(trainable.var_infos()):
            nodes.append(NodeConfig(
                var_name=info.name,
                synchronizer=AllReduceSynchronizer(
                    compressor=self.compressor,
                    group=idx // self.chunk_size),
                is_sparse=info.is_sparse))
        return Strategy(node_configs=nodes,
                        graph_config=self._graph_config(resource_spec))


class PartitionedAR(StrategyBuilder):
    """Partition + allreduce each shard ⇒ gradient reduce-scatter / ZeRO-2
    (reference ``partitioned_all_reduce_strategy.py:25-130``)."""

    def __init__(self, chunk_size=128, compressor="none", split_axis=0):
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        self.chunk_size = chunk_size
        self.compressor = compressor
        self.split_axis = split_axis

    def _choose_axis(self, info: VarInfo) -> int:
        if info.shape and len(info.shape) > self.split_axis \
                and info.shape[self.split_axis] >= 2:
            return self.split_axis
        return -1

    def build(self, trainable, resource_spec):
        n = self.num_replicas(resource_spec)
        nodes = []
        for idx, info in enumerate(trainable.var_infos()):
            axis = self._choose_axis(info)
            node = NodeConfig(
                var_name=info.name,
                synchronizer=AllReduceSynchronizer(
                    compressor=self.compressor,
                    group=idx // self.chunk_size),
                is_sparse=info.is_sparse)
            if axis >= 0 and n > 1:
                node.partitioner = PartitionerConfig(
                    partition_str=_partition_str(info.shape, axis, n))
            nodes.append(node)
        return Strategy(node_configs=nodes,
                        graph_config=self._graph_config(resource_spec))


class RandomAxisPartitionAR(PartitionedAR):
    """PartitionedAR with a per-variable random partition axis among dims
    of size >1 (reference
    ``random_axis_partition_all_reduce_strategy.py:26-141``); seeded by
    variable name for cross-host determinism."""

    def __init__(self, chunk_size=128, compressor="none", seed=0):
        super().__init__(chunk_size, compressor)
        self.seed = seed

    def _choose_axis(self, info: VarInfo) -> int:
        cand = [i for i, d in enumerate(info.shape) if d >= 2]
        if not cand:
            return -1
        h = int(hashlib.md5(f"{self.seed}:{info.name}".encode()).hexdigest(), 16)
        return cand[h % len(cand)]


class Parallax(StrategyBuilder):
    """Hybrid: dense vars → AllReduce, sparse/embedding vars →
    partitioned PS on the vocab axis (reference
    ``parallax_strategy.py:24-71``, arxiv 1808.02621)."""

    def __init__(self, chunk_size=128, compressor="none",
                 local_proxy_variable=False, sync=True, staleness=0):
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        self.chunk_size = chunk_size
        self.compressor = compressor
        self.local_proxy_variable = local_proxy_variable
        self.sync = sync
        self.staleness = staleness

    def build(self, trainable, resource_spec):
        n = self.num_replicas(resource_spec)
        infos = trainable.var_infos()
        sparse = [i for i in infos if i.is_sparse]
        assignment = greedy_assign(sparse, n)
        nodes = []
        dense_idx = 0
        for info in infos:
            if info.is_sparse:
                node = NodeConfig(
                    var_name=info.name,
                    synchronizer=PSSynchronizer(
                        reduction_destination=f"shard:{assignment[info.name]}",
                        local_replication=self.local_proxy_variable,
                        sync=self.sync, staleness=self.staleness),
                    is_sparse=True)
                if info.shape and info.shape[0] >= 2 and n > 1:
                    node.partitioner = PartitionerConfig(
                        partition_str=_partition_str(info.shape, 0, n))
            else:
                node = NodeConfig(
                    var_name=info.name,
                    synchronizer=AllReduceSynchronizer(
                        compressor=self.compressor,
                        group=dense_idx // self.chunk_size))
                dense_idx += 1
            nodes.append(node)
        return Strategy(node_configs=nodes,
                        graph_config=self._graph_config(resource_spec))


# ----------------------------------------------------------------------- #
# TPU-first extensions beyond reference parity.
# ----------------------------------------------------------------------- #
class GradAccumulation(StrategyBuilder):
    """Wrap any builder with gradient accumulation: each step scans
    ``steps`` microbatches before the one synchronization + optimizer
    update (global batches beyond device memory; not in the reference —
    its batch was bounded by what one GPU graph replica held)."""

    def __init__(self, builder: "StrategyBuilder | str | None" = None,
                 steps: int = 2):
        if steps < 1:
            raise ValueError("accumulation steps must be >= 1")
        if builder is None:
            builder = PSLoadBalancing()  # the AutoDist default builder
        elif isinstance(builder, str):
            builder = create(builder)
        self.builder = builder
        self.steps = steps

    def build(self, trainable, resource_spec):
        strategy = self.builder.build(trainable, resource_spec)
        strategy.graph_config.accum_steps = self.steps
        return strategy


class ZeRO(StrategyBuilder):
    """Weight-update/param sharding by stage: 1 → PS (opt-state sharding),
    2 → PartitionedAR (grad reduce-scatter), 3 → PartitionedPS (FSDP).
    (PAPERS.md 2004.13336; not in the reference — convenience alias.)"""

    def __init__(self, stage=1, **kw):
        if stage not in (1, 2, 3):
            raise ValueError("ZeRO stage must be 1, 2 or 3")
        self._impl = {1: PS, 2: PartitionedAR, 3: PartitionedPS}[stage](**kw)

    def build(self, trainable, resource_spec):
        return self._impl.build(trainable, resource_spec)


BUILDERS = {
    "PS": PS,
    "PSLoadBalancing": PSLoadBalancing,
    "PartitionedPS": PartitionedPS,
    "UnevenPartitionedPS": UnevenPartitionedPS,
    "AllReduce": AllReduce,
    "PartitionedAR": PartitionedAR,
    "RandomAxisPartitionAR": RandomAxisPartitionAR,
    "Parallax": Parallax,
    "ZeRO": ZeRO,
    "GradAccumulation": GradAccumulation,
}


def create(name: str, **kw) -> StrategyBuilder:
    """Builder factory by name (≙ reference ``Synchronizer.create``
    reflection, ``synchronizer.py:90-104``)."""
    if name == "AutoStrategy":  # lazy: simulator imports this module
        from autodist_tpu.simulator import AutoStrategy
        return AutoStrategy(**kw)
    if name in ("Sharded", "TensorParallel", "FSDPSharded"):
        from autodist_tpu.strategy import gspmd_builders
        return getattr(gspmd_builders, name)(**kw)
    if name in ("SequenceParallel", "Pipeline", "ExpertParallel"):
        from autodist_tpu.strategy import parallel_builders
        return getattr(parallel_builders, name)(**kw)
    if name not in BUILDERS:
        raise ValueError(
            f"unknown strategy builder {name!r}; have "
            f"{sorted(BUILDERS) + ['AutoStrategy', 'Sharded', 'TensorParallel', 'FSDPSharded', 'SequenceParallel', 'Pipeline', 'ExpertParallel']}")
    return BUILDERS[name](**kw)


def builder_from_knobs(knobs, *, stage_structured: bool = True
                       ) -> StrategyBuilder:
    """Programmatic builder construction from a knob dict — the bridge
    the topology-aware search (:mod:`autodist_tpu.simulator.search`)
    uses to turn one point of the ``(pp, tp, vocab_parallel,
    zero_stage, comm_overlap, collective_precision, num_microbatches,
    compressor)`` cross-product into a buildable strategy.

    ``knobs`` keys (all optional; sensible no-op defaults): ``pp``,
    ``tp``, ``virtual_stages``, ``num_microbatches``,
    ``vocab_parallel``, ``zero_stage``, ``comm_overlap``,
    ``collective_precision`` (a bare precision string — resolved onto
    only the boundary classes the knob set actually emits, so the plan
    linter never sees an orphan slot), ``compressor``.

    Stage-structured trainables map onto :class:`~autodist_tpu.strategy.
    parallel_builders.Pipeline`; generic trainables onto the
    collective/GSPMD families (``tp>1`` → ``TensorParallel``,
    ``zero_stage`` s → ``ZeRO(stage=s)`` — PS / PartitionedAR /
    PartitionedPS per the classic ladder — else ``AllReduce``).
    Unrealizable combinations raise ``ValueError`` so a search loop can
    skip them the way AutoStrategy skips unbuildable zoo candidates.
    """
    k = dict(knobs or {})
    tp = max(int(k.get("tp", 1) or 1), 1)
    zero_stage = int(k.get("zero_stage", 0) or 0)
    compressor = k.get("compressor") or "none"
    vocab_parallel = bool(k.get("vocab_parallel", False))
    comm_overlap = k.get("comm_overlap") or None
    prec = k.get("collective_precision") or None
    kern = k.get("kernel") or None

    # Expert-parallel family (PR 18): an ``expert`` degree routes the
    # point onto ExpertParallel before the pipeline/generic resolution
    # below — the moe_a2a boundary is the ONLY one this lowering emits,
    # so a bare precision string resolves onto that slot alone and a
    # "fused" kernel request onto the a2a_ring (each rejected when its
    # enabling knob is absent, like every other family).
    expert = int(k.get("expert", 0) or 0)
    if expert and not stage_structured:
        from autodist_tpu.strategy.parallel_builders import ExpertParallel

        for knob, value in (("vocab_parallel", vocab_parallel),
                            ("comm_overlap", comm_overlap),
                            ("num_microbatches",
                             int(k.get("num_microbatches", 1) or 1) > 1)):
            if value:
                raise ValueError(
                    f"{knob} has no realization under the expert "
                    "lowering")
        over_dcn = bool(k.get("expert_over_dcn", False))
        precision = None
        if prec:
            if expert <= 1:
                raise ValueError(
                    f"collective_precision={prec!r} touches no boundary "
                    "of a degree-1 expert axis (no all_to_all to narrow)")
            precision = {"moe_a2a": prec}
        kernel = None
        if kern:
            if prec == "int8" and expert > 1 and not over_dcn:
                kernel = ("a2a_ring",)
            else:
                raise ValueError(
                    f"kernel='fused' enables no kernel for this expert "
                    f"knob set (expert={expert}, "
                    f"collective_precision={prec!r}, "
                    f"expert_over_dcn={over_dcn})")
        return ExpertParallel(
            zero_stage=zero_stage or None,
            compressor=compressor,
            collective_precision=precision,
            num_experts=int(k.get("num_experts", 0) or 0) or None,
            capacity_factor=float(k.get("capacity_factor", 2.0) or 2.0),
            expert_over_dcn=over_dcn,
            kernel=kernel)

    # Resolve a bare precision string onto only the boundary classes
    # this knob set emits (a full-slot policy on a plan without the
    # matching boundary is the ADT020 silent no-op the linter flags).
    precision = None
    if prec:
        slots = {}
        if tp > 1:
            slots["tp_psum"] = prec
            if vocab_parallel:
                slots["vocab_stats"] = prec
        if zero_stage >= 3:
            slots["zero3_gather"] = prec
        if zero_stage == 0 and compressor == "none":
            slots["grad"] = prec
        if not slots:
            raise ValueError(
                f"collective_precision={prec!r} touches no boundary of "
                f"this knob set (tp={tp}, zero_stage={zero_stage}, "
                f"compressor={compressor!r})")
        precision = slots

    # Resolve a "fused" kernel request onto only the kernels this knob
    # set enables (electing one without its knob is the ADT090
    # contradiction the Pipeline builder rejects).
    kernel = None
    if kern:
        if kern in ("fused", True):
            names = []
            if tp > 1 and comm_overlap is None \
                    and precision and precision.get("tp_psum") == "int8":
                names.append("quant_ring")
            if tp > 1 and comm_overlap == "matmul":
                names.append("collective_matmul")
            if not names:
                raise ValueError(
                    f"kernel='fused' enables no kernel for this knob "
                    f"set (tp={tp}, comm_overlap={comm_overlap!r}, "
                    f"collective_precision={prec!r})")
            kernel = tuple(names)
        else:
            kernel = kern

    if stage_structured:
        from autodist_tpu.strategy.parallel_builders import Pipeline

        return Pipeline(
            num_microbatches=max(int(k.get("num_microbatches", 1) or 1),
                                 1),
            virtual_stages=max(int(k.get("virtual_stages", 1) or 1), 1),
            tensor_parallel=tp,
            vocab_parallel=vocab_parallel,
            comm_overlap=comm_overlap,
            zero_stage=zero_stage or None,
            compressor=compressor,
            collective_precision=precision,
            kernel=kernel)

    # Generic (non-stage-structured) trainable: the collective/GSPMD
    # families.  Knobs with no realization here are rejected, not
    # silently dropped.
    for knob, value in (("vocab_parallel", vocab_parallel),
                        ("comm_overlap", comm_overlap),
                        ("collective_precision", prec),
                        ("kernel", kern),
                        ("num_microbatches",
                         int(k.get("num_microbatches", 1) or 1) > 1)):
        if value:
            raise ValueError(
                f"{knob} has no realization outside the pipeline "
                "lowering")
    if tp > 1:
        from autodist_tpu.strategy.gspmd_builders import TensorParallel

        if zero_stage > 1:
            raise ValueError(
                "zero_stage>1 with GSPMD tensor parallelism: use "
                "ZeRO (tp=1) or the pipeline lowering")
        if compressor != "none":
            raise ValueError(
                "compressor has no realization under GSPMD tensor "
                "parallelism (XLA owns the emitted collectives)")
        return TensorParallel(zero_stage=zero_stage or None)
    if zero_stage:
        if compressor != "none":
            raise ValueError("ZeRO sync reduces at full precision; "
                             "compression is an AllReduce knob")
        return ZeRO(stage=zero_stage)
    return AllReduce(compressor=compressor)
