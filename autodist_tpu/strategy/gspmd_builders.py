"""GSPMD strategy builders: rule-based mesh sharding (tensor/model
parallelism).

Beyond reference parity (``architecture.rst:49-51`` declared op-level
model parallelism unimplemented): these builders emit per-variable
multi-axis sharding specs lowered by :mod:`autodist_tpu.kernel.gspmd`.
"""
from __future__ import annotations

import re
from typing import Optional, Sequence

from autodist_tpu import const
from autodist_tpu.strategy.base import StrategyBuilder
from autodist_tpu.strategy.ir import (AllReduceSynchronizer, NodeConfig,
                                      PartitionerConfig, PSSynchronizer,
                                      Strategy)


class Sharded(StrategyBuilder):
    """Shard variables by (regex → per-dim mesh-axis spec) rules.

    ``rules`` example (megatron-style for the bundled transformer)::

        [(r"qkv/kernel$",  [None, None, "model", None]),
         (r"out/kernel$",  ["model", None, None]),
         (r"wi/kernel$",   [None, "model"]),
         (r"wo/kernel$",   ["model", None])]

    First matching rule wins; unmatched variables are replicated (pure DP
    via the sharded batch).

    ``zero_stage=1`` (alias ``zero1=True``) emits ``PSSynchronizer``
    node configs: the gspmd lowering shards each variable's
    optimizer-state leading dim over the data axes (GSPMD ZeRO-1; XLA
    derives the reduce-scatter/all-gather) — composable with TP sharding
    of the other dims.  Stages 2/3 are the *pipeline* lowering's knob
    (``parallel_builders.Pipeline(zero_stage=...)``); under gspmd the
    stage-3 layout is :class:`FSDPSharded` (params stored data-sharded,
    XLA inserts the gathers), so this builder rejects stage > 1 instead
    of silently training stage-1 semantics.
    """

    def __init__(self, rules: Sequence[tuple[str, list]] = (), *,
                 zero_stage: int = None, zero1: bool = None):
        from autodist_tpu.strategy.parallel_builders import \
            _resolve_zero_stage
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        stage = _resolve_zero_stage(zero_stage, zero1)
        if stage > 1:
            raise ValueError(
                f"zero_stage={stage} under the gspmd lowering: use "
                "FSDPSharded (the GSPMD-native sharded-parameter layout) "
                "or the pipeline builder's zero_stage knob")
        self.zero1 = bool(stage)

    def spec_for(self, info) -> Optional[list]:
        for pat, spec in self.rules:
            if pat.search(info.name):
                return list(spec)
        return None

    def build(self, trainable, resource_spec):
        nodes = []
        for info in trainable.var_infos():
            node = NodeConfig(var_name=info.name,
                              synchronizer=(PSSynchronizer()
                                            if getattr(self, "zero1", False)
                                            else AllReduceSynchronizer()),
                              is_sparse=info.is_sparse)
            spec = self.spec_for(info)
            if spec is not None:
                if len(spec) != len(info.shape):
                    raise ValueError(
                        f"rule spec {spec} does not match rank of "
                        f"{info.name} {info.shape}")
                node.partitioner = PartitionerConfig(spec=spec)
            nodes.append(node)
        gc = self._graph_config(resource_spec)
        gc.lowering = "gspmd"
        return Strategy(node_configs=nodes, graph_config=gc)


# Default megatron-style rules matching the naming of
# autodist_tpu.models.transformer / bert.
TRANSFORMER_TP_RULES = (
    (r"(^|/)qkv/kernel$", [None, None, const.MODEL_AXIS, None]),
    (r"(^|/)out/kernel$", [const.MODEL_AXIS, None, None]),
    (r"(^|/)wi/kernel$", [None, const.MODEL_AXIS]),
    (r"(^|/)wo/kernel$", [const.MODEL_AXIS, None]),
    (r"(^|/)(token_embed|embedding)/embedding$", [const.MODEL_AXIS, None]),
)


class TensorParallel(Sharded):
    """Megatron-style TP for the bundled transformer stack; extra rules
    can extend/override the defaults."""

    def __init__(self, extra_rules: Sequence[tuple[str, list]] = (), *,
                 zero_stage: int = None, zero1: bool = None):
        super().__init__(tuple(extra_rules) + TRANSFORMER_TP_RULES,
                         zero_stage=zero_stage, zero1=zero1)


class FSDPSharded(Sharded):
    """GSPMD-native FSDP: every matching variable's dim-0 sharded over the
    data axis (cf. the collective-path PartitionedPS which is the
    shard_map realization of the same layout)."""

    def __init__(self, min_size: int = 1024):
        super().__init__(())
        self.min_size = min_size

    def spec_for(self, info):
        if info.size >= self.min_size and info.shape \
                and info.shape[0] >= 2:
            return [const.DATA_AXIS] + [None] * (len(info.shape) - 1)
        return None
