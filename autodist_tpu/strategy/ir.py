"""Strategy IR: the serializable distribution strategy.

TPU-native counterpart of the reference's protobuf strategy schema
(``autodist/proto/strategy.proto:30-69`` and
``autodist/proto/synchronizers.proto:25-57``) and its Python wrapper
(``autodist/strategy/base.py:28-99``).  A Strategy is a per-variable list of
node configs — synchronizer choice plus optional partitioning — together
with a graph-level config (replica count ≙ data-axis size, mesh axes).

Design differences from the reference, on purpose:

* Serialization is JSON (the reference used protobuf purely as a
  file-serializable IR; JSON keeps the same chief-builds/workers-load flow
  with zero codegen).
* ``partitioner`` is still the reference's `"1,4,1"` axis-split string
  (``partitioner.py:38-150``), but it now resolves to a mesh-axis
  assignment (GSPMD ``PartitionSpec``) instead of graph surgery.
* Synchronizers describe *collective lowering* (psum / reduce-scatter /
  all-gather patterns over ICI) instead of graph-rewrite kernels.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Optional

from autodist_tpu import const


# --------------------------------------------------------------------------- #
# Per-collective precision policy (PR 8, EQuARX-style: PAPERS.md
# 2506.17615).  Every collective *boundary class* a lowering emits gets
# one policy slot; the slot's value is the wire precision the boundary's
# payload narrows to (summing collectives carry int8 levels on an fp16
# wire; gathers carry a true s8 wire — kernel/quantize.py).  An absent
# policy (the empty dict — what every pre-PR-8 strategy JSON
# deserializes to) is fp32 everywhere: today's exact behavior.
# --------------------------------------------------------------------------- #
from autodist_tpu.kernel.quantize import (PRECISIONS,  # noqa: E402
                                          UnknownPrecisionError)

# --------------------------------------------------------------------------- #
# Fused-kernel tier (PR 13): the Strategy IR's ``kernel`` slot elects
# Pallas kernels from :data:`~autodist_tpu.kernel.pallas.KERNEL_CHOICES`
# in place of their composed-XLA-op lowerings — a per-topology cost-model
# decision beside ``comm_overlap``/``precision``, never an unconditional
# swap.  An absent slot (the empty dict — what every pre-PR-13 strategy
# JSON deserializes to) is the composed lowering everywhere.
# --------------------------------------------------------------------------- #
from autodist_tpu.kernel.pallas import KERNEL_CHOICES  # noqa: E402


class UnknownKernelError(ValueError):
    """A kernel name outside :data:`~autodist_tpu.kernel.pallas
    .KERNEL_CHOICES` — the named error a hand-edited strategy JSON gets
    instead of a silently ignored election."""


def normalize_kernel(policy) -> dict:
    """Canonicalize a fused-kernel election.

    ``None``/``{}``/``False``/``""`` -> ``{}`` (composed lowerings —
    the pre-PR-13 behavior); ``True``/``"all"`` elects every kernel; a
    bare name or an iterable of names elects those; a dict keeps only
    truthy entries.  The canonical form maps each elected name to
    ``True`` so pre-PR-13 JSON round-trips with the slot absent-or-empty
    and hand edits stay readable.  Unknown names raise
    :class:`UnknownKernelError`.
    """
    if policy in (None, False, "", {}, (), []):
        return {}
    if policy is True or policy == "all":
        return {k: True for k in KERNEL_CHOICES}
    if isinstance(policy, str):
        policy = (policy,)
    if isinstance(policy, dict):
        names = [k for k, v in policy.items() if v]
    elif isinstance(policy, (list, tuple, set, frozenset)):
        names = list(policy)
    else:
        raise UnknownKernelError(
            f"kernel election must be a name, an iterable of names, or "
            f"a name->bool dict; got {type(policy).__name__}")
    out = {}
    for name in names:
        if name not in KERNEL_CHOICES:
            raise UnknownKernelError(
                f"unknown kernel {name!r}; expected one of "
                f"{list(KERNEL_CHOICES)}")
        out[name] = True
    return {k: True for k in KERNEL_CHOICES if k in out}


# --------------------------------------------------------------------------- #
# Serving KV-cache layout (PR 14): the ``parallel`` dict's serving knob.
# ``"dense"`` reserves one [max_len] lane per batch slot (the pre-PR-14
# behavior, what every earlier strategy JSON deserializes to);
# ``"paged"`` elects the block-paged pool + block-table layout
# (serving/kv_cache.py PagedKVCache), admitted against free blocks —
# the capacity side the cost model's decode objective prices.
# --------------------------------------------------------------------------- #
KV_LAYOUTS = ("dense", "paged")


class UnknownKVLayoutError(ValueError):
    """A kv_layout outside :data:`KV_LAYOUTS` — the named error a
    hand-edited strategy JSON (or engine kwarg) gets instead of a
    silently dense cache."""


def normalize_kv_layout(value) -> str:
    """Canonicalize the serving KV-cache layout knob.  ``None``/``""``
    -> ``"dense"`` (every pre-PR-14 strategy); unknown names raise
    :class:`UnknownKVLayoutError`."""
    if value in (None, ""):
        return "dense"
    if value not in KV_LAYOUTS:
        raise UnknownKVLayoutError(
            f"unknown kv_layout {value!r}; expected one of "
            f"{list(KV_LAYOUTS)}")
    return str(value)


# --------------------------------------------------------------------------- #
# Serving throughput ladder (PR 16): three more ``parallel``-dict knobs,
# each normalized here and seeded into the engine by
# ``seed_engine_kwargs`` exactly like ``kv_layout``.  All three default
# to OFF, which is what every pre-PR-16 strategy JSON deserializes to —
# the absent-key form keeps earlier JSON byte-stable.
# --------------------------------------------------------------------------- #
def normalize_prefill_chunk(value):
    """Canonicalize the chunked-prefill knob: ``None``/``0``/``False``
    -> ``None`` (single-shot prefill, the pre-PR-16 behavior); a
    positive int is the chunk length in tokens (the engine additionally
    requires a ``kv_block_len`` multiple so chunk writes stay
    block-granular).  Anything else raises ``ValueError``."""
    if value in (None, 0, False, ""):
        return None
    if isinstance(value, bool) or not isinstance(value, int) or value < 1:
        raise ValueError(
            f"prefill_chunk must be None or a positive int (tokens per "
            f"prefill chunk); got {value!r}")
    return int(value)


def normalize_prefix_caching(value) -> bool:
    """Canonicalize the prefix-caching knob: truthy -> ``True`` (the
    refcounted copy-on-write block allocator shares prompt-prefix
    blocks), anything falsy -> ``False`` (pre-PR-16).  Requires the
    paged layout — the engine validates, plan lint reports."""
    return bool(value)


def normalize_speculative(value):
    """Canonicalize the speculative-decoding knob: ``None``/``0``/
    ``False`` -> ``None`` (vanilla decode); a positive int is ``k``,
    the number of draft tokens proposed per target verify step.
    Anything else raises ``ValueError``."""
    if value in (None, 0, False, ""):
        return None
    if isinstance(value, bool) or not isinstance(value, int) or value < 1:
        raise ValueError(
            f"speculative must be None or a positive int (draft tokens "
            f"per verify step); got {value!r}")
    return int(value)


PRECISION_BOUNDARIES = (
    # dp gradient sync (all-reduce / reduce-scatter).  Realized through
    # the compressor machinery — the one boundary with persistent error-
    # feedback state — so "bf16"/"int8" here elect the EF compressors.
    "grad",
    # TP activation psums (Megatron row/column boundaries, forward AND
    # their custom-VJP backward), including the decomposed rs+ag halves
    # and the vocab-parallel prologue lookup psum.
    "tp_psum",
    # Vocab-parallel epilogue statistics: the pmax/psum token-shaped
    # stats and the backward hidden-state cotangent psum.
    "vocab_stats",
    # ZeRO-3 on-demand parameter gathers (forward all-gather) and their
    # custom-VJP backward cotangent reduce-scatter.
    "zero3_gather",
    # MoE dispatch/combine all-to-alls over the expert axis (forward AND
    # backward; permute-shaped, so the wire narrows like a gather — a
    # true s8 wire, no level-headroom bit).
    "moe_a2a",
)

# Wire bits per precision (telemetry gauges / the report schema gate).
PRECISION_BITS = {"fp32": 32, "bf16": 16, "int8": 8}


def normalize_precision(policy) -> dict:
    """Canonicalize a per-collective precision request.

    ``None``/``{}``/``"fp32"`` -> ``{}`` (fp32 everywhere — the
    pre-PR-8 behavior); a bare string applies one precision to every
    boundary class; a dict maps boundary -> precision (unnamed
    boundaries stay fp32).  Explicit ``"fp32"`` entries are dropped so
    the canonical form is minimal and pre-PR-8 JSON round-trips
    byte-stable.  Unknown boundaries/values raise
    :class:`UnknownPrecisionError`.
    """
    if policy in (None, "", "fp32"):
        return {}
    if isinstance(policy, str):
        if policy not in PRECISIONS:
            raise UnknownPrecisionError(
                f"unknown collective precision {policy!r}; expected one "
                f"of {list(PRECISIONS)}")
        return {b: policy for b in PRECISION_BOUNDARIES}
    if not isinstance(policy, dict):
        raise UnknownPrecisionError(
            f"collective precision must be a string or a per-boundary "
            f"dict, got {type(policy).__name__}")
    out = {}
    for boundary, value in policy.items():
        if boundary not in PRECISION_BOUNDARIES:
            raise UnknownPrecisionError(
                f"unknown collective boundary {boundary!r}; expected one "
                f"of {list(PRECISION_BOUNDARIES)}")
        if value not in PRECISIONS:
            raise UnknownPrecisionError(
                f"{boundary}: unknown precision {value!r}; expected one "
                f"of {list(PRECISIONS)}")
        if value != "fp32":
            out[boundary] = value
    return out


# --------------------------------------------------------------------------- #
# Synchronizer configs (≙ reference synchronizers.proto:25-57)
# --------------------------------------------------------------------------- #
@dataclasses.dataclass
class AllReduceSynchronizer:
    """Dense gradient allreduce over the data axis.

    ≙ reference ``AllReduceSynchronizer{spec, compressor, group}``
    (``synchronizers.proto:44-57``).  ``spec`` (NCCL/RING/AUTO) becomes the
    ICI fabric — XLA chooses the algorithm — so only compressor and
    bucketing (``group`` ≙ ScopedAllocator merge group,
    ``all_reduce_strategy.py:61-67``) survive as knobs.
    """

    kind: str = "allreduce"
    compressor: str = "none"     # none | fp16 | bf16 | fp16_ef | bf16_ef
                                 # | int8_ef | int8_ring | powersgd[:rank]
    group: int = 0               # bucket id for flatten-concat merging

    def to_dict(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass
class PSSynchronizer:
    """Sharded-state synchronization (parameter-server semantics on TPU).

    ≙ reference ``PSSynchronizer{reduction_destination, local_replication,
    sync, staleness}`` (``synchronizers.proto:25-42``).  On TPU the "PS
    device" becomes a *shard* of the data axis: gradients are
    reduce-scattered (each device owns 1/N of the flattened gradient ≙ the
    accumulator on the PS, ``ps_synchronizer.py:556-633``), the optimizer
    update runs on the owned shard (≙ apply op on the PS device), and
    updated parameters are all-gathered (≙ workers pulling new values /
    proxy refresh, ``proxy_variable.py:96-114``).  The sync barrier token
    queues (``ps_synchronizer.py:335-385``) are implicit in SPMD lockstep.

    ``staleness > 0`` (SSP, ``ps_synchronizer.py:387-458``) fundamentally
    fights SPMD lockstep; it is accepted in the IR and surfaced as a
    documented host-coordination extension (SURVEY.md §5.7 / §7).

    ``zero_stage`` extends the PS semantics along the classic weight-
    update-sharding ladder (arxiv 2004.13336):

    * ``1`` — optimizer state sharded (the U_FLAT scheme above; the
      default, and what every pre-stage strategy JSON deserializes to);
    * ``2`` — gradients live sharded too.  The U_FLAT lowering already
      reduce-scatters instead of all-reducing, so stages 1 and 2 emit
      the same program; the stage is the *accounting* record — the cost
      model charges the gradient term at 1/n only for stage >= 2.
    * ``3`` — the parameter itself is *stored* sharded over the replica
      axes and all-gathered on demand per layer inside the step (the
      gathers are step-internal temporaries; nothing full-sized lives
      across the step boundary).
    """

    kind: str = "ps"
    reduction_destination: str = ""   # informational shard tag; "" = flat uniform
    local_replication: bool = False   # ≙ proxy variable; TPU: params re-gathered anyway
    sync: bool = True
    staleness: int = 0
    zero_stage: int = 1

    def to_dict(self):
        return dataclasses.asdict(self)


SYNCHRONIZER_TYPES = {
    "allreduce": AllReduceSynchronizer,
    "ps": PSSynchronizer,
}


def synchronizer_from_dict(d: dict):
    d = dict(d)
    cls = SYNCHRONIZER_TYPES[d.get("kind", "allreduce")]
    return cls(**d)


# --------------------------------------------------------------------------- #
# Partitioner config (≙ reference PartitionerConfig, partitioner.py:38-150)
# --------------------------------------------------------------------------- #
@dataclasses.dataclass
class PartitionerConfig:
    """Axis-split spec for one variable.

    ``partition_str`` keeps the reference's `"1,4,1"` format — a
    num-splits per dimension list, single split axis (the reference's
    single-axis constraint, ``partitioner.py:126-150``).  ``mesh_axis``
    names the mesh axis the split maps onto (default: data axis —
    PS-partitioning in the reference spread shards over PS *devices*; the
    TPU analog spreads them over the mesh).
    """

    partition_str: str = ""
    mesh_axis: str = const.DATA_AXIS
    # GSPMD generalization (beyond the reference's single axis): one mesh
    # axis name (or None) per tensor dimension, e.g. ["data", None, "model"].
    # When set it overrides partition_str/mesh_axis and may shard several
    # dimensions — the strategy.proto:40-42 extensibility the reference
    # anticipated.
    spec: Optional[list] = None
    # Latency-hiding lowering of this variable's model-axis activation
    # collective (tensor-parallel layers only): None — blocking psum;
    # "rsag" — reduce-scatter + all-gather pair; "matmul" — chunked
    # collective-matmul ppermute ring (per-hop transfer hides behind
    # per-chunk compute).  Recorded per variable so the cost model can
    # price overlapped layers as max(comm, compute) instead of
    # comm + compute, and so a hand-edited strategy can convert layers
    # selectively.
    comm_overlap: Optional[str] = None
    # Wire precision of this variable's model-axis activation collective
    # (tensor-parallel layers / the vocab-sharded table) — the per-
    # variable record of the graph-level precision policy's tp_psum /
    # vocab_stats slot, mirroring comm_overlap: the cost model prices
    # each boundary from it, and a hand-edited strategy stays readable.
    # None = fp32 (today's exact psum).
    precision: Optional[str] = None

    @property
    def partition_list(self) -> list[int]:
        if not self.partition_str:
            return []
        return [int(x) for x in self.partition_str.split(",")]

    @property
    def split_axis(self) -> int:
        """The single partitioned dimension (reference partitioner.py:139-150)."""
        pl = self.partition_list
        axes = [i for i, n in enumerate(pl) if n > 1]
        if len(axes) > 1:
            raise ValueError(
                f"single-axis partitioning only (got {self.partition_str!r})")
        return axes[0] if axes else -1

    @property
    def num_shards(self) -> int:
        pl = self.partition_list
        return max(pl) if pl else 1

    def to_dict(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        prec = d.get("precision")
        if prec is not None and prec not in PRECISIONS:
            raise UnknownPrecisionError(
                f"partitioner precision {prec!r}: expected one of "
                f"{list(PRECISIONS)} (or null)")
        return cls(**d)


# --------------------------------------------------------------------------- #
# Node / graph / strategy (≙ reference strategy.proto:30-69)
# --------------------------------------------------------------------------- #
@dataclasses.dataclass
class NodeConfig:
    """Per-variable distribution choice (≙ ``strategy.proto Node``)."""

    var_name: str
    synchronizer: AllReduceSynchronizer | PSSynchronizer = dataclasses.field(
        default_factory=AllReduceSynchronizer)
    partitioner: Optional[PartitionerConfig] = None
    is_sparse: bool = False   # sparse/embedding path (≙ IndexedSlices grads)

    def to_dict(self):
        return {
            "var_name": self.var_name,
            "synchronizer": self.synchronizer.to_dict(),
            "partitioner": self.partitioner.to_dict() if self.partitioner else None,
            "is_sparse": self.is_sparse,
        }

    @classmethod
    def from_dict(cls, d):
        return cls(
            var_name=d["var_name"],
            synchronizer=synchronizer_from_dict(d["synchronizer"]),
            partitioner=(PartitionerConfig.from_dict(d["partitioner"])
                         if d.get("partitioner") else None),
            is_sparse=d.get("is_sparse", False),
        )


@dataclasses.dataclass
class GraphConfig:
    """Graph-level config (≙ ``strategy.proto GraphConfig.replicas``).

    ``replicas`` is the data-parallel degree; ``mesh_axes`` records any
    additional model/seq/pipe/expert axis sizes the strategy assumes.
    """

    replicas: int = 1
    mesh_axes: dict[str, int] = dataclasses.field(default_factory=dict)
    # Lowering path: "collective" = explicit per-variable collectives inside
    # one shard_map (the synchronizer semantics of the reference);
    # "gspmd" = jit + NamedSharding annotations, XLA inserts collectives
    # (for tensor/model-parallel and mixed-axis strategies);
    # "sequence" | "pipeline" | "expert" = the advanced-parallelism
    # lowerings (ring-attention sequence parallel, microbatched pipeline,
    # MoE expert parallel) — the strategy.proto:40-42 extension point the
    # reference anticipated, realized as first-class serializable
    # strategies.
    lowering: str = "collective"
    # Gradient accumulation: each step scans over this many microbatches
    # before the (single) synchronization + optimizer update, trading
    # step latency for global batch sizes that exceed device memory.
    # Composes with the pipeline lowering: each accumulation slice runs
    # the full microbatched pipeline schedule (accum_steps outer scans x
    # parallel.num_microbatches pipeline ticks per optimizer update).
    accum_steps: int = 1
    # Knobs of the advanced-parallelism lowerings, JSON-serializable:
    #   sequence: {"seq_leaves": ["x", "y"]}
    #   pipeline: {"num_microbatches": 4}
    #   expert:   {} (no lowering knobs; routing capacity lives at the
    #   model's expert_parallel_ffn call)
    parallel: dict = dataclasses.field(default_factory=dict)
    # Per-collective precision policy: boundary class -> wire precision
    # (see PRECISION_BOUNDARIES / normalize_precision above).  Empty —
    # what every pre-PR-8 strategy JSON deserializes to — is fp32
    # everywhere; hand-edited unknown boundaries/values are rejected
    # with UnknownPrecisionError at deserialization.
    precision: dict = dataclasses.field(default_factory=dict)
    # Fused-kernel tier election: kernel name -> True (see
    # normalize_kernel above).  Empty — what every pre-PR-13 strategy
    # JSON deserializes to — is the composed lowering everywhere;
    # hand-edited unknown names are rejected with UnknownKernelError at
    # deserialization.
    kernel: dict = dataclasses.field(default_factory=dict)

    def to_dict(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d):
        return cls(replicas=d.get("replicas", 1),
                   mesh_axes=dict(d.get("mesh_axes", {})),
                   lowering=d.get("lowering", "collective"),
                   accum_steps=d.get("accum_steps", 1),
                   parallel=dict(d.get("parallel", {})),
                   precision=normalize_precision(d.get("precision")),
                   kernel=normalize_kernel(d.get("kernel")))


@dataclasses.dataclass
class Strategy:
    """The full serializable strategy (≙ reference ``Strategy`` wrapper,
    ``strategy/base.py:28-99``: ID'd, file-serializable, pretty-printable).
    """

    node_configs: list[NodeConfig] = dataclasses.field(default_factory=list)
    graph_config: GraphConfig = dataclasses.field(default_factory=GraphConfig)
    id: str = ""

    def __post_init__(self):
        if not self.id:
            self.id = self._gen_id()

    def _gen_id(self) -> str:
        h = hashlib.md5(json.dumps(
            [n.to_dict() for n in self.node_configs], sort_keys=True
        ).encode()).hexdigest()[:12]
        return f"{time.strftime('%Y%m%dT%H%M%S')}-{h}"

    def node_config_for(self, var_name: str) -> Optional[NodeConfig]:
        for n in self.node_configs:
            if n.var_name == var_name:
                return n
        return None

    # -- serialization (≙ strategy/base.py:78-99 serialize/deserialize) ---- #
    def to_json(self) -> str:
        return json.dumps({
            "id": self.id,
            "node_configs": [n.to_dict() for n in self.node_configs],
            "graph_config": self.graph_config.to_dict(),
        }, indent=1)

    @classmethod
    def from_json(cls, s: str) -> "Strategy":
        d = json.loads(s)
        return cls(
            id=d["id"],
            node_configs=[NodeConfig.from_dict(n) for n in d["node_configs"]],
            graph_config=GraphConfig.from_dict(d["graph_config"]),
        )

    def serialize(self, path: Optional[str] = None) -> str:
        path = path or os.path.join(const.DEFAULT_STRATEGY_DIR, self.id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(self.to_json())
        return path

    @classmethod
    def deserialize(cls, strategy_id: str, path: Optional[str] = None) -> "Strategy":
        path = path or os.path.join(const.DEFAULT_STRATEGY_DIR, strategy_id)
        with open(path) as f:
            return cls.from_json(f.read())

    def __str__(self):
        gc = self.graph_config
        head = f"Strategy(id={self.id}, replicas={gc.replicas}"
        if gc.lowering != "collective":
            head += f", lowering={gc.lowering}"
        if gc.parallel:
            head += f", parallel={gc.parallel}"
        if gc.precision:
            head += f", precision={gc.precision}"
        if gc.kernel:
            head += f", kernel={sorted(gc.kernel)}"
        if gc.accum_steps > 1:
            head += f", accum_steps={gc.accum_steps}"
        lines = [head + ")"]
        for n in self.node_configs:
            part = "-"
            if n.partitioner:
                part = (str(n.partitioner.spec) if n.partitioner.spec
                        else n.partitioner.partition_str)
                if n.partitioner.comm_overlap:
                    part += f" overlap={n.partitioner.comm_overlap}"
            detail = getattr(n.synchronizer, "compressor", "")
            if n.synchronizer.kind == "ps":
                detail = f"zero{getattr(n.synchronizer, 'zero_stage', 1)}"
            lines.append(
                f"  {n.var_name}: sync={n.synchronizer.kind}"
                f"({detail}) part={part}"
                f"{' sparse' if n.is_sparse else ''}")
        return "\n".join(lines)
