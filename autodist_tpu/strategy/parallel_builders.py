"""Strategy builders for the advanced parallelisms.

The reference's strategy IR anticipated per-node distribution choices
beyond per-variable synchronizers (``strategy.proto:40-42``: node configs
"could be any node in the graph"); these builders realize that extension
point TPU-first: pipeline, sequence/context, and expert parallelism are
*serializable strategies* — they flow through ``AutoDist.build``, the
chief→worker strategy handoff, ``Saver``, and ``AutoStrategy`` exactly
like the reference-parity DP/PS/AR strategies, instead of being library
functions outside the IR.

Each builder emits node configs for every variable (so strategy
pretty-printing and serialization stay uniform) plus a ``GraphConfig``
whose ``lowering`` selects the backend and whose ``parallel`` dict holds
the schedule knobs.
"""
from __future__ import annotations

import re
from typing import Sequence

from autodist_tpu import const
from autodist_tpu.strategy.base import StrategyBuilder
from autodist_tpu.strategy.gspmd_builders import TRANSFORMER_TP_RULES
from autodist_tpu.utils import logging
from autodist_tpu.strategy.ir import (AllReduceSynchronizer, NodeConfig,
                                      PartitionerConfig, PSSynchronizer,
                                      Strategy, normalize_kernel,
                                      normalize_precision)

# Megatron-style model-axis rules for tensor parallelism *inside* pipeline
# stages, matched against the per-stage variable (the stacked leaf minus
# its leading chunk dim).  The kernel rules are the shared GSPMD table
# (gspmd_builders.TRANSFORMER_TP_RULES, minus the embedding rule — a
# pipelined transformer's embedding is a replicated *shared* variable);
# the bias rules are the manual-collective lowering's addition: GSPMD
# re-shards a replicated bias against a sharded activation automatically,
# but shard_map stage code adds bias shards to activation shards
# elementwise, so column-parallel biases must shard with their kernels.
PIPELINE_TP_RULES = tuple(
    (pat, spec) for pat, spec in TRANSFORMER_TP_RULES
    if "embed" not in pat
) + (
    (r"(^|/)qkv/bias$", [None, const.MODEL_AXIS, None]),
    (r"(^|/)wi/bias$", [const.MODEL_AXIS]),
)

# Vocab-parallel rules for the *shared* group (the pipelined
# transformer's tied embedding/unembedding, excluded from the stage rule
# table above): dim 0 — the vocab — shards over the model axis.
# Matched against the shared-variable name minus its ``shared/`` prefix;
# non-divisible vocab sizes are legal (the lowering zero-pads storage).
PIPELINE_VOCAB_RULES = (
    (r"(^|/)embedding$", [const.MODEL_AXIS, None]),
)


def _resolve_zero_stage(zero_stage, zero1) -> int:
    """Canonicalize the ZeRO request: ``zero_stage`` ∈ {0, 1, 2, 3} is
    the API (0 = off); ``zero1=True`` survives as a deprecated alias for
    ``zero_stage=1`` (note: prefer ``zero_stage=`` — the boolean cannot
    express stages 2/3 and will be removed)."""
    if zero1 is not None and zero_stage is not None:
        raise ValueError(
            "pass either zero_stage= or the deprecated zero1= alias, "
            "not both")
    if zero1 is not None:
        return 1 if zero1 else 0
    if zero_stage is None:
        return 0
    if zero_stage not in (0, 1, 2, 3):
        raise ValueError(
            f"zero_stage must be 0 (off), 1, 2 or 3; got {zero_stage!r}")
    return int(zero_stage)


def _check_grad_precision(precision: dict, compressor: str):
    """The precision policy's grad slot elects an EF compressor, so it
    conflicts with an explicit ``compressor=`` exactly like
    ``zero_stage`` does — and a silent drop would leave the user
    believing narrowed gradient sync is active."""
    if precision.get("grad") and (compressor or "none") != "none":
        raise ValueError(
            "collective_precision's 'grad' slot elects an error-"
            "feedback compressor; pass either it or compressor=, "
            "not both")


def _default_sync(zero_stage: int, compressor: str,
                  zero_min_bytes=None):
    """The per-variable synchronizer a parallel builder emits, as a
    function of the variable's :class:`~autodist_tpu.capture.VarInfo`:
    PS ≙ ZeRO sharding at the requested stage (the reference's PS
    semantics on TPU, ``ir.py:56-95``), AllReduce with an optional
    compressor otherwise.

    ``zero_min_bytes`` is the heterogeneous Parallax-style mix
    (``parallax_strategy.py:24-71``): variables at or above the
    threshold get ZeRO (at ``zero_stage``, default stage 1), smaller
    ones the (optionally compressed) allreduce — the classic
    big-tensors-sharded / small-tensors-cheap split, per variable in the
    serialized strategy.  Arbitrary mixes remain available by editing
    the emitted node configs before ``AutoDist.build``."""
    comp = compressor or "none"
    if zero_stage and comp != "none" and zero_min_bytes is None:
        raise ValueError(
            f"zero_stage={zero_stage} and compressor are mutually "
            "exclusive per variable: PS (ZeRO) sync reduces at full "
            "precision; compression is an AllReduce knob (zero_min_bytes "
            "composes them: large vars ZeRO-staged, small vars "
            "compressed)")
    stage = zero_stage or 1   # the stage the threshold mix shards at

    def sync_for(info):
        if zero_min_bytes is not None:
            if info.byte_size >= zero_min_bytes:
                return PSSynchronizer(zero_stage=stage)
            return AllReduceSynchronizer(compressor=comp)
        if zero_stage:
            return PSSynchronizer(zero_stage=zero_stage)
        return AllReduceSynchronizer(compressor=comp)

    return sync_for


class SequenceParallel(StrategyBuilder):
    """Sequence/context parallelism over the ``seq`` mesh axis.

    The mesh must declare a ``seq`` axis (e.g. ``mesh: {data: 2, seq: 4}``);
    token-dimension batch leaves (named by ``seq_leaves``) are split over
    ``data x seq``, parameters replicate, and gradients synchronize over
    both axes.  The model must attend globally (ring attention,
    :mod:`autodist_tpu.parallel.ring_attention`) and position tokens with
    :func:`autodist_tpu.parallel.sequence.global_positions`.
    """

    def __init__(self, seq_leaves: Sequence[str] = ("x", "y"), *,
                 zero_stage: int = None, zero1: bool = None,
                 compressor: str = "none", zero_min_bytes=None,
                 collective_precision=None):
        self.seq_leaves = tuple(seq_leaves)
        self.zero_stage = _resolve_zero_stage(zero_stage, zero1)
        self.precision = normalize_precision(collective_precision)
        _check_grad_precision(self.precision, compressor)
        self.make_sync = _default_sync(self.zero_stage, compressor,
                                       zero_min_bytes)

    def build(self, trainable, resource_spec):
        shape = resource_spec.resolved_mesh_shape()
        if const.SEQ_AXIS not in shape:
            raise ValueError(
                f"SequenceParallel needs a {const.SEQ_AXIS!r} mesh axis; "
                f"spec resolves to {shape} — declare e.g. "
                "mesh: {data: ..., seq: ...}")
        nodes = [NodeConfig(var_name=i.name,
                            synchronizer=self.make_sync(i),
                            is_sparse=i.is_sparse)
                 for i in trainable.var_infos()]
        cfg = self._graph_config(resource_spec)
        cfg.lowering = "sequence"
        cfg.parallel = {"seq_leaves": list(self.seq_leaves)}
        cfg.precision = dict(self.precision)
        return Strategy(node_configs=nodes, graph_config=cfg)


class Pipeline(StrategyBuilder):
    """Microbatched pipeline parallelism over the ``pipe`` mesh axis.

    Lowers a :class:`~autodist_tpu.capture.PipelineTrainable` (stacked
    stage parameters, leading stage dimension) onto the pipeline schedule
    of :mod:`autodist_tpu.parallel.pipeline`: every stage variable is
    stored sharded on the ``pipe`` axis, activations hop stages via a
    ``ppermute`` ring.  ``GraphConfig.accum_steps`` (GradAccumulation)
    composes: each accumulation slice runs the full microbatched
    schedule.

    ``tensor_parallel=t`` adds Megatron TP *inside* each stage (the
    dp×pp×tp composition): stage variables matching ``tp_rules``
    (default :data:`PIPELINE_TP_RULES`) additionally shard over the
    ``model`` mesh axis, recorded per variable in the strategy's
    partitioner specs; the trainable's ``stage_fn`` must be TP-aware
    (accept ``model_axis=`` — see :mod:`autodist_tpu.parallel.tensor`).

    ``comm_overlap`` (with ``tensor_parallel > 1``) decomposes the
    model-axis activation collectives for latency hiding: ``"rsag"`` —
    reduce-scatter + all-gather pairs; ``"matmul"``/``True`` — the
    chunked collective-matmul ``ppermute`` ring at the row-parallel
    boundaries (hop *k*'s transfer overlaps chunk *k+1*'s matmul).
    Recorded per tp-sharded variable in the partitioner configs *and*
    as the graph-level ``parallel.comm_overlap`` knob; the stage_fn
    must accept a ``comm_overlap=`` keyword (the bundled pipelined LM
    does).  With ``tensor_parallel == 1`` the knob is recorded but the
    lowering is collective-free either way (the tp∈{1,2} parity
    goldens rely on that no-op).

    ``vocab_parallel=True`` (with ``tensor_parallel > 1``) additionally
    shards the *shared* embedding/unembedding's vocab dimension over the
    model axis (``vocab_rules``, default :data:`PIPELINE_VOCAB_RULES`) —
    the prologue runs the masked shard-lookup psum and the loss head the
    streaming fused cross-entropy epilogue of
    :mod:`autodist_tpu.parallel.tensor`, so embedding state, optimizer
    moments, and peak logits memory all shrink by ``1/tp`` and no
    full-vocab buffer is ever materialized (``tools/hlo_probe.py
    --probe vocab_parallel`` proves it structurally).  The trainable's
    ``prologue``/``loss_head`` must accept ``model_axis=`` (the bundled
    pipelined LM does); non-divisible vocab sizes are zero-padded by
    the lowering.  Like ``comm_overlap``, a no-op at
    ``tensor_parallel == 1``.
    """

    def __init__(self, num_microbatches: int = 1, virtual_stages: int = 1,
                 *, zero_stage: int = None, zero1: bool = None,
                 compressor: str = "none",
                 zero_min_bytes=None, remat: bool = False,
                 tensor_parallel: int = 1,
                 tp_rules: Sequence[tuple[str, list]] = None,
                 comm_overlap=None, vocab_parallel: bool = False,
                 vocab_rules: Sequence[tuple[str, list]] = None,
                 collective_precision=None, kernel=None):
        if num_microbatches < 1:
            raise ValueError("num_microbatches must be >= 1")
        if virtual_stages < 1:
            raise ValueError("virtual_stages must be >= 1")
        if tensor_parallel < 1:
            raise ValueError("tensor_parallel must be >= 1")
        self.num_microbatches = num_microbatches
        self.virtual_stages = virtual_stages
        # Rematerialize each chunk in the backward (jax.checkpoint around
        # stage_fn): per-chunk residuals shrink to the boundary
        # activation, trading recompute FLOPs for the memory that
        # otherwise grows with M x V chunk executions per device.
        self.remat = remat
        # Megatron TP inside each stage: stage variables matching tp_rules
        # shard over the 'model' mesh axis in addition to 'pipe'; the
        # stage_fn must be TP-aware (accept model_axis= and psum at its
        # row-parallel boundaries — parallel/tensor.py primitives).
        self.tensor_parallel = tensor_parallel
        self.tp_rules = [(re.compile(pat), list(spec))
                         for pat, spec in (tp_rules if tp_rules is not None
                                           else PIPELINE_TP_RULES)]
        # Vocab parallelism for the *shared* embedding/unembedding: dim 0
        # of matching shared variables shards over the model axis, the
        # prologue runs the masked-lookup psum and the loss head the
        # streaming fused cross-entropy epilogue (parallel/tensor.py) —
        # the first knob that shrinks shared-parameter *memory* (state,
        # opt moments, and peak logits all /tp).  Like comm_overlap, the
        # knob is recorded but a no-op at tensor_parallel == 1.
        self.vocab_parallel = bool(vocab_parallel)
        self.vocab_rules = [(re.compile(pat), list(spec))
                            for pat, spec in
                            (vocab_rules if vocab_rules is not None
                             else PIPELINE_VOCAB_RULES)]
        from autodist_tpu.parallel.tensor import normalize_comm_overlap
        self.comm_overlap = normalize_comm_overlap(comm_overlap)
        # Per-collective precision policy (PR 8): a bare string narrows
        # every boundary class, a dict picks slots ({"tp_psum": "int8",
        # ...}).  The grad slot resolves onto the EF compressors, so it
        # conflicts with an explicit compressor= the same way zero does.
        self.precision = normalize_precision(collective_precision)
        _check_grad_precision(self.precision, compressor)
        # Fused-kernel tier (PR 13): elect Pallas kernels in place of
        # the composed lowerings — names from kernel.pallas
        # .KERNEL_CHOICES.  Each training kernel needs its enabling knob
        # (validated here so AutoStrategy/search skip unbuildable
        # combos instead of failing at lowering; lower_pipeline_ir
        # re-checks hand-edited JSON and plan lint ADT090 reports it):
        # quant_ring rides the blocking int8 tp_psum, collective_matmul
        # the comm_overlap="matmul" ring; flash_decode is serving-side
        # and recorded for the engine to read.
        self.kernel = normalize_kernel(kernel)
        if "quant_ring" in self.kernel:
            if tensor_parallel <= 1 \
                    or self.precision.get("tp_psum") != "int8":
                raise ValueError(
                    "kernel 'quant_ring' fuses q/dq into the int8 "
                    "tp_psum ring: it needs tensor_parallel > 1 and "
                    "collective_precision's tp_psum slot at 'int8'")
            if self.comm_overlap is not None:
                raise ValueError(
                    "kernel 'quant_ring' replaces the monolithic "
                    "tp_psum; comm_overlap routes the boundary through "
                    "the decomposed forms instead — pick one")
        if "collective_matmul" in self.kernel and (
                tensor_parallel <= 1 or self.comm_overlap != "matmul"):
            raise ValueError(
                "kernel 'collective_matmul' fuses the chunked ppermute "
                "ring: it needs tensor_parallel > 1 and "
                "comm_overlap='matmul'")
        # ZeRO stage over the data axes (stage vars) / pipe x data
        # (shared vars): 1 shards optimizer state, 2 additionally
        # accounts the gradients sharded (same U_FLAT program), 3 stores
        # the parameters sharded with per-layer on-demand gathers.
        self.zero_stage = _resolve_zero_stage(zero_stage, zero1)
        self.make_sync = _default_sync(self.zero_stage, compressor,
                                       zero_min_bytes)

    def _tp_spec_for(self, name: str, stage_shape: tuple, tp: int):
        """Per-stage model-axis spec for a stage variable, or None.

        First name-matching rule whose rank fits wins; a matching rule
        whose sharded dims don't divide by the tp degree is a user error
        (silent replication would quietly train a different program than
        the strategy declares)."""
        for pat, spec in self.tp_rules:
            if not pat.search(name) or len(spec) != len(stage_shape):
                continue
            for dim, axis in zip(stage_shape, spec):
                if axis == const.MODEL_AXIS and dim % tp:
                    raise ValueError(
                        f"{name}: per-stage dim {dim} does not divide by "
                        f"tensor_parallel={tp} (rule spec {spec})")
            return list(spec)
        return None

    def build(self, trainable, resource_spec):
        shape = resource_spec.resolved_mesh_shape()
        if const.PIPE_AXIS not in shape:
            raise ValueError(
                f"Pipeline needs a {const.PIPE_AXIS!r} mesh axis; spec "
                f"resolves to {shape} — declare e.g. "
                "mesh: {data: ..., pipe: ...}")
        num_stages = getattr(trainable, "num_stages", None)
        if num_stages is None:
            # ValueError (not TypeError) so AutoStrategy's candidate loop
            # can skip this builder for non-stage-structured trainables.
            raise ValueError(
                "Pipeline lowers stage-structured trainables; declare one "
                "with PipelineTrainable(stage_fn, stacked_params, "
                "loss_head, optimizer, num_stages=S)")
        if num_stages != shape[const.PIPE_AXIS] * self.virtual_stages:
            raise ValueError(
                f"trainable declares {num_stages} stages; mesh pipe axis "
                f"has {shape[const.PIPE_AXIS]} devices x "
                f"{self.virtual_stages} virtual stages")
        tp = self.tensor_parallel
        if tp > 1 and shape.get(const.MODEL_AXIS, 1) != tp:
            raise ValueError(
                f"Pipeline(tensor_parallel={tp}) needs a "
                f"{const.MODEL_AXIS!r} mesh axis of that size; spec "
                f"resolves to {shape} — declare e.g. "
                "mesh: {data: ..., pipe: ..., model: ...}")
        if tp > 1 and self.comm_overlap:
            # Validate at build time (not lowering) so AutoStrategy's
            # candidate loop SKIPS this builder for trainables whose
            # stage_fn cannot honor the decomposition, instead of
            # electing it on cost and failing the job at compile.
            import inspect
            try:
                sig = inspect.signature(
                    getattr(trainable, "stage_fn", None)).parameters
            except (TypeError, ValueError):  # partials/builtins: trust it
                sig = {"comm_overlap": None}
            if "comm_overlap" not in sig:
                raise ValueError(
                    f"comm_overlap={self.comm_overlap!r} needs an "
                    "overlap-aware stage_fn: it must accept comm_overlap= "
                    "and route it to its row/column-parallel boundaries "
                    "(autodist_tpu.parallel.tensor primitives)")
        if tp > 1 and self.vocab_parallel:
            # Build-time validation (mirrors the comm_overlap check): a
            # vocab-parallel lowering hands the prologue and loss head
            # local vocab shards, so both must accept model_axis= —
            # otherwise AutoStrategy's candidate loop must SKIP this
            # builder instead of electing it and failing at compile.
            import inspect
            if not getattr(trainable, "has_shared", False):
                raise ValueError(
                    "vocab_parallel=True shards the shared embedding/"
                    "unembedding; this trainable declares no shared_params")
            for role in ("prologue", "loss_head"):
                fn = getattr(trainable, role, None)
                try:
                    sig = inspect.signature(fn).parameters
                except (TypeError, ValueError):  # partials: trust it
                    sig = {"model_axis": None}
                if "model_axis" not in sig:
                    raise ValueError(
                        f"vocab_parallel=True needs a vocab-parallel-aware "
                        f"{role}: it must accept model_axis= and use the "
                        "autodist_tpu.parallel.tensor vocab primitives "
                        "(vocab_parallel_embedding / "
                        "vocab_parallel_cross_entropy)")
                if self.comm_overlap and "comm_overlap" not in sig:
                    raise ValueError(
                        f"comm_overlap={self.comm_overlap!r} with "
                        f"vocab_parallel=True needs the {role} to accept "
                        "comm_overlap= and route it to the epilogue psums")
        has_shared = getattr(trainable, "has_shared", False)
        nodes = []
        tp_matched = []
        vocab_matched = []
        for i in trainable.var_infos():
            node = NodeConfig(var_name=i.name,
                              synchronizer=self.make_sync(i),
                              is_sparse=i.is_sparse)
            # shared-group vars (embedding/unembedding of a pipelined
            # transformer) replicate; stage vars shard on the pipe axis
            # (their leading chunk dim), plus — with tensor_parallel —
            # the model axis on the dims the tp rules name.
            if not has_shared or i.name.startswith("stages/"):
                tail = [None] * (max(len(i.shape), 1) - 1)
                overlap = None
                tp_prec = None
                if tp > 1:
                    tp_tail = self._tp_spec_for(i.name, tuple(i.shape[1:]),
                                                tp)
                    if tp_tail is not None:
                        tail = tp_tail
                        tp_matched.append(i.name)
                        # The overlap and wire-precision choices ride
                        # every tp-sharded variable: row-parallel ones
                        # decompose/narrow their forward output
                        # reduction, column-parallel ones their backward
                        # cotangent reduction (the cost model prices
                        # each boundary from these records).
                        overlap = self.comm_overlap
                        tp_prec = self.precision.get("tp_psum")
                node.partitioner = PartitionerConfig(
                    mesh_axis=const.PIPE_AXIS,
                    spec=[const.PIPE_AXIS] + tail,
                    comm_overlap=overlap, precision=tp_prec)
            elif self.vocab_parallel and tp > 1:
                # Shared-group variable: vocab rules shard dim 0 over the
                # model axis (the lowering zero-pads non-divisible
                # vocabs); everything else stays replicated — the
                # per-leaf record parallel/pipeline.py reads instead of
                # pinning every shared leaf to P().
                for pat, spec in self.vocab_rules:
                    if pat.search(i.name) and len(spec) == len(i.shape):
                        node.partitioner = PartitionerConfig(
                            mesh_axis=const.MODEL_AXIS, spec=list(spec),
                            comm_overlap=self.comm_overlap,
                            precision=self.precision.get("vocab_stats"))
                        vocab_matched.append(i.name)
                        break
            nodes.append(node)
        if tp > 1 and self.vocab_parallel and not vocab_matched:
            raise ValueError(
                "Pipeline(vocab_parallel=True): no shared variable "
                "matched the vocab rules; name the tied table "
                "'embedding' (PIPELINE_VOCAB_RULES) or pass vocab_rules=...")
        if tp > 1 and not tp_matched:
            # ValueError (not a warning): AutoStrategy's candidate loop
            # skips the builder, and a direct user gets told their
            # naming doesn't meet the rule table instead of silently
            # training plain pipeline parallelism on a model mesh axis.
            raise ValueError(
                f"Pipeline(tensor_parallel={tp}): no stage variable "
                "matched the tp rules; name the projections "
                "qkv/out/wi/wo (PIPELINE_TP_RULES) or pass tp_rules=...")
        cfg = self._graph_config(resource_spec)
        cfg.lowering = "pipeline"
        cfg.parallel = {"num_microbatches": self.num_microbatches,
                        "virtual_stages": self.virtual_stages,
                        "remat": self.remat,
                        "tensor_parallel": tp,
                        "comm_overlap": self.comm_overlap,
                        "vocab_parallel": self.vocab_parallel,
                        # Builder-level record (telemetry/manifest); the
                        # authoritative per-variable stage lives in each
                        # PSSynchronizer.zero_stage node config.
                        "zero_stage": self.zero_stage}
        cfg.precision = dict(self.precision)
        cfg.kernel = dict(self.kernel)
        return Strategy(node_configs=nodes, graph_config=cfg)


_EXPERT_NAME_RE = re.compile(r"(expert|moe)", re.IGNORECASE)


class ExpertParallel(StrategyBuilder):
    """Expert parallelism (MoE) over the ``expert`` mesh axis.

    Variables carrying a leading expert dimension — named explicitly via
    ``expert_params`` (path-suffix match) or auto-detected (name contains
    ``expert``/``moe``, rank >= 3, and the leading dim divides the expert
    axis; rank-2 tensors like gating matrices are never auto-sharded — a
    gate's leading dim is the hidden size, not the expert count) — are
    stored sharded across experts; everything else replicates with the
    expert axis doubling as a batch axis (GShard arrangement).  The
    model must route tokens through
    :func:`autodist_tpu.parallel.moe.expert_parallel_ffn`.
    """

    def __init__(self, expert_params: Sequence[str] = (),
                 detect: bool = True, *, zero_stage: int = None,
                 zero1: bool = None,
                 compressor: str = "none", zero_min_bytes=None,
                 collective_precision=None, num_experts: int = None,
                 capacity_factor: float = 2.0,
                 expert_over_dcn: bool = False, kernel=None):
        self.expert_params = tuple(expert_params)
        self.detect = detect
        self.zero_stage = _resolve_zero_stage(zero_stage, zero1)
        self.precision = normalize_precision(collective_precision)
        _check_grad_precision(self.precision, compressor)
        # MoE shape knobs (PR 18): recorded on the strategy's parallel
        # slot so the cost model prices the dispatch/combine payload
        # (capacity-factor scaling, placement level) and the manifest /
        # drift join can read the elected shape back.
        self.num_experts = num_experts
        self.capacity_factor = float(capacity_factor)
        if self.capacity_factor <= 0:
            raise ValueError(
                f"capacity_factor must be > 0, got {capacity_factor}")
        # Placement: the expert axis stays within a slice unless
        # explicitly crossed — an across-DCN a2a pays DCN rates every
        # microbatch and plan lint ADT061 flags it (the search only
        # emits it to let inverted link constants elect it).
        self.expert_over_dcn = bool(expert_over_dcn)
        # Fused-kernel tier: a2a_ring swaps both dispatch/combine
        # all_to_alls for the fused-q/dq s8 ppermute ring.  Like
        # quant_ring it needs its enabling knobs (validated here so the
        # search skips unbuildable combos; lower_expert_ir re-checks the
        # binding and ADT090 reports hand-edited JSON).
        self.kernel = normalize_kernel(kernel)
        for k in self.kernel:
            if k in ("quant_ring", "collective_matmul"):
                raise ValueError(
                    f"kernel {k!r} fuses a tensor-parallel ring; the "
                    "expert lowering has no tp_psum/matmul boundary — "
                    "use the Pipeline builder")
        if "a2a_ring" in self.kernel:
            if self.precision.get("moe_a2a") != "int8":
                raise ValueError(
                    "kernel 'a2a_ring' fuses q/dq into the s8 "
                    "dispatch/combine ring: it needs "
                    "collective_precision's moe_a2a slot at 'int8'")
            if self.expert_over_dcn:
                raise ValueError(
                    "kernel 'a2a_ring' is an ICI ring; it cannot span "
                    "slices — drop expert_over_dcn or the kernel")
        self.make_sync = _default_sync(self.zero_stage, compressor,
                                       zero_min_bytes)

    def build(self, trainable, resource_spec):
        shape = resource_spec.resolved_mesh_shape()
        if const.EXPERT_AXIS not in shape:
            raise ValueError(
                f"ExpertParallel needs an {const.EXPERT_AXIS!r} mesh axis; "
                f"spec resolves to {shape} — declare e.g. "
                "mesh: {expert: ...}")
        E = shape[const.EXPERT_AXIS]
        if self.num_experts is not None and self.num_experts % E:
            raise ValueError(
                f"num_experts={self.num_experts} must divide the "
                f"{E}-way expert axis (each device holds E/axis experts)")
        # expert_over_dcn's mesh absorbs the slice dimension INTO the
        # expert axis (no separate dcn axis) — so the check is against
        # the topology's slice count, not the mesh.
        n_slices = max(int(getattr(resource_spec, "num_slices", 1) or 1),
                       1)
        if self.expert_over_dcn and n_slices <= 1 \
                and shape.get(const.DCN_AXIS, 1) <= 1:
            raise ValueError(
                "expert_over_dcn declares the expert axis spans slices, "
                f"but the spec resolves single-slice ({shape})")
        nodes = []
        matched = set()
        for i in trainable.var_infos():
            explicit = any(i.name == p or i.name.endswith("/" + p)
                           for p in self.expert_params)
            auto = (self.detect and _EXPERT_NAME_RE.search(i.name)
                    and len(i.shape) >= 3 and i.shape[0] % E == 0)
            if (not explicit and not auto and self.detect
                    and _EXPERT_NAME_RE.search(i.name)
                    and len(i.shape) == 2 and i.shape[0] % E == 0):
                # A rank-2 tensor in an expert scope could be a gate
                # (leading dim = hidden — must replicate) or a
                # per-expert bias (leading dim = experts — should
                # shard); only the user can tell.  Say so instead of
                # silently replicating.
                logging.info(
                    "%s: rank-2 tensor in an expert-named scope is NOT "
                    "auto-sharded (could be a gate); pass "
                    "expert_params=(%r,) if it is a per-expert table",
                    i.name, i.name.rsplit("/", 1)[-1])
            node = NodeConfig(var_name=i.name,
                              synchronizer=self.make_sync(i),
                              is_sparse=i.is_sparse)
            if explicit or auto:
                matched.add(i.name)
                node.partitioner = PartitionerConfig(
                    mesh_axis=const.EXPERT_AXIS,
                    spec=[const.EXPERT_AXIS]
                    + [None] * (len(i.shape) - 1))
            nodes.append(node)
        for p in self.expert_params:
            if not any(n == p or n.endswith("/" + p) for n in matched):
                raise ValueError(
                    f"expert_params entry {p!r} matched no variable "
                    f"(have {[i.name for i in trainable.var_infos()]})")
        if not matched:
            raise ValueError(
                "ExpertParallel found no expert variables: pass "
                "expert_params=... or name them with 'expert'/'moe'")
        cfg = self._graph_config(resource_spec)
        cfg.lowering = "expert"
        cfg.parallel = {
            "num_experts": (self.num_experts if self.num_experts
                            is not None else E),
            "capacity_factor": self.capacity_factor,
            "expert_over_dcn": self.expert_over_dcn,
            "zero_stage": self.zero_stage,
        }
        cfg.precision = dict(self.precision)
        cfg.kernel = dict(self.kernel)
        return Strategy(node_configs=nodes, graph_config=cfg)
