"""Unified telemetry: spans, metrics registry, per-step records, drift.

The single observability surface for the framework (the reference's
chrome-trace timelines + ``TimeHistory`` meter tier, SURVEY.md §5.1,
rebuilt process-wide).  Typical use::

    from autodist_tpu import telemetry

    telemetry.configure(out_dir="/tmp/run1")
    with telemetry.span("compile"):
        ...
    telemetry.counter("asyncps/push").inc()
    telemetry.record_step(step=3, duration_s=0.012, examples=32)
    telemetry.flush()        # trace.json / metrics.jsonl / manifest.json
    telemetry.drift_report(strategy, cost_model, measured,
                           trainable=trainable)

Disabled entirely with ``AUTODIST_TPU_TELEMETRY=0`` (no files, shared
no-op span/instrument singletons).  See ``docs/usage/observability.md``.
"""
from autodist_tpu.telemetry import tracing
from autodist_tpu.telemetry.aggregate import (RollingWindow,
                                              TelemetryAggregator)
from autodist_tpu.telemetry.core import (NULL_SPAN, Telemetry, configure,
                                         get, reset)
from autodist_tpu.telemetry.drift import DriftMonitor, drift_report
from autodist_tpu.telemetry.metrics import (NULL_INSTRUMENT, Counter, Gauge,
                                            Histogram, MetricsRegistry)
from autodist_tpu.telemetry.records import build_manifest, provenance
from autodist_tpu.telemetry.tracing import (current_trace_id, mint_trace_id,
                                            request_timeline, stitch_trace,
                                            trace_context)

__all__ = [
    "Telemetry", "get", "configure", "reset", "enabled", "span", "counter",
    "gauge", "histogram", "record_step", "record_event", "annotate",
    "flush", "manifest",
    "summary", "drift_report", "provenance", "build_manifest",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "NULL_SPAN", "NULL_INSTRUMENT",
    "tracing", "mint_trace_id", "current_trace_id", "trace_context",
    "stitch_trace", "request_timeline",
    "RollingWindow", "TelemetryAggregator", "DriftMonitor",
]


def enabled() -> bool:
    return get().enabled


def span(name: str, **args):
    return get().span(name, **args)


def counter(name: str):
    return get().counter(name)


def gauge(name: str):
    return get().gauge(name)


def histogram(name: str):
    return get().histogram(name)


def record_step(step: int, duration_s: float, **kw) -> bool:
    return get().record_step(step, duration_s, **kw)


def record_event(kind: str, **fields) -> bool:
    return get().record_event(kind, **fields)


def annotate(**kv):
    return get().annotate(**kv)


def flush(out_dir=None) -> dict:
    return get().flush(out_dir)


def manifest() -> dict:
    return get().manifest()


def summary() -> str:
    return get().summary()
