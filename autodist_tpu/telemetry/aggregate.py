"""Live fleet telemetry aggregation: ONE shared rolling-window
percentile implementation and the fleet-level SLO monitor over it.

Before this module, three call sites each kept a private bounded deque
over the same completion traffic — the Router's hedge-calibration
latencies, the Autoscaler's TTFT window, and whatever a report wanted
to percentile after the fact — and two of them could disagree on the
same stream (different maxlens, different refresh points).
:class:`RollingWindow` is the one implementation; the Router and the
Autoscaler are now *views* over the same :class:`TelemetryAggregator`
windows (``e2e_s`` / ``ttft_ms``), and the SLO gauges
(``slo/ttft_p99_ms`` / ``slo/inter_token_p99_ms`` / ``slo/error_rate``
plus the threshold-burn gauges) read the identical numbers.

Cross-process, the aggregator *tails* worker metrics shards: each
worker flushes ``kind="serve"`` records into its own
``<tel_dir>/<replica>-i<inc>/metrics.jsonl``; :meth:`tail_shards`
re-reads each shard from its remembered offset and folds the new
records into the same windows — fleet-level percentiles without a new
transport.
"""
from __future__ import annotations

import json
import os
from collections import deque
from typing import Optional

import numpy as np

from autodist_tpu.telemetry import core as _core

# Finish reasons that count against the SLO error budget: the request
# left without the stream its client asked for (budget/EOS terminals
# and operator-driven cancels are successes, not errors).
ERROR_FINISHES = ("shed", "deadline_exceeded")


class RollingWindow:
    """A bounded window of recent scalar observations with exact
    percentiles over the retained span — the ONE windowed-percentile
    implementation every consumer views (hedge calibration, autoscale
    trigger, SLO gauges, the online drift monitor)."""

    def __init__(self, maxlen: int = 512):
        if maxlen < 1:
            raise ValueError("window maxlen must be >= 1")
        self._buf: deque = deque(maxlen=int(maxlen))

    @property
    def maxlen(self) -> int:
        return self._buf.maxlen

    def push(self, value: float) -> None:
        self._buf.append(float(value))

    def __len__(self) -> int:
        return len(self._buf)

    def values(self) -> np.ndarray:
        return np.asarray(self._buf, float)

    def percentile(self, q: float) -> Optional[float]:
        """The q-th percentile of the retained window; ``None`` when
        empty — an empty window has no latency, and callers that want
        0.0 (the autoscaler's "an empty fleet is not slow") say so."""
        if not self._buf:
            return None
        return float(np.percentile(self.values(), q))

    def mean(self) -> Optional[float]:
        if not self._buf:
            return None
        return float(self.values().mean())

    def resize(self, maxlen: int) -> "RollingWindow":
        """Re-bound the window, keeping the most recent values — the
        hook that lets a later consumer (the autoscaler's
        ``ttft_window`` knob) narrow a window the router already
        created without forking the stream."""
        if maxlen < 1:
            raise ValueError("window maxlen must be >= 1")
        if maxlen != self._buf.maxlen:
            self._buf = deque(self._buf, maxlen=int(maxlen))
        return self


class TelemetryAggregator:
    """Named rolling windows + error accounting over one traffic
    stream, with the SLO gauges emitted from the same numbers every
    view reads.

    ``slo_ttft_p99_ms`` / ``slo_inter_token_p99_ms`` are optional SLO
    thresholds: when set, :meth:`emit_slo_gauges` additionally emits
    ``slo/<signal>_burn`` — measured over threshold, the classic
    burn-rate gauge (1.0 = exactly at the objective)."""

    def __init__(self, *, slo_ttft_p99_ms: Optional[float] = None,
                 slo_inter_token_p99_ms: Optional[float] = None):
        self._windows: dict[str, RollingWindow] = {}
        self._offsets: dict[str, int] = {}
        self.slo_ttft_p99_ms = slo_ttft_p99_ms
        self.slo_inter_token_p99_ms = slo_inter_token_p99_ms
        self.requests = 0
        self.errors = 0

    def window(self, name: str, maxlen: int = 512) -> RollingWindow:
        """Get-or-create the named window.  The first creation fixes
        the bound; a consumer that needs a different span calls
        :meth:`RollingWindow.resize` explicitly (so two views can never
        silently percentile different windows under one name)."""
        win = self._windows.get(name)
        if win is None:
            win = self._windows[name] = RollingWindow(maxlen)
        return win

    # ---- observation ------------------------------------------------- #
    def observe(self, name: str, value: float) -> None:
        self.window(name).push(value)

    def observe_completion(self, *, ttft_s: float, e2e_s: float,
                           finish_reason: str) -> None:
        """Fold one finished request into the shared windows — the
        Router calls this at ``_complete``, the shard tail calls it per
        ``kind="serve"`` record, and every percentile consumer reads
        the result."""
        self.window("ttft_ms").push(float(ttft_s) * 1e3)
        self.window("e2e_s").push(float(e2e_s))
        self.requests += 1
        if finish_reason in ERROR_FINISHES:
            self.errors += 1

    # ---- cross-process shard tailing --------------------------------- #
    def tail_shards(self, tel_dir: str) -> int:
        """Fold NEW ``kind="serve"`` records from every worker metrics
        shard under ``tel_dir`` (``<replica>-i<inc>/metrics.jsonl``)
        into the windows; per-file byte offsets make repeated calls
        incremental.  Returns how many records were folded."""
        folded = 0
        try:
            entries = sorted(os.listdir(tel_dir))
        except OSError:
            return 0
        for name in entries:
            path = os.path.join(tel_dir, name, "metrics.jsonl")
            if not os.path.isfile(path):
                continue
            offset = self._offsets.get(path, 0)
            try:
                size = os.path.getsize(path)
                if size < offset:
                    offset = 0   # a replacement incarnation rewrote it
                with open(path) as f:
                    f.seek(offset)
                    chunk = f.read()
                    self._offsets[path] = f.tell()
            except OSError:
                continue
            for line in chunk.splitlines():
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict) \
                        or rec.get("kind") != "serve":
                    continue
                if rec.get("ttft_ms") is not None:
                    self.window("ttft_ms").push(float(rec["ttft_ms"]))
                if rec.get("inter_token_p99_ms") is not None:
                    self.window("inter_token_ms").push(
                        float(rec["inter_token_p99_ms"]))
                self.requests += 1
                if rec.get("finish") in ERROR_FINISHES:
                    self.errors += 1
                folded += 1
        return folded

    # ---- the unified SLO view ---------------------------------------- #
    def error_rate(self) -> float:
        return self.errors / self.requests if self.requests else 0.0

    def emit_slo_gauges(self) -> dict:
        """Refresh the fleet-level SLO gauges from the shared windows
        and return the values emitted.  Empty windows gauge 0.0 (no
        traffic is not a violation), and burn gauges appear only when
        their threshold is configured."""
        ttft = self.window("ttft_ms").percentile(99) or 0.0
        itl = self.window("inter_token_ms").percentile(99) or 0.0
        rate = self.error_rate()
        out = {"slo/ttft_p99_ms": ttft, "slo/inter_token_p99_ms": itl,
               "slo/error_rate": rate}
        if self.slo_ttft_p99_ms:
            out["slo/ttft_burn"] = ttft / self.slo_ttft_p99_ms
        if self.slo_inter_token_p99_ms:
            out["slo/inter_token_burn"] = \
                itl / self.slo_inter_token_p99_ms
        for name, value in out.items():
            _core.get().gauge(name).set(value)
        return out
