"""Process-wide telemetry: spans, metrics, per-step records, sinks.

The reference AutoDist's observability was chrome-trace timelines per
``session.run`` (``runner.py:64-75``), graph-stage snapshots, and the
``TimeHistory`` meter; this module unifies that tier for the TPU build:

* :meth:`Telemetry.span` — nested timing spans (``with
  telemetry.span("compile"):``) exported as chrome-trace JSON
  (``chrome://tracing`` / Perfetto load it directly).
* counters / gauges / histograms (:mod:`autodist_tpu.telemetry.metrics`)
  flushed to a JSONL sink plus a human-readable summary.
* per-step records (step latency, examples, metrics) with a sampling
  knob, flushed to the same JSONL sink.

Config plane (see :mod:`autodist_tpu.const`):

* ``AUTODIST_TPU_TELEMETRY=0`` disables everything: ``span()`` returns a
  shared no-op context manager, instruments are a shared null object, no
  files are ever written.  Default is ON (cheap: in-memory, bounded).
* ``AUTODIST_TPU_TELEMETRY_DIR`` — flush destination (also settable via
  :func:`configure`); without a directory, telemetry stays in-memory.
* ``AUTODIST_TPU_TELEMETRY_SAMPLE=N`` — keep every Nth per-step record.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from autodist_tpu import const
from autodist_tpu.telemetry import tracing
from autodist_tpu.telemetry.metrics import (NULL_INSTRUMENT, MetricsRegistry)

# In-memory caps (the default-on-cheap contract): beyond them new spans /
# step records are counted but not retained, so an unbounded training
# loop cannot grow the process with observability data.
MAX_SPANS = 20000
MAX_STEP_RECORDS = 100000


class _NullSpan:
    """Shared no-op context manager for the disabled path — ``span()``
    returns this exact singleton, so a disabled run leaves no wrapper
    object behind per call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NULL_SPAN = _NullSpan()


class Span:
    """One timed region; nesting is tracked per thread so the chrome
    trace shows parent/child stacks."""

    __slots__ = ("name", "args", "_tel", "_t0", "_tid")

    def __init__(self, tel: "Telemetry", name: str, args: dict):
        self._tel = tel
        self.name = name
        self.args = args

    def set(self, **attrs) -> "Span":
        """Attach attributes after entry (e.g. a lowering kind resolved
        mid-region); they land in the trace event's ``args``."""
        self.args.update(attrs)
        return self

    def __enter__(self):
        self._tid = threading.get_ident()
        self._tel._span_stack().append(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        stack = self._tel._span_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        self._tel._record_span(self, self._t0, t1, self._tid,
                               depth=len(stack))
        return False


class Telemetry:
    """The process-wide recorder.  Use the module-level functions in
    :mod:`autodist_tpu.telemetry` rather than instantiating directly."""

    def __init__(self, out_dir: Optional[str] = None,
                 sample: Optional[int] = None,
                 enabled: Optional[bool] = None):
        self.enabled = (const.ENV.AUTODIST_TPU_TELEMETRY.val
                        if enabled is None else enabled)
        self.out_dir = (out_dir or const.ENV.AUTODIST_TPU_TELEMETRY_DIR.val
                        or None)
        self.sample = (sample if sample is not None
                       else const.ENV.AUTODIST_TPU_TELEMETRY_SAMPLE.val)
        self.registry = MetricsRegistry()
        self._lock = threading.Lock()
        self._local = threading.local()
        self._spans: list[dict] = []
        self._spans_dropped = 0
        self._steps: list[dict] = []
        self._steps_dropped = 0
        self._steps_seen = 0
        self._annotations: dict = {}
        # chrome-trace timestamps: wall-clock epoch anchored once, deltas
        # from the monotonic clock (wall time can step mid-run).
        self._epoch_wall_us = time.time() * 1e6
        self._epoch_perf = time.perf_counter()

    # ---------------- spans ------------------------------------------- #
    def _span_stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def span(self, name: str, **args):
        if not self.enabled:
            return NULL_SPAN
        if "trace_id" not in args and "trace_ids" not in args:
            tid = tracing.current_trace_id()
            if tid is not None:
                args["trace_id"] = tid
        return Span(self, name, args)

    def _record_span(self, span: Span, t0: float, t1: float, tid: int,
                     depth: int):
        event = {"name": span.name, "ph": "X", "pid": os.getpid(),
                 "tid": tid,
                 "ts": self._epoch_wall_us + (t0 - self._epoch_perf) * 1e6,
                 "dur": (t1 - t0) * 1e6}
        if span.args:
            event["args"] = {k: _jsonable(v) for k, v in span.args.items()}
        if depth:
            event.setdefault("args", {})["depth"] = depth
        with self._lock:
            if len(self._spans) < MAX_SPANS:
                self._spans.append(event)
            else:
                self._spans_dropped += 1

    # ---------------- metrics ----------------------------------------- #
    def counter(self, name: str):
        return self.registry.counter(name) if self.enabled \
            else NULL_INSTRUMENT

    def gauge(self, name: str):
        return self.registry.gauge(name) if self.enabled else NULL_INSTRUMENT

    def histogram(self, name: str):
        return self.registry.histogram(name) if self.enabled \
            else NULL_INSTRUMENT

    # ---------------- per-step records -------------------------------- #
    def record_step(self, step: int, duration_s: float, *,
                    examples: Optional[int] = None,
                    steps: int = 1, **extra) -> bool:
        """One training-step (or fused-window: ``steps=k``) record.
        The JSONL record is subject to the sampling knob (returns
        whether it was kept); the ``step/duration_s`` histogram sees
        every call regardless, so percentiles stay exact under
        sampling."""
        if not self.enabled:
            return False
        self.registry.histogram("step/duration_s").observe(
            float(duration_s) / max(steps, 1))
        with self._lock:
            self._steps_seen += 1
            if self.sample > 1 and (self._steps_seen - 1) % self.sample:
                return False
            if len(self._steps) >= MAX_STEP_RECORDS:
                self._steps_dropped += 1
                return False
            rec = {"kind": "step", "step": int(step),
                   "duration_ms": float(duration_s) * 1e3}
            if steps != 1:
                rec["steps"] = int(steps)
            if examples is not None:
                rec["examples"] = int(examples)
            for k, v in extra.items():
                rec[k] = _jsonable(v)
            self._steps.append(rec)
        return True

    def record_event(self, kind: str, **fields) -> bool:
        """One typed event record on the JSONL sink (``kind`` other than
        the reserved ``"step"`` — e.g. the serving path's per-request
        ``"serve"`` records).  Events share the step records' retention
        cap but not the sampling knob: a request-level record is already
        aggregated, so dropping every Nth would lose requests, not
        resolution."""
        if not self.enabled:
            return False
        if kind == "step":
            raise ValueError("use record_step for step records")
        rec = {"kind": str(kind)}
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        # The same wall-anchored timestamp spans carry: what lets the
        # trace stitcher fold typed records into the merged timeline as
        # causally-ordered instant events.
        rec.setdefault("ts_us", self._epoch_wall_us
                       + (time.perf_counter() - self._epoch_perf) * 1e6)
        if "trace_id" not in rec:
            tid = tracing.current_trace_id()
            if tid is not None:
                rec["trace_id"] = tid
        with self._lock:
            if len(self._steps) >= MAX_STEP_RECORDS:
                self._steps_dropped += 1
                return False
            self._steps.append(rec)
        return True

    def step_records(self) -> list[dict]:
        with self._lock:
            return list(self._steps)

    # ---------------- manifest / annotations -------------------------- #
    def annotate(self, **kv):
        """Attach run-level facts (mesh, config, argv...) to the
        manifest."""
        if not self.enabled:
            return
        with self._lock:
            self._annotations.update(
                {k: _jsonable(v) for k, v in kv.items()})

    def manifest(self) -> dict:
        """The run manifest: provenance (git SHA, jax/jaxlib versions —
        the identity stamp ``bench.py`` embeds in every record) plus
        run-level annotations and telemetry bookkeeping."""
        from autodist_tpu.telemetry import records

        with self._lock:
            ann = dict(self._annotations)
            book = {"spans": len(self._spans),
                    "spans_dropped": self._spans_dropped,
                    "step_records": len(self._steps),
                    "steps_seen": self._steps_seen,
                    "step_records_dropped": self._steps_dropped,
                    "sample": self.sample}
        return records.build_manifest(annotations=ann, telemetry=book)

    # ---------------- sinks ------------------------------------------- #
    def chrome_trace(self) -> dict:
        with self._lock:
            events = list(self._spans)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def summary(self) -> str:
        lines = [f"telemetry summary (pid {os.getpid()})"]
        with self._lock:
            lines.append(f"  spans: {len(self._spans)} "
                         f"(dropped {self._spans_dropped})")
            lines.append(f"  step records: {len(self._steps)} of "
                         f"{self._steps_seen} seen (sample={self.sample})")
        for line in self.registry.summary_lines():
            lines.append("  " + line)
        return "\n".join(lines)

    def flush(self, out_dir: Optional[str] = None) -> dict:
        """Write every sink and return ``{artifact: path}``.

        Artifacts: ``trace.json`` (chrome trace), ``metrics.jsonl``
        (per-step records then instrument snapshots, one object per
        line), ``manifest.json``, ``summary.txt``.  A no-op (returns
        ``{}``) when disabled or when no directory is configured — the
        disabled path never writes files.
        """
        if not self.enabled:
            return {}
        out_dir = out_dir or self.out_dir
        if not out_dir:
            return {}
        os.makedirs(out_dir, exist_ok=True)
        paths = {}

        trace_path = os.path.join(out_dir, "trace.json")
        with open(trace_path, "w") as f:
            json.dump(self.chrome_trace(), f)
        paths["trace"] = trace_path

        jsonl_path = os.path.join(out_dir, "metrics.jsonl")
        with open(jsonl_path, "w") as f:
            for rec in self.step_records():
                f.write(json.dumps(rec) + "\n")
            for snap in self.registry.snapshot():
                f.write(json.dumps(snap) + "\n")
        paths["metrics"] = jsonl_path

        manifest_path = os.path.join(out_dir, "manifest.json")
        with open(manifest_path, "w") as f:
            json.dump(self.manifest(), f, indent=1)
        paths["manifest"] = manifest_path

        summary_path = os.path.join(out_dir, "summary.txt")
        with open(summary_path, "w") as f:
            f.write(self.summary() + "\n")
        paths["summary"] = summary_path
        return paths


def _jsonable(v):
    """Best-effort JSON coercion for span/record attributes (numpy
    scalars, tuples, device arrays)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    try:
        import numpy as np

        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
        arr = np.asarray(v)
        if arr.ndim == 0:
            return arr.item()
        if arr.size <= 16:
            return arr.tolist()
    except Exception:
        pass
    return str(v)


# ---------------- process-wide singleton ------------------------------- #
_singleton: Optional[Telemetry] = None
_singleton_lock = threading.Lock()


def get() -> Telemetry:
    global _singleton
    if _singleton is None:
        with _singleton_lock:
            if _singleton is None:
                _singleton = Telemetry()
    return _singleton


def configure(out_dir: Optional[str] = None, sample: Optional[int] = None,
              enabled: Optional[bool] = None) -> Telemetry:
    """Adjust the live singleton (flush destination, sampling, on/off)."""
    tel = get()
    if out_dir is not None:
        tel.out_dir = out_dir
    if sample is not None:
        tel.sample = max(int(sample), 1)
    if enabled is not None:
        tel.enabled = bool(enabled)
    return tel


def reset() -> Telemetry:
    """Discard all recorded state and re-read the env config (tests; a
    fresh run in a reused process)."""
    global _singleton
    with _singleton_lock:
        _singleton = Telemetry()
    return _singleton
