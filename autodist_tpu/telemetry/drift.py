"""Predicted-vs-measured drift report: the cost-model calibration loop.

The analytic cost model (:mod:`autodist_tpu.simulator.cost_model`) ranks
strategies from chip-table constants; GSPMD-style auto-sharding and
placement synthesis both live or die by keeping such models honest
against silicon.  :func:`drift_report` joins a strategy's *predicted*
step-time terms (comm vs compute vs exposed-overlap) and per-device
memory against *measured* step percentiles (``StepTimer``/runner
summaries) and HBM (``profiling.memory_summary``), emits per-term
ratios, and proposes updated ``calibration.json`` ``"link"`` constants —
so a hardware window produces calibration data mechanically instead of
by hand.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Optional

# Propose a link-constant update only when prediction and measurement
# disagree by more than this factor — below it the analytic default is
# within measurement noise.
_PROPOSAL_THRESHOLD = 0.10


def _measured_step_seconds(step: Optional[dict]) -> tuple[Optional[float],
                                                          dict]:
    """(p50 step seconds, echo dict) from a ``StepTimer.summary()`` /
    ``DistributedRunner.summary()``-shaped dict."""
    if not step:
        return None, {}
    echo = {k: step[k] for k in ("steps", "mean_ms", "p50_ms", "p99_ms",
                                 "examples_per_sec") if step.get(k)
            is not None}
    for key in ("p50_ms", "mean_ms"):
        if step.get(key) is not None:
            return float(step[key]) / 1e3, echo
    return None, echo


def _measured_memory_bytes(memory: Optional[dict]) -> tuple[Optional[float],
                                                            Optional[str]]:
    """(bytes, source) — HBM ``bytes_in_use`` where the backend exposes
    it; host peak-RSS fallback otherwise (CPU meshes report no device
    memory, but the calibration join must still cover the memory axis —
    flagged so nobody mistakes RSS for HBM)."""
    if memory and memory.get("bytes_in_use"):
        return float(memory["bytes_in_use"]), "device_bytes_in_use"
    try:
        import resource as _resource

        rss_kb = _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss
        if rss_kb:
            return float(rss_kb) * 1024.0, "host_rss_peak"
    except (ImportError, OSError, ValueError):  # pragma: no cover
        pass
    return None, None


def drift_report(strategy=None, cost_model=None,
                 measured: Optional[dict] = None, *,
                 trainable=None, predicted=None,
                 flops_per_step: Optional[float] = None,
                 out_dir: Optional[str] = None) -> dict:
    """Join a strategy's predicted cost against a measured run.

    Args:
      strategy: the :class:`~autodist_tpu.strategy.ir.Strategy` that ran.
      cost_model: a :class:`~autodist_tpu.simulator.cost_model.CostModel`
        (supplies the prediction via ``strategy_cost`` and the link
        constants the proposal updates).
      measured: ``{"step": StepTimer.summary()-shaped dict,
        "memory": profiling.memory_summary() dict}`` plus optional
        ``"examples_per_sec"`` / ``"flops_per_example"`` for MFU.
      trainable: needed with ``cost_model`` to price the strategy
        (ignored when ``predicted`` is given).
      predicted: a precomputed
        :class:`~autodist_tpu.simulator.cost_model.StrategyCost` (or
        dict with its fields) — bypasses ``cost_model.strategy_cost``.
      flops_per_step: model FLOPs per optimizer step; enables the
        compute term (and its ratio) — without it the predicted step
        time is the communication envelope only, flagged ``comm_only``.
      out_dir: write ``drift.json`` here (defaults to the telemetry
        flush directory when one is configured).

    Returns the report dict (always; file output is best-effort).
    """
    from autodist_tpu import telemetry

    measured = measured or {}
    if predicted is None:
        if cost_model is None or strategy is None or trainable is None:
            raise ValueError(
                "drift_report needs either predicted= or all of "
                "(strategy, cost_model, trainable)")
        predicted = cost_model.strategy_cost(trainable, strategy)
    if not isinstance(predicted, dict):
        predicted = {
            "comm_bytes": predicted.comm_bytes,
            "comm_time_s": predicted.comm_time_s,
            "overlap_time_s": getattr(predicted, "overlap_time_s", 0.0),
            "num_collectives": predicted.num_collectives,
            "mem_bytes_per_device": predicted.mem_bytes_per_device,
            "feasible": predicted.feasible,
            "peak_logits_bytes": getattr(predicted, "peak_logits_bytes",
                                         0.0),
            "param_shard_bytes": getattr(predicted, "param_shard_bytes",
                                         0.0),
            "grad_shard_bytes": getattr(predicted, "grad_shard_bytes",
                                        0.0),
            "wire_bytes_saved": getattr(predicted, "wire_bytes_saved",
                                        0.0),
            "quant_dq_time_s": getattr(predicted, "quant_dq_time_s",
                                       0.0),
            "dcn_bytes": getattr(predicted, "dcn_bytes", 0.0),
            "dcn_time_s": getattr(predicted, "dcn_time_s", 0.0),
            "a2a_bytes": getattr(predicted, "a2a_bytes", 0.0),
            "a2a_time_s": getattr(predicted, "a2a_time_s", 0.0),
        }

    comm_s = float(predicted.get("comm_time_s") or 0.0)
    overlap_s = float(predicted.get("overlap_time_s") or 0.0)
    # Per-level comm terms of the hierarchical network model: the
    # cross-slice (DCN) share of comm_time_s/comm_bytes, broken out so
    # the calibration fit below can propose dcn_gbps independently of
    # ici_gbps.
    dcn_s = float(predicted.get("dcn_time_s") or 0.0)
    pred_dcn_bytes = float(predicted.get("dcn_bytes") or 0.0)
    pred_wire_saved = float(predicted.get("wire_bytes_saved") or 0.0)
    pred_qdq_s = float(predicted.get("quant_dq_time_s") or 0.0)
    pred_mem = float(predicted.get("mem_bytes_per_device") or 0.0)
    pred_logits = float(predicted.get("peak_logits_bytes") or 0.0)
    pred_param_shard = float(predicted.get("param_shard_bytes") or 0.0)
    pred_grad_shard = float(predicted.get("grad_shard_bytes") or 0.0)

    compute_s = None
    wire_s = None
    dcn_wire_s = None
    if cost_model is not None:
        bw_link = float(cost_model.link_profile.get(
            "ici_gbps", cost_model.chip.ici_gbps)) * 1e9
        # comm_bytes totals both levels; each level's wire term is fit
        # against its own bandwidth constant.
        wire_s = max(float(predicted.get("comm_bytes") or 0.0)
                     - pred_dcn_bytes, 0.0) / bw_link
        if pred_dcn_bytes and hasattr(cost_model, "_dcn_link"):
            bw_dcn, _ = cost_model._dcn_link()
            dcn_wire_s = pred_dcn_bytes / bw_dcn
        if flops_per_step:
            from autodist_tpu.simulator import cost_model as _cm

            mxu_eff = float(cost_model.link_profile.get(
                "mxu_efficiency", _cm._DEFAULT_MXU_EFFICIENCY))
            n = cost_model.spec.num_devices()
            peak = cost_model.chip.peak_bf16_tflops * 1e12 * n
            compute_s = float(flops_per_step) / (peak * mxu_eff)

    pred_step_s = comm_s + (compute_s or 0.0)
    pred_terms = {
        "step_time_s": pred_step_s,
        "comm_time_s": comm_s - overlap_s,   # blocking wire + launch term
        "exposed_overlap_s": overlap_s,
        "compute_time_s": compute_s,
        "comm_only": compute_s is None,
        "mem_bytes_per_device": pred_mem,
        # Peak loss-head logits buffer — the memory term vocab
        # parallelism divides by tp; broken out so a hardware window can
        # attribute an HBM delta between the replicated and
        # vocab-parallel configs to the logits term specifically.
        "peak_logits_bytes": pred_logits or None,
        # Per-device param/grad storage — the terms the ZeRO stages
        # divide (stage 2 the grads, stage 3 the params too); broken out
        # so an HBM delta between stages attributes to the right term.
        "param_shard_bytes": pred_param_shard or None,
        "grad_shard_bytes": pred_grad_shard or None,
        # Predicted bytes-on-wire delta of the per-collective precision
        # policy (and the q/dq compute charged against it): the terms a
        # hardware window joins against measured step time to check the
        # quantized-collectives win.
        "wire_bytes_saved": pred_wire_saved or None,
        "quant_dq_time_s": pred_qdq_s or None,
        # Per-level comm terms (hierarchical network model): the
        # cross-slice share of comm_time_s / comm_bytes, priced at the
        # DCN constants — what a multi-slice hardware window joins
        # against measured step time to fit dcn_gbps.
        "comm_time_dcn_s": dcn_s or None,
        "dcn_bytes": pred_dcn_bytes or None,
        # Expert dispatch/combine all_to_all breakout (already included
        # in comm_time_s, and in the dcn terms when the expert axis
        # crosses slices): the share a MoE hardware window joins
        # against measured step time to fit the a2a_ring constants.
        "a2a_bytes": float(predicted.get("a2a_bytes") or 0.0) or None,
        "a2a_time_s": float(predicted.get("a2a_time_s") or 0.0) or None,
        "comm_bytes": predicted.get("comm_bytes"),
        "num_collectives": predicted.get("num_collectives"),
        "feasible": predicted.get("feasible"),
    }

    meas_step_s, step_echo = _measured_step_seconds(measured.get("step"))
    meas_mem, mem_source = _measured_memory_bytes(measured.get("memory"))
    meas_terms: dict[str, Any] = dict(step_echo)
    if meas_step_s is not None:
        meas_terms["step_time_s"] = meas_step_s
    if meas_mem is not None:
        meas_terms["mem_bytes_per_device"] = meas_mem
        meas_terms["memory_source"] = mem_source
    if measured.get("examples_per_sec") is not None:
        meas_terms["examples_per_sec"] = float(measured["examples_per_sec"])

    ratios: dict[str, Optional[float]] = {}
    if meas_step_s is not None and pred_step_s > 0:
        ratios["step_time"] = meas_step_s / pred_step_s
    if meas_mem is not None and pred_mem > 0:
        ratios["memory"] = meas_mem / pred_mem
    residual_comm = None
    if meas_step_s is not None:
        residual_comm = max(meas_step_s - (compute_s or 0.0), 0.0)
        if comm_s > 0:
            ratios["comm_time"] = residual_comm / comm_s
        if compute_s:
            # comm_s may be 0 (single-device mesh): the compute ratio is
            # then the whole measured step against the compute term —
            # exactly the quantity the mxu_efficiency proposal fits.
            measured_compute = max(meas_step_s - comm_s, 0.0)
            if measured_compute > 0:
                ratios["compute_time"] = measured_compute / compute_s

    mfu = None
    if (measured.get("examples_per_sec") and measured.get("flops_per_example")
            and cost_model is not None):
        from autodist_tpu.utils import profiling

        peak = (cost_model.chip.peak_bf16_tflops * 1e12
                * cost_model.spec.num_devices())
        mfu = profiling.mfu(float(measured["examples_per_sec"]),
                            float(measured["flops_per_example"]), peak)
        meas_terms["mfu"] = mfu

    # ---- calibration proposal ---------------------------------------- #
    proposal: dict[str, Any] = {}
    if (cost_model is not None and residual_comm and residual_comm > 0
            and comm_s > 0):
        # First-order per-level bandwidth fit: split the comm residual
        # across the levels in proportion to their predicted shares,
        # then attribute each level's residual to its wire term.
        # measured_wire ≈ residual - launch overhead;
        # bytes/bw_new = residual ⇒ bw_new = bw_old · wire_s/residual.
        # With no dcn term the ici share is 1 — exactly the single-level
        # fit this report always made.
        ici_residual = residual_comm * max(comm_s - dcn_s, 0.0) / comm_s
        if wire_s and ici_residual > 0:
            old_ici = float(cost_model.link_profile.get(
                "ici_gbps", cost_model.chip.ici_gbps))
            new_ici = old_ici * wire_s / ici_residual
            if abs(new_ici - old_ici) / old_ici > _PROPOSAL_THRESHOLD:
                # significant digits, not decimal places: a CPU-mesh fit
                # can land orders of magnitude below 1 Gbps and must not
                # round to an (unusable) 0.0
                proposal.setdefault("link", {})["ici_gbps"] = \
                    float(f"{new_ici:.4g}")
        dcn_residual = residual_comm * dcn_s / comm_s
        if dcn_wire_s and dcn_residual > 0:
            # The dcn analog of the ici fit, proposed the same way —
            # a two-slice hardware window turns measured grad-sync time
            # into a measured "link" dcn_gbps mechanically.
            old_dcn = float(cost_model.link_profile.get(
                "dcn_gbps", getattr(cost_model.chip, "dcn_gbps", 5.0)))
            new_dcn = old_dcn * dcn_wire_s / dcn_residual
            if abs(new_dcn - old_dcn) / old_dcn > _PROPOSAL_THRESHOLD:
                proposal.setdefault("link", {})["dcn_gbps"] = \
                    float(f"{new_dcn:.4g}")
    if (cost_model is not None and compute_s and meas_step_s is not None):
        measured_compute = meas_step_s - comm_s
        if measured_compute > 0:
            from autodist_tpu.simulator import cost_model as _cm

            old_eff = float(cost_model.link_profile.get(
                "mxu_efficiency", _cm._DEFAULT_MXU_EFFICIENCY))
            new_eff = min(old_eff * compute_s / measured_compute, 1.0)
            if abs(new_eff - old_eff) / old_eff > _PROPOSAL_THRESHOLD:
                proposal.setdefault("link", {})["mxu_efficiency"] = \
                    float(f"{new_eff:.4g}")
    if proposal:
        proposal["note"] = (
            "first-order fit from ONE measured config; merge into "
            "calibration.json's \"link\" section only after a second "
            "config reproduces it (hop_alpha_s/dcn_alpha_s need two "
            "payload sizes to separate from bandwidth, and are left "
            "untouched)")

    report = {
        "kind": "drift",
        "strategy": {
            "id": getattr(strategy, "id", None),
            "lowering": getattr(
                getattr(strategy, "graph_config", None), "lowering", None),
        } if strategy is not None else None,
        "predicted": pred_terms,
        "measured": meas_terms,
        "ratios": ratios,
        "proposal": proposal or None,
    }

    tel = telemetry.get()
    for name, value in ratios.items():
        tel.gauge(f"drift/{name}_ratio").set(value)
    if mfu is not None:
        tel.gauge("drift/mfu").set(mfu)
    if pred_logits > 0:
        tel.gauge("memory/peak_logits_bytes").set(pred_logits)
    if pred_param_shard > 0:
        tel.gauge("memory/param_shard_bytes").set(pred_param_shard)
    if pred_grad_shard > 0:
        tel.gauge("memory/grad_shard_bytes").set(pred_grad_shard)
    if pred_wire_saved > 0:
        tel.gauge("comm/wire_bytes_saved").set(pred_wire_saved)
    if pred_dcn_bytes > 0:
        tel.gauge("comm/dcn_bytes").set(pred_dcn_bytes)
    pred_a2a_bytes = float(predicted.get("a2a_bytes") or 0.0)
    if pred_a2a_bytes > 0:
        tel.gauge("comm/a2a_bytes").set(pred_a2a_bytes)

    out_dir = out_dir or tel.out_dir
    if out_dir and tel.enabled:
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir, "drift.json")
            with open(path, "w") as f:
                json.dump(report, f, indent=1)
            report["path"] = path
        except OSError:  # report still returned; file is best-effort
            pass
    return report


# --------------------------------------------------------------------------- #
# Online (windowed) drift: the live half of the calibration loop
# --------------------------------------------------------------------------- #
class DriftMonitor:
    """Windowed measured-vs-predicted drift, evaluated DURING the run.

    :func:`drift_report` joins prediction against measurement once, at
    the end; this monitor keeps the join live — a rolling window of
    measured values per term (the shared
    :class:`~autodist_tpu.telemetry.aggregate.RollingWindow`), a
    ``drift/<term>_ratio`` gauge refreshed every ``every_n_steps``
    observed steps, and ONE schema-gated ``kind="drift"`` record each
    time a term's measured/predicted ratio crosses the ``threshold``
    band (edge-triggered: a term sitting in breach re-records only
    after it first returns inside the band).  ``on_drift`` is the
    opt-in callback hook the ROADMAP's re-election loop plugs into —
    this monitor lands the mechanical signal; invoking
    ``ElasticController.hot_swap`` from it stays follow-on work.

    ``predicted`` maps term name → predicted value (terms with a
    non-positive prediction are ignored: no ratio exists).  Feed
    measurements with :meth:`observe_step` (the runner hook) or the
    generic :meth:`observe`.
    """

    def __init__(self, predicted: dict, *, every_n_steps: int = 10,
                 threshold: float = 0.25, window: int = 64,
                 on_drift: Optional[Callable[[dict], None]] = None):
        from autodist_tpu.telemetry.aggregate import RollingWindow

        if every_n_steps < 1:
            raise ValueError("every_n_steps must be >= 1")
        if threshold <= 0:
            raise ValueError("threshold must be > 0")
        self.predicted = {str(k): float(v) for k, v in predicted.items()
                          if v is not None and float(v) > 0}
        if not self.predicted:
            raise ValueError(
                "DriftMonitor needs at least one term with a positive "
                "predicted value")
        self.every_n_steps = int(every_n_steps)
        self.threshold = float(threshold)
        self.on_drift = on_drift
        self._windows = {term: RollingWindow(window)
                         for term in self.predicted}
        self._breached: set = set()
        self._observed = 0
        self.events: list = []   # every emitted drift record, in order

    def observe(self, term: str, value: float) -> None:
        """Push one measured value for ``term`` (unknown terms are
        ignored — the monitor only tracks what was predicted)."""
        win = self._windows.get(term)
        if win is not None:
            win.push(float(value))

    def observe_step(self, step: int, duration_s: float) -> None:
        """The runner hook: fold one measured step and evaluate every
        ``every_n_steps`` observations."""
        self.observe("step_time", duration_s)
        self._observed += 1
        if self._observed % self.every_n_steps == 0:
            self.evaluate(step)

    def ratios(self) -> dict:
        """Current measured(p50-of-window)/predicted per term (terms
        with an empty window are absent)."""
        out = {}
        for term, win in self._windows.items():
            measured = win.percentile(50)
            if measured is not None:
                out[term] = measured / self.predicted[term]
        return out

    def evaluate(self, step: int) -> list:
        """Refresh the ``drift/<term>_ratio`` gauges and emit the
        edge-triggered ``kind="drift"`` records; returns the records
        emitted by THIS call."""
        from autodist_tpu import telemetry

        fired = []
        for term, ratio in self.ratios().items():
            telemetry.gauge(f"drift/{term}_ratio").set(ratio)
            breach = abs(ratio - 1.0) > self.threshold
            if breach and term not in self._breached:
                self._breached.add(term)
                event = dict(
                    term=term, ratio=float(ratio),
                    threshold=self.threshold, step=int(step),
                    predicted=self.predicted[term],
                    measured=float(ratio * self.predicted[term]),
                    direction="over" if ratio > 1.0 else "under")
                telemetry.record_event("drift", **event)
                self.events.append(event)
                fired.append(event)
                if self.on_drift is not None:
                    self.on_drift(event)
            elif not breach:
                self._breached.discard(term)
        return fired
