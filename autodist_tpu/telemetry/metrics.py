"""Metrics registry: counters, gauges, histograms.

The counterpart of the reference's ``TimeHistory`` meter and ad-hoc
per-run printouts (SURVEY.md §5.1), generalized: any subsystem registers
a named instrument once and updates it on the hot path; the registry
snapshots to JSONL lines (one ``{"kind": ..., "name": ..., ...}`` object
per line) and renders a human-readable summary.  Instruments are
process-wide and thread-safe — the AsyncPS server thread and the step
loop update the same registry.
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np

# Histogram sample cap: beyond it new observations still update count /
# sum / min / max but stop being retained for percentiles (the summary
# reports how many were dropped).  Keeps a million-step run's registry
# bounded.
HISTOGRAM_CAP = 65536


class Counter:
    """Monotonic event count (``asyncps/push``, ``bench/retries``...)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value

    def snapshot(self) -> dict:
        return {"kind": "counter", "name": self.name, "value": self._value}


class Gauge:
    """Last-write-wins scalar (HBM in use, MFU, examples/sec)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[float] = None
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> Optional[float]:
        return self._value

    def snapshot(self) -> dict:
        return {"kind": "gauge", "name": self.name, "value": self._value}


class Histogram:
    """Distribution of observations (step latency, SSP gate waits)."""

    __slots__ = ("name", "_values", "_count", "_sum", "_min", "_max",
                 "_lock")

    def __init__(self, name: str):
        self.name = name
        self._values: list[float] = []
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._count += 1
            self._sum += value
            self._min = value if self._min is None else min(self._min, value)
            self._max = value if self._max is None else max(self._max, value)
            if len(self._values) < HISTOGRAM_CAP:
                self._values.append(value)

    @property
    def count(self) -> int:
        return self._count

    def percentile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._values:
                return None
            return float(np.percentile(np.asarray(self._values), q))

    def snapshot(self) -> dict:
        with self._lock:
            vs = np.asarray(self._values) if self._values else None
        out = {"kind": "histogram", "name": self.name, "count": self._count,
               "sum": self._sum, "min": self._min, "max": self._max,
               "mean": (self._sum / self._count) if self._count else None,
               "p50": float(np.percentile(vs, 50)) if vs is not None else None,
               "p99": float(np.percentile(vs, 99)) if vs is not None else None}
        if self._count > len(self._values):
            out["samples_dropped"] = self._count - len(self._values)
        return out


class NullInstrument:
    """The disabled path's stand-in for every instrument kind: all
    updates are no-ops, all reads are empty.  A single shared instance —
    the zero-overhead-when-disabled contract is that call sites hold no
    per-call allocation or state."""

    __slots__ = ()
    name = "<disabled>"
    value = None
    count = 0

    def inc(self, n: int = 1) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def percentile(self, q: float) -> None:
        return None

    def snapshot(self) -> dict:
        return {}


NULL_INSTRUMENT = NullInstrument()


class MetricsRegistry:
    """Name → instrument map; get-or-create, kind-checked."""

    def __init__(self):
        self._instruments: dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, requested {cls.__name__}")
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> list[dict]:
        """One JSONL-ready dict per instrument, name-sorted."""
        with self._lock:
            insts = sorted(self._instruments.items())
        return [inst.snapshot() for _, inst in insts]

    def summary_lines(self) -> list[str]:
        """Human-readable one-liner per instrument."""
        lines = []
        for snap in self.snapshot():
            if snap["kind"] == "histogram":
                mean = snap["mean"]
                lines.append(
                    f"{snap['name']}: n={snap['count']}"
                    + (f" mean={mean:.6g} p50={snap['p50']:.6g} "
                       f"p99={snap['p99']:.6g}" if mean is not None else ""))
            else:
                lines.append(f"{snap['name']}: {snap['value']}")
        return lines
