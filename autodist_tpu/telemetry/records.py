"""Run manifest + provenance: the identity stamp of a measurement.

One schema for what used to live in two places: ``bench.py``'s
git-SHA/jax-version record (every BENCH_r*.json row) and
``examples/pipeline_train.py``'s hand-rolled ``step_times.json``.  A
hardware window's numbers must stay interpretable months later — the
manifest records exactly which code and stack produced them.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional

# Env vars worth recording: the launch-config plane that changes what a
# run measures.
_MANIFEST_ENV = (
    "AUTODIST_TPU_WORKER", "AUTODIST_TPU_STRATEGY_ID",
    "AUTODIST_TPU_NUM_PROCESSES", "AUTODIST_TPU_PROCESS_ID",
    "AUTODIST_TPU_GENERATION", "AUTODIST_TPU_ASYNC_COLLECTIVES",
    "AUTODIST_TPU_TELEMETRY", "AUTODIST_TPU_TELEMETRY_SAMPLE",
    "JAX_PLATFORMS", "XLA_FLAGS",
)

_provenance_cache: dict[str, dict] = {}


def provenance(repo_root: Optional[str] = None, refresh: bool = False) -> dict:
    """Identity stamp: git SHA + jax/jaxlib/python versions (the exact
    keys ``bench.py`` has always embedded — ``git_sha``/``jax``/
    ``jaxlib`` — so BENCH record consumers keep working).  Cached per
    root: the answer cannot change within a process, but different
    callers may stamp different checkouts."""
    root = repo_root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if root in _provenance_cache and not refresh:
        return dict(_provenance_cache[root])
    rec: dict = {}
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=root,
            capture_output=True, text=True, timeout=10).stdout.strip()
        rec["git_sha"] = sha or None
    except (OSError, subprocess.SubprocessError):
        rec["git_sha"] = None
    try:
        import jax

        rec["jax"] = getattr(jax, "__version__", None)
    except ImportError:  # pragma: no cover - jax is a hard dep
        rec["jax"] = None
    try:
        import jaxlib

        rec["jaxlib"] = getattr(jaxlib, "__version__", None)
    except ImportError:  # pragma: no cover
        rec["jaxlib"] = None
    rec["python"] = sys.version.split()[0]
    _provenance_cache[root] = rec
    return dict(rec)


def build_manifest(annotations: Optional[dict] = None,
                   telemetry: Optional[dict] = None) -> dict:
    """The run-manifest dict ``Telemetry.flush`` writes as
    ``manifest.json``: provenance + launch env + run annotations."""
    manifest = {
        "kind": "manifest",
        "created_unix": time.time(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "provenance": provenance(),
        "env": {k: os.environ[k] for k in _MANIFEST_ENV
                if k in os.environ},
    }
    if annotations:
        manifest["run"] = dict(annotations)
    if telemetry:
        manifest["telemetry"] = dict(telemetry)
    return manifest
