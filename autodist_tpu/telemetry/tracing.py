"""Distributed request tracing: trace ids, context propagation, and
cross-process shard stitching.

A request that enters :meth:`Router.submit` is minted ONE ``trace_id``
that travels with it everywhere it goes — chief-side dispatch records,
the coord-service submit op, the worker batcher's prefill/decode spans,
the disaggregated handoff record, the completion's ``kind="serve"``
record.  Each process keeps writing its own telemetry shard exactly as
before (``<tel_dir>/trace.json`` chief-side,
``<tel_dir>/<replica>-i<inc>/trace.json`` per worker incarnation);
:func:`stitch_trace` merges the shards into ONE chrome-trace whose
events keep their real pids — loadable as-is in ``chrome://tracing`` /
Perfetto, with one named process track per shard.

Span timestamps are wall-clock anchored at telemetry construction
(``epoch_wall_us + monotonic delta``, :mod:`autodist_tpu.telemetry.core`),
so shards from different processes land on one comparable timeline
without any clock negotiation.  Typed records (``dispatch`` / ``fault``
/ ``handoff`` / ``scale`` / ``serve`` / ``drift``) carry the same-anchor
``ts_us`` stamp and are folded into the stitched trace as instant
events — a failover reads causally in one view: the fault instant on
the dead replica's track, the ``dispatch/failover`` instant on the
chief's, the re-prefill span on the survivor's.

The context plumbing is :mod:`contextvars`-based so the ambient trace
id survives threads the way spans' nesting stacks do: code inside
``with trace_context() as tid:`` gets its spans and records auto-tagged
without threading ``trace_id=`` through every call.
"""
from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
from typing import Optional

# Record kinds folded into the stitched trace as instant events (the
# causal glue between span shards); anything else stays JSONL-only.
_FOLDED_KINDS = ("dispatch", "fault", "handoff", "scale", "serve",
                 "drift")

_ids = itertools.count()
_current: contextvars.ContextVar = contextvars.ContextVar(
    "autodist_tpu_trace_id", default=None)


def mint_trace_id() -> str:
    """A process-unique trace id: pid + a monotone counter — no
    randomness, so a deterministic run mints a deterministic sequence
    (the cross-process parity tests rely on reproducible submits)."""
    return f"tr-{os.getpid():x}-{next(_ids):04x}"


def current_trace_id() -> Optional[str]:
    """The ambient trace id (``None`` outside any trace context)."""
    return _current.get()


@contextlib.contextmanager
def trace_context(trace_id: Optional[str] = None):
    """Bind ``trace_id`` (minting one when not given) as the ambient
    trace for the dynamic extent: spans and records emitted inside are
    auto-tagged with it.  Yields the id."""
    tid = trace_id if trace_id is not None else mint_trace_id()
    token = _current.set(tid)
    try:
        yield tid
    finally:
        _current.reset(token)


# --------------------------------------------------------------------------- #
# Stitching
# --------------------------------------------------------------------------- #
def _load_trace_events(path: str) -> list:
    try:
        with open(path) as f:
            data = json.load(f)
        events = data.get("traceEvents", [])
        return events if isinstance(events, list) else []
    except (OSError, ValueError):
        return []


def _load_records(path: str) -> list:
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError:
        pass
    return records


def _shard_dirs(run_dir: str) -> list:
    """Worker shard directories under ``run_dir`` (any subdirectory a
    worker flushed a trace or metrics shard into), name-sorted for a
    deterministic stitch."""
    shards = []
    try:
        entries = sorted(os.listdir(run_dir))
    except OSError:
        return []
    for name in entries:
        sub = os.path.join(run_dir, name)
        if not os.path.isdir(sub):
            continue
        if os.path.exists(os.path.join(sub, "trace.json")) \
                or os.path.exists(os.path.join(sub, "metrics.jsonl")):
            shards.append(sub)
    return shards


def _fold_record(rec: dict, pid: int) -> Optional[dict]:
    """One typed record as a chrome-trace instant event (``ph="i"``) on
    its process's track — only records stamped with the wall-anchored
    ``ts_us`` fold (pre-stamp records stay JSONL-only)."""
    kind = rec.get("kind")
    ts = rec.get("ts_us")
    if kind not in _FOLDED_KINDS or not isinstance(ts, (int, float)):
        return None
    detail = {"dispatch": rec.get("reason"), "fault": rec.get("phase"),
              "scale": rec.get("direction"), "serve": rec.get("finish"),
              "drift": rec.get("term")}.get(kind)
    name = f"{kind}/{detail}" if detail else str(kind)
    args = {k: v for k, v in rec.items() if k not in ("kind", "ts_us")}
    args["folded"] = True
    return {"name": name, "ph": "i", "s": "g", "pid": pid, "tid": 0,
            "ts": float(ts), "args": args}


def _shard_events(shard_dir: str, fallback_pid: int) -> tuple:
    """``(span events, folded record instants, pid)`` for one shard."""
    events = [ev for ev in _load_trace_events(
        os.path.join(shard_dir, "trace.json"))
        if ev.get("ph") != "M"
        and not (ev.get("args") or {}).get("folded")
        and not (ev.get("args") or {}).get("stitched_from")]
    pid = next((ev["pid"] for ev in events
                if isinstance(ev.get("pid"), int)), fallback_pid)
    instants = []
    for rec in _load_records(os.path.join(shard_dir, "metrics.jsonl")):
        ev = _fold_record(rec, pid)
        if ev is not None:
            instants.append(ev)
    return events, instants, pid


def stitch_trace(run_dir: str, out_path: Optional[str] = None) -> dict:
    """Merge the chief's span shard and every worker shard under
    ``run_dir`` into ONE chrome trace, written to ``out_path``
    (default: ``run_dir/trace.json`` — the stitched trace REPLACES the
    chief shard, so a run directory always holds exactly one
    ``trace.json``).  Idempotent: re-stitching drops previously folded
    instants and metadata before merging again.

    Returns the stitched trace dict; its ``stitched`` key records the
    pids and shard directories merged (chrome ignores extra top-level
    keys)."""
    events = []
    pid_labels: dict[int, str] = {}
    chief_events, chief_instants, chief_pid = _shard_events(
        run_dir, os.getpid())
    events += chief_events + chief_instants
    pid_labels[chief_pid] = "chief"
    for i, shard in enumerate(_shard_dirs(run_dir)):
        label = os.path.basename(shard)
        shard_events, instants, pid = _shard_events(
            shard, fallback_pid=-(i + 1))
        for ev in shard_events + instants:
            # Provenance marker: absorbed-from-a-worker-shard events
            # are dropped when the stitched output is re-read as the
            # chief shard, then re-absorbed fresh — idempotency.
            ev.setdefault("args", {})["stitched_from"] = label
        events += shard_events + instants
        pid_labels.setdefault(pid, label)
    meta = [{"name": "process_name", "ph": "M", "ts": 0.0, "pid": pid,
             "tid": 0, "args": {"name": label}}
            for pid, label in sorted(pid_labels.items())]
    events.sort(key=lambda ev: (ev.get("ts", 0.0), ev.get("pid", 0)))
    trace = {"traceEvents": meta + events, "displayTimeUnit": "ms",
             "stitched": {"pids": sorted(pid_labels),
                          "shards": len(pid_labels)}}
    out_path = out_path or os.path.join(run_dir, "trace.json")
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return trace


# --------------------------------------------------------------------------- #
# Per-request timelines
# --------------------------------------------------------------------------- #
def event_trace_ids(ev: dict) -> list:
    """Every trace id an event is tagged with (a batched span carries
    the ``trace_ids`` of all its resident requests; a record instant
    carries one ``trace_id``)."""
    args = ev.get("args") or {}
    ids = []
    tid = args.get("trace_id")
    if tid:
        ids.append(tid)
    many = args.get("trace_ids")
    if isinstance(many, (list, tuple)):
        ids.extend(t for t in many if t)
    return ids


def request_timeline(trace: dict, trace_id: str) -> list:
    """The ts-ordered events of one request across every process: the
    spans and folded instants tagged with ``trace_id``."""
    events = [ev for ev in trace.get("traceEvents", [])
              if trace_id in event_trace_ids(ev)]
    events.sort(key=lambda ev: ev.get("ts", 0.0))
    return events
