"""High-level training loop: the reference's Keras ``Model.fit`` tier.

The reference's integration case c7 drove training through
``Model.fit``/``evaluate`` on top of the distributed session
(``tests/integration/cases/c7.py``); :func:`fit` is that convenience for
this framework — loader prefetch, periodic eval, periodic/final
checkpointing, throughput logging, and preemption-safe resume in one
call, all composed from the public pieces (``DataLoader``, ``Saver``,
``runner.step/evaluate``).
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

import numpy as np

from autodist_tpu import telemetry
from autodist_tpu.utils import logging


def fit(runner, source: Iterable | Callable[[int], Any], *,
        steps: int,
        eval_source: Optional[Iterable | Callable[[int], Any]] = None,
        eval_every: int = 0, eval_batches: int = 10,
        saver=None, save_every: int = 0,
        resume: bool = True,
        log_every: int = 100,
        prefetch: int = 2,
        steps_per_loop: int = 1) -> dict:
    """Train ``runner`` for ``steps`` optimizer steps.

    Args:
      runner: a built runner (``AutoDist(...).build(trainable)``).
      source: host-batch source — an iterable, or ``step -> batch``.
      eval_source / eval_every / eval_batches: run
        ``runner.evaluate`` over ``eval_batches`` batches every
        ``eval_every`` steps (0 = never).  Pass a callable or a
        re-iterable (e.g. a list) — a one-shot iterator is exhausted
        after the first eval round.
      saver: a :class:`~autodist_tpu.checkpoint.Saver`; when given, a
        final checkpoint is always written, plus one every
        ``save_every`` steps (0 = final only).  With ``resume=True``
        training continues from the saver's latest step — restarted
        preempted jobs pick up where they left off.
      log_every: throughput/loss log cadence (0 = silent).
      prefetch: device-prefetch depth (see :class:`DataLoader`).
      steps_per_loop: fuse up to this many steps into one device
        dispatch (:meth:`DistributedRunner.run_steps`); windows never
        cross a log/eval/save boundary, so every cadence fires at
        exactly the same steps as the per-step loop.  Each DISTINCT
        window size compiles its own k-step program — pick a
        steps_per_loop that divides the active cadences (or vice versa)
        to keep one size; misaligned cadences still work but pay a
        compile per size.  1 (default) keeps per-step dispatch with
        DataLoader prefetch.

    Returns a history dict: ``{"steps", "loss", "eval", "examples_per_sec"}``.
    """
    from autodist_tpu.data import DataLoader

    if saver is not None and resume and saver.latest_step() is not None:
        saver.restore(runner)
        logging.info("fit: resumed at step %d", runner.step_count)
    start = runner.step_count
    remaining = steps - start
    history: dict[str, Any] = {"steps": steps, "loss": [], "eval": [],
                               "examples_per_sec": 0.0}
    if remaining <= 0:
        logging.info("fit: nothing to do (at step %d >= %d)", start, steps)
        return history

    if callable(source) and start:
        # Resumed jobs continue the data stream, not replay it; iterable
        # sources are consumed wherever they stand and are the caller's
        # responsibility to fast-forward.
        inner = source
        source = lambda i: inner(start + i)  # noqa: E731
    import time

    fused = steps_per_loop > 1 and hasattr(runner, "run_steps")
    if fused:
        import jax

        from autodist_tpu.runner import stack_steps

        it = iter(_iter_source(source, remaining))
        pending: list = []   # lookahead for shape-change window breaks

        def next_window_size(step: int) -> int:
            """Largest window ending at (not crossing) the next cadence
            boundary, so logs/evals/saves fire at the same steps as the
            per-step loop."""
            k = min(steps_per_loop, start + remaining - step)
            for every in (log_every,
                          eval_every if eval_source is not None else 0,
                          save_every if saver is not None else 0):
                if every:
                    k = min(k, every - step % every)
            return k

        def shape_sig(b):
            return tuple(np.shape(l) for l in jax.tree.leaves(b))

        def take_window(k: int) -> list:
            """Up to ``k`` CONSECUTIVE same-shape batches (stack_steps
            needs uniform leaves; a ragged final batch — fine on the
            per-step path — just becomes its own window of 1)."""
            while len(pending) < k:
                try:
                    pending.append(next(it))
                except StopIteration:
                    break
            if not pending:
                return []
            sig = shape_sig(pending[0])
            w = []
            while pending and len(w) < k and shape_sig(pending[0]) == sig:
                w.append(pending.pop(0))
            return w
    loader = None if fused else iter(
        DataLoader(source, runner.mesh, buffer_size=prefetch,
                   num_batches=remaining,
                   lowered=getattr(runner, "lowered", None)))

    t0 = time.perf_counter()
    examples = window_examples = 0
    t_window = t0
    # Host-side step mirror: reading runner.step_count would block on
    # the in-flight (async) window's device state every iteration.
    step = start
    fit_span = telemetry.span("train/fit", steps=steps, start=start,
                              fused=bool(fused))
    with fit_span:
        while step < start + remaining:
            t_step = time.perf_counter()
            if fused:
                window = take_window(next_window_size(step))
                if not window:
                    break
                stacked_metrics = runner.run_steps(stack_steps(window))
                metrics = {k: v[-1] for k, v in stacked_metrics.items()}
                bsz = _batch_size(window[0]) * len(window)
                step += len(window)
                telemetry.record_step(
                    step=step - 1, duration_s=time.perf_counter() - t_step,
                    examples=bsz, steps=len(window))
            else:
                try:
                    batch = next(loader)
                except StopIteration:
                    break
                metrics = runner.step(batch)
                bsz = _batch_size(batch)
                step += 1
                telemetry.record_step(
                    step=step - 1, duration_s=time.perf_counter() - t_step,
                    examples=bsz)
            examples += bsz
            window_examples += bsz
            if log_every and step % log_every == 0:
                loss = float(np.asarray(metrics.get("loss", np.nan)))
                dt = time.perf_counter() - t_window
                rate = window_examples / dt if dt > 0 else float("nan")
                history["loss"].append((step, loss))
                logging.info("fit: step %d loss %.4f (%.1f examples/s)",
                             step, loss, rate)
                window_examples, t_window = 0, time.perf_counter()
            if eval_every and eval_source is not None \
                    and step % eval_every == 0:
                with telemetry.span("train/eval", step=step):
                    ev = runner.evaluate(
                        _iter_source(eval_source, eval_batches),
                        num_batches=eval_batches)
                if not ev:
                    logging.warning(
                        "fit: eval at step %d saw no batches — a one-shot "
                        "iterator eval_source is exhausted; pass a callable "
                        "or a re-iterable (list)", step)
                history["eval"].append((step, ev))
                logging.info("fit: step %d eval %s", step,
                             {k: round(float(v), 4) for k, v in ev.items()})
            if saver is not None and save_every and step % save_every == 0:
                with telemetry.span("train/checkpoint", step=step):
                    saver.save(runner)

        if saver is not None and saver.latest_step() != runner.step_count:
            with telemetry.span("train/checkpoint", step=runner.step_count):
                saver.save(runner, force=True)
    total = time.perf_counter() - t0
    history["examples_per_sec"] = examples / total if total > 0 else 0.0
    if history["examples_per_sec"]:
        telemetry.gauge("fit/examples_per_sec").set(
            history["examples_per_sec"])
    return history


def _batch_size(batch) -> int:
    import jax

    for leaf in jax.tree.leaves(batch):
        if np.ndim(leaf) > 0:
            return int(np.shape(leaf)[0])
    return 0


def _iter_source(source, n: int):
    if callable(source):
        return (source(i) for i in range(n))
    return source
