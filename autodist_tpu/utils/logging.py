"""Project logger: console + timestamped file.

Counterpart of reference ``autodist/utils/logging.py:33-106`` (own logger,
stderr + file under a working dir, level from env).
"""
import logging as _logging
import os
import sys
import time

from autodist_tpu import const

_LOGGER_NAME = "autodist_tpu"
_logger = None


def get_logger():
    """Return the singleton framework logger (console + file handler)."""
    global _logger
    if _logger is not None:
        return _logger
    logger = _logging.getLogger(_LOGGER_NAME)
    logger.propagate = False
    level = const.ENV.AUTODIST_TPU_MIN_LOG_LEVEL.val.upper()
    logger.setLevel(getattr(_logging, level, _logging.INFO))
    fmt = _logging.Formatter(
        "%(asctime)s %(levelname).1s %(process)d %(filename)s:%(lineno)d] %(message)s"
    )
    sh = _logging.StreamHandler(sys.stderr)
    sh.setFormatter(fmt)
    logger.addHandler(sh)
    try:
        os.makedirs(const.DEFAULT_LOG_DIR, exist_ok=True)
        # Per-run name: pid + timestamp.  Concurrent workers on one host
        # (multi-process launches, AutoStrategy measurement subprocesses)
        # used to collide on the same epoch-second filename and interleave
        # into one file.
        fh = _logging.FileHandler(
            os.path.join(const.DEFAULT_LOG_DIR,
                         f"{os.getpid()}-{int(time.time())}.log")
        )
        fh.setFormatter(fmt)
        logger.addHandler(fh)
    except OSError:  # read-only fs etc. — console-only logging is fine
        pass
    _logger = logger
    return logger


def set_verbosity(level):
    """Set the level on the logger AND its handlers: a handler carrying
    its own (stricter) level would otherwise keep filtering records the
    logger now admits."""
    logger = get_logger()
    logger.setLevel(level)
    for handler in logger.handlers:
        handler.setLevel(level)


def debug(msg, *a):
    get_logger().debug(msg, *a, stacklevel=2)


def info(msg, *a):
    get_logger().info(msg, *a, stacklevel=2)


def warning(msg, *a):
    get_logger().warning(msg, *a, stacklevel=2)


def error(msg, *a):
    get_logger().error(msg, *a, stacklevel=2)
