"""Tracing / profiling / throughput meters.

Counterpart of the reference's observability layer (SURVEY.md §5.1):
chrome-trace timelines per ``session.run`` (``runner.py:64-75``), graph
transformation-stage snapshots (``visualization_util.py:24-36``), and the
benchmark ``TimeHistory`` examples/sec meter
(``examples/benchmark/imagenet.py:84-140``) — rebuilt on ``jax.profiler``
traces (TensorBoard/Perfetto), HLO stage dumps, and blocking step timers.
"""
from __future__ import annotations

import contextlib
import os
import time
from typing import Optional

import numpy as np

from autodist_tpu import const
from autodist_tpu.utils import logging


@contextlib.contextmanager
def trace(trace_dir: Optional[str] = None):
    """Profile a region to a TensorBoard/Perfetto trace
    (≙ chrome://tracing JSON under ``/tmp/autodist/traces``)."""
    import jax

    trace_dir = trace_dir or const.DEFAULT_TRACE_DIR
    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)
    try:
        yield trace_dir
    finally:
        jax.profiler.stop_trace()
        logging.info("trace written to %s", trace_dir)


def dump_stages(lowered, trainable, strategy, out_dir: Optional[str] = None,
                example_batch=None):
    """Dump the per-stage artifacts of a build (≙ the reference's
    0-original … 3-transformed TensorBoard graph snapshots,
    ``graph_transformer.py:62-90``):

      0-strategy.json   — the strategy IR
      1-plan.txt        — resolved per-variable lowering plan
      2-step.hlo.txt    — the compiled SPMD step's HLO
    """
    import jax

    out_dir = out_dir or os.path.join(const.DEFAULT_WORKING_DIR, "stages")
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "0-strategy.json"), "w") as f:
        f.write(strategy.to_json())
    with open(os.path.join(out_dir, "1-plan.txt"), "w") as f:
        plan = getattr(lowered, "plan", None)
        if plan is not None and hasattr(plan, "var_plans"):
            for name, vp in plan.var_plans.items():
                f.write(f"{name}: stored_sharded={vp.stored_sharded} "
                        f"axis={vp.split_axis} update={vp.update} "
                        f"bucket={vp.bucket} compressor={vp.compressor}\n")
        else:
            f.write("gspmd lowering (XLA-derived collectives)\n")
    if example_batch is not None:
        try:
            import jax.random as jrandom
            state = lowered.init_state(trainable=trainable)
            txt = lowered.step_fn.lower(
                state, example_batch, jrandom.PRNGKey(0)).as_text()
            with open(os.path.join(out_dir, "2-step.hlo.txt"), "w") as f:
                f.write(txt)
        except Exception as e:  # HLO dump is best-effort observability
            logging.warning("HLO dump failed: %s", e)
    logging.info("stage dumps written to %s", out_dir)
    return out_dir


class StepTimer:
    """Throughput meter (≙ ``TimeHistory``: examples/sec =
    batch_size × log_steps / elapsed)."""

    def __init__(self, batch_size: int, warmup: int = 2):
        self.batch_size = batch_size
        self.warmup = warmup
        self._times: list[float] = []
        self._t0: Optional[float] = None
        self._count = 0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self._count += 1
        if self._count > self.warmup:
            self._times.append(dt)
            # Mirror into the process-wide registry so a flushed run
            # carries the meter's distribution without a second wiring.
            from autodist_tpu import telemetry

            telemetry.histogram("steptimer/step_s").observe(dt)

    @property
    def steps_recorded(self) -> int:
        return len(self._times)

    @property
    def mean_step_seconds(self) -> float:
        return float(np.mean(self._times)) if self._times else float("nan")

    @property
    def examples_per_sec(self) -> float:
        return self.batch_size / self.mean_step_seconds

    def summary(self) -> dict:
        ts = np.asarray(self._times)
        return {
            "steps": len(ts),
            "mean_ms": float(ts.mean() * 1e3) if len(ts) else None,
            "p50_ms": float(np.percentile(ts, 50) * 1e3) if len(ts) else None,
            "p99_ms": float(np.percentile(ts, 99) * 1e3) if len(ts) else None,
            "examples_per_sec": self.examples_per_sec if len(ts) else None,
        }


def mfu(examples_per_sec: float, flops_per_example: float,
        peak_flops_total: float) -> float:
    """Model FLOP utilization (the BASELINE.md headline metric)."""
    return examples_per_sec * flops_per_example / peak_flops_total


def transformer_train_flops_per_token(num_params: int) -> float:
    """6N approximation: fwd 2N + bwd 4N FLOPs per token."""
    return 6.0 * num_params


def memory_summary(device=None) -> dict:
    """Per-device HBM usage snapshot (bytes), where the backend exposes
    it (TPU does; CPU returns {}).  The observability analog of the
    reference's trace/metadata collection (``runner.py:64-75``) for the
    memory axis — pair with the cost model's mem_bytes_per_device to
    validate a strategy's predicted footprint."""
    import jax

    device = device or jax.local_devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if not stats:
        return {}
    out = {k: int(v) for k, v in stats.items() if isinstance(v, (int,))}
    if "bytes_in_use" in out and "bytes_limit" in out and out["bytes_limit"]:
        out["utilization"] = out["bytes_in_use"] / out["bytes_limit"]
    return out
