"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline (BASELINE.md): BERT-base masked-LM training MFU — the reference's
flagship benchmark (``examples/benchmark/bert.py``) measured the way its
``TimeHistory`` meter did (examples/sec = batch x steps / elapsed,
``examples/benchmark/imagenet.py:84-140``), converted to model-FLOP
utilization against the chip's peak bf16 throughput.  Runs on whatever
devices are visible (the driver runs this on real TPU hardware; on a CPU
dev machine it shrinks the model so the bench stays fast).
"""
import json
import os
import subprocess
import sys
import tempfile
import time

import jax
import numpy as np
import optax

# The monitor runs as a separate *process*: a SIGALRM watchdog cannot
# preempt a C call that never returns to the interpreter (observed: a
# wedged tunnel client blocks inside PJRT client init and the alarm
# handler runs only when something else unblocks the call), so in-process
# schemes can die silently — exactly what the driver must never see.
_MONITOR_SRC = r"""
import json, os, signal, sys, time
ppid, stage_path, secs = int(sys.argv[1]), sys.argv[2], float(sys.argv[3])
partial_path = sys.argv[4]
deadline = time.time() + secs
while time.time() < deadline:
    time.sleep(1.0)
    try:
        os.kill(ppid, 0)          # parent finished -> it killed us already,
    except OSError:               # or died on its own: stay silent either way
        sys.exit(0)
try:
    with open(stage_path) as f:
        stage = f.read().strip() or "?"
except OSError:
    stage = "?"
# A timed-out bench may still have MEASURED something: the probe loop
# drops its best-so-far record into partial_path as rates land.  A real
# (if low-confidence) number beats a bare diagnostic — the whole round
# may get exactly one hardware window.
record = None
try:
    with open(partial_path) as f:
        record = json.load(f)
except (OSError, ValueError):
    pass
if record and record.get("value"):
    if not record.get("scored"):
        # Only probe-grade data landed before the hang: flag it.  A
        # record carrying "scored" already IS a completed measured run
        # (the bench scores first, then tunes) — report it unflagged.
        record["partial"] = (f"watchdog fired after {int(secs)}s during "
                             f"stage {stage!r}; value is the best probe "
                             f"rate, not the scored run")
    print(json.dumps(record), flush=True)
else:
    print(json.dumps({
        "metric": "bert_base_mlm_mfu", "value": 0.0, "unit": "mfu",
        "vs_baseline": 0.0,
        "error": f"watchdog: no result after {int(secs)}s; stuck in stage "
                 f"{stage!r} (accelerator backend unresponsive)"}), flush=True)
try:
    os.kill(ppid, signal.SIGKILL)
except OSError:
    pass
"""


class _Watchdog:
    """Whole-run hang watchdog in a child process sharing our stdout: if
    the bench produces no result within the budget, the child prints a
    diagnostic JSON line (with the live stage label) and kills the bench."""

    def __init__(self, seconds: int, stage: str):
        self.seconds = seconds
        fd, self._stage_path = tempfile.mkstemp(prefix="bench_stage_")
        os.close(fd)
        fd, self.partial_path = tempfile.mkstemp(prefix="bench_partial_")
        os.close(fd)
        os.unlink(self.partial_path)  # exists only once a probe lands
        self._proc = None
        self.stage = stage

    @property
    def stage(self):
        return self._stage

    @stage.setter
    def stage(self, value: str):
        self._stage = value
        try:
            with open(self._stage_path, "w") as f:
                f.write(value)
        except OSError:
            pass

    def arm(self):
        self.armed_at = time.monotonic()   # the budget clock _bench reads
        self._proc = subprocess.Popen(
            [sys.executable, "-c", _MONITOR_SRC,
             str(os.getpid()), self._stage_path, str(self.seconds),
             self.partial_path],
            stdout=None, stderr=subprocess.DEVNULL)  # inherit our stdout
        return self

    def disarm(self):
        """Kill + reap the monitor.  Call *before* printing the result
        line: after wait() returns the child has either never fired or
        already flushed its error line, so the real record — printed
        after — is the last JSON line on stdout either way."""
        if self._proc is not None:
            self._proc.kill()
            self._proc.wait()
            self._proc = None
        for p in (self._stage_path, self.partial_path):
            try:
                os.unlink(p)
            except OSError:
                pass


def _provenance() -> dict:
    """Identity stamp for every emitted record (git SHA + jax/jaxlib
    versions) — one schema with every other run artifact: the telemetry
    run manifest owns it (``telemetry/records.py``)."""
    from autodist_tpu.telemetry import records

    return records.provenance(
        repo_root=os.path.dirname(os.path.abspath(__file__)))


def _probe_summary(timeout_s: float) -> dict:
    """Structural provenance: per-probe pass/fail of ``tools/hlo_probe.py``
    (collective counts proven on a simulated CPU mesh), run in a fresh
    CPU-pinned subprocess — the bench process owns the accelerator
    backend and cannot host the probe's 8-device CPU mesh.  Skips (with
    the reason recorded) rather than risking the measurement budget."""
    if os.environ.get("AUTODIST_TPU_BENCH_PROBE", "1") in ("0", "false"):
        return {"skipped": "AUTODIST_TPU_BENCH_PROBE=0"}
    if timeout_s < 120:
        return {"skipped": f"no budget ({int(timeout_s)}s left)"}
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "hlo_probe.py")
    fd, out = tempfile.mkstemp(prefix="bench_probe_", suffix=".json")
    os.close(fd)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # A TPU bench environment may carry TPU-only XLA flags (the
    # AUTODIST_TPU_ASYNC_COLLECTIVES knob appends some): XLA *aborts* on
    # flags a CPU build doesn't define, so the probe subprocess gets
    # them stripped.
    env.pop("AUTODIST_TPU_ASYNC_COLLECTIVES", None)
    from autodist_tpu.kernel.lowering import LATENCY_HIDING_XLA_FLAGS
    if env.get("XLA_FLAGS"):
        env["XLA_FLAGS"] = " ".join(
            f for f in env["XLA_FLAGS"].split()
            if not f.startswith("--xla_tpu")
            and f not in LATENCY_HIDING_XLA_FLAGS)
    try:
        proc = subprocess.run(
            [sys.executable, script, "--json", out],
            env=env, capture_output=True, text=True, timeout=timeout_s)
        with open(out) as f:
            report = json.load(f)
        summary = {"ok": proc.returncode == 0,
                   "probes": {name: bool(r.get("ok"))
                              for name, r in report.items()}}
        failed = [n for n, r in report.items() if not r.get("ok")]
        if failed:
            summary["failed"] = failed
        return summary
    except subprocess.TimeoutExpired:
        return {"skipped": f"probe subprocess exceeded {int(timeout_s)}s"}
    except (OSError, ValueError) as e:
        return {"skipped": f"probe run failed: {e}"}
    finally:
        try:
            os.unlink(out)
        except OSError:
            pass


def _fail_record(msg: str, skipped: bool = False) -> str:
    """The one failure-record shape: hw_session.sh greps these exact keys
    (``"error"``/``"value"``) to gate the measurement queue, so every
    in-process failure path must emit the same dict."""
    rec = {"metric": "bert_base_mlm_mfu", "value": 0.0, "unit": "mfu",
           "vs_baseline": 0.0, "error": msg, "provenance": _provenance()}
    if skipped:
        rec["skipped"] = True
    return json.dumps(rec)


_MAX_ATTEMPTS = 3


def _backoff_delay(attempt: int, base: float = 5.0,
                   cap: float = 60.0) -> float:
    """Capped exponential backoff: 5s, 10s, ... <= 60s — the shared
    implementation (``runtime/retry.py``); only the bench defaults live
    here.  The fresh-process re-exec loop itself cannot ride
    ``RetryPolicy.call`` (each attempt is a new interpreter, threaded
    through ``AUTODIST_TPU_BENCH_ATTEMPT``)."""
    from autodist_tpu.runtime.retry import backoff_delay

    return backoff_delay(attempt, base_s=base, cap_s=cap)


def _unavailable_exit(msg: str):
    """An UNAVAILABLE accelerator backend is an environment condition,
    not a bench crash: retry up to ``_MAX_ATTEMPTS`` total with capped
    exponential backoff, then exit 0 with a well-formed ``skipped``
    record — so a BENCH_r*.json row never records a missing backend as
    a score of 0 with a crash rc.

    jax caches a failed PJRT client process-wide, so an in-process retry
    can never succeed: each retry re-execs a fresh interpreter (attempt
    count threaded through the environment).  Callers must disarm the
    watchdog first — its monitor child would outlive the exec image.
    """
    attempt = int(os.environ.get("AUTODIST_TPU_BENCH_ATTEMPT", "1"))
    if attempt < _MAX_ATTEMPTS:
        base = float(os.environ.get("AUTODIST_TPU_BENCH_BACKOFF", "5"))
        delay = _backoff_delay(attempt, base)
        print(f"# backend unavailable (attempt {attempt}/{_MAX_ATTEMPTS}), "
              f"retrying in {delay:.0f}s: {msg}", flush=True)
        time.sleep(delay)
        env = dict(os.environ,
                   AUTODIST_TPU_BENCH_ATTEMPT=str(attempt + 1))
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__)]
                  + sys.argv[1:], env)
    print(_fail_record(
        f"accelerator backend unavailable after {_MAX_ATTEMPTS} "
        f"attempts: {msg}", skipped=True), flush=True)
    sys.exit(0)


def mlm_model_flops_per_example(cfg, seq_len: int, num_masked: int) -> float:
    """Analytic matmul FLOPs for one BERT MLM training example (fwd x3 for
    fwd+bwd).  Counts encoder matmuls (qkv 6H^2 + out-proj 2H^2 + mlp
    4*H*mlp_dim per token), attention score+value einsums (4*L*H per
    token), and the MLM head (2*H^2 transform + 2*H*V tied decode per
    masked position)."""
    H, L, V, P = cfg.hidden_size, seq_len, cfg.vocab_size, num_masked
    per_token_layer = 8.0 * H * H + 4.0 * H * cfg.mlp_dim + 4.0 * L * H
    encoder_fwd = L * cfg.num_layers * per_token_layer
    head_fwd = P * (2.0 * H * H + 2.0 * H * V)
    return 3.0 * (encoder_fwd + head_fwd)


def main():
    # One alarm for the whole bench: a healthy run finishes well inside
    # the budget; a wedged tunnel gets a diagnostic JSON line instead of
    # silence.  (jax.default_backend() alone can hang: the tunnel client
    # initializes even under JAX_PLATFORMS=cpu.)
    # `bench.py serve` measures the serving engine's decode throughput
    # instead of training MFU; `bench.py quant` compares the dp×pp×tp
    # pipeline step at fp32 vs int8 collective precision.  The
    # UNAVAILABLE fresh-process retry carries the mode through sys.argv.
    # `bench.py flash` compares the composed einsum decode step against
    # the flash-decode Pallas kernel at the same cache occupancy.
    run = (_bench_serve if "serve" in sys.argv[1:]
           else _bench_quant if "quant" in sys.argv[1:]
           else _bench_flash if "flash" in sys.argv[1:]
           else _bench_moe if "moe" in sys.argv[1:] else _bench)
    dog = _Watchdog(2400, "backend init").arm()
    try:
        run(dog)
    except RuntimeError as e:
        # A degraded tunnel surfaces as UNAVAILABLE from PJRT init
        # (observed: ~30 min blocked inside init, then this error; jax
        # caches the failure process-wide so retrying here is useless).
        # The driver still gets one well-formed diagnostic line instead
        # of a bare traceback.
        if "UNAVAILABLE" not in str(e) and "backend" not in str(e):
            raise
        dog.disarm()
        _unavailable_exit(str(e))
    finally:
        dog.disarm()   # every exit path reaps the monitor + stage file


def _bench_quant(dog):
    """`bench.py quant`: step-time ratio of the dp×pp×tp pipeline at
    fp32 vs int8 per-collective precision — the measured half of the
    quantized-collectives claim (the HLO probe proves the narrowed wire
    structurally; this puts a wall-clock number on it).  Same one-line
    provenance-stamped record shape as the other modes; UNAVAILABLE
    backends take the same fresh-process backoff via main()."""
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist, telemetry
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.resource import ResourceSpec, factor_3d
    from autodist_tpu.simulator.cost_model import CostModel

    on_accel = jax.default_backend() != "cpu"
    rs = ResourceSpec({})
    n = rs.num_devices()
    tp = 2 if n >= 4 else 1
    pp = 2 if n // tp >= 2 else 1
    dp = n // (tp * pp)
    if on_accel:
        cfg = TransformerConfig(vocab_size=32768, hidden_size=1024,
                                num_layers=2 * pp, num_heads=16,
                                mlp_dim=4096, max_len=512,
                                dtype=jnp.bfloat16, dropout_rate=0.0,
                                attention_dropout_rate=0.0)
        batch, steps = 8 * dp * 2, 20
    else:  # CPU dev smoke: same code path, toy size
        cfg = TransformerConfig(vocab_size=128, hidden_size=32,
                                num_layers=2 * pp, num_heads=2,
                                mlp_dim=64, max_len=32,
                                dtype=jnp.float32, dropout_rate=0.0,
                                attention_dropout_rate=0.0)
        batch, steps = 4 * max(dp, 1) * 2, 3
    mesh = factor_3d(n, pipe=pp, model=tp, data=dp)
    spec = {"topology": {"num_devices": n}, "mesh": mesh}
    telemetry.annotate(bench="quantized_collectives_speedup", devices=n,
                       chip=rs.chip.name)
    r = np.random.RandomState(0)
    b = {"x": r.randint(0, cfg.vocab_size, (batch, cfg.max_len))
         .astype(np.int32),
         "y": r.randint(0, cfg.vocab_size, (batch, cfg.max_len))
         .astype(np.int32)}

    def timed(precision):
        trainable = make_pipeline_lm_trainable(
            cfg, optax.adam(1e-3), jax.random.PRNGKey(0))
        # activation-shape hint so the cost model prices the policied
        # activation boundaries (and their q/dq term) for the record
        trainable.tokens_per_step = batch * cfg.max_len
        ad = AutoDist(spec, "Pipeline", num_microbatches=2,
                      virtual_stages=cfg.num_layers // pp,
                      tensor_parallel=tp,
                      vocab_parallel=tp > 1,
                      collective_precision=precision)
        strategy = ad.build_or_load_strategy(trainable)
        runner = ad.build(trainable, strategy)
        try:
            float(np.asarray(runner.step(b)["loss"]))     # compile+warm
            t0 = time.perf_counter()
            for _ in range(steps):
                metrics = runner.step(b)
            float(np.asarray(metrics["loss"]))
            dt = (time.perf_counter() - t0) / steps
        finally:
            runner.close()
        cost = CostModel(ResourceSpec(spec)).strategy_cost(trainable,
                                                           strategy)
        return dt, cost

    dog.stage = f"quant bench fp32 (tp{tp}/pp{pp}: build+compile+steps)"
    try:
        dt_fp32, _ = timed(None)
        dog.stage = f"quant bench int8 (tp{tp}/pp{pp}: build+compile+steps)"
        dt_int8, cost_q = timed("int8")
    except Exception as e:
        dog.disarm()
        if "UNAVAILABLE" in str(e) or "Connection" in str(e):
            _unavailable_exit(f"transport: {e}")
        print(json.dumps({
            "metric": "quantized_collectives_speedup", "value": 0.0,
            "unit": "ratio", "vs_baseline": 0.0,
            "error": f"quant bench failed: {e}",
            "provenance": _provenance()}))
        sys.exit(4)
    ratio = dt_fp32 / dt_int8 if dt_int8 > 0 else 0.0
    # Topology-aware search provenance: what the searched frontier
    # would elect for this same (trainable, topology) — so a hardware
    # window can compare the measured config against the search winner
    # mechanically (tools/lint_strategy.py --search is the CI analog).
    # Plan-level only (no extra compiles); failure never eats the
    # measurement.
    try:
        from autodist_tpu.simulator.search import search_strategies

        t_search = make_pipeline_lm_trainable(
            cfg, optax.adam(1e-3), jax.random.PRNGKey(0))
        t_search.tokens_per_step = batch * cfg.max_len
        sres = search_strategies(t_search, ResourceSpec(spec),
                                 global_batch=batch)
        search_rec = dict(sres.counts())
        if sres.winner is not None:
            search_rec["winner"] = sres.winner.name
            search_rec["winner_comm_time_s"] = round(
                sres.winner.cost.comm_time_s, 9)
            search_rec["winner_dcn_time_s"] = round(
                sres.winner.cost.dcn_time_s, 9)
    except Exception as e:   # provenance only — never fail the record
        search_rec = {"error": f"{type(e).__name__}: {e}"}
    record = {
        "metric": "quantized_collectives_speedup",
        "value": round(ratio, 4), "unit": "ratio",
        "vs_baseline": round(ratio, 4), "devices": n,
        "chip": rs.chip.name, "tensor_parallel": tp, "pipe": pp,
        "batch": batch, "steps": steps,
        "step_ms_fp32": round(dt_fp32 * 1e3, 3),
        "step_ms_int8": round(dt_int8 * 1e3, 3),
        "predicted_wire_bytes_saved": round(cost_q.wire_bytes_saved, 1),
        "predicted_qdq_ms": round(cost_q.quant_dq_time_s * 1e3, 4),
        "search": search_rec,
        "scored": True, "provenance": _provenance(),
    }
    dog.disarm()
    print(json.dumps(record), flush=True)
    telemetry.gauge("bench/quantized_speedup").set(ratio)
    telemetry.flush()


def _bench_flash(dog):
    """`bench.py flash`: fused-vs-composed decode step ratio — the
    measured half of the flash-decode kernel claim (the interpreter
    goldens prove numerics, ADT120 proves the kernel is in the program;
    this puts a wall-clock number on the crossover).  The record carries
    the cost model's predicted crossover beside the measured ratio so a
    hardware window can see whether the calibrated `"kernel"` section
    still matches silicon.  Same provenance-stamped one-line record
    shape and UNAVAILABLE fresh-process backoff as the other modes."""
    import jax.numpy as jnp
    import optax

    from autodist_tpu import telemetry
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.serving import ServingEngine
    from autodist_tpu.simulator.cost_model import CostModel

    on_accel = jax.default_backend() != "cpu"
    rs = ResourceSpec({})
    n = rs.num_devices()
    if on_accel:
        cfg = TransformerConfig(vocab_size=32768, hidden_size=1024,
                                num_layers=4, num_heads=16,
                                mlp_dim=4096, max_len=2048,
                                dtype=jnp.bfloat16, dropout_rate=0.0,
                                attention_dropout_rate=0.0)
        slots, windows = 8, 10
    else:  # CPU dev smoke: same code path, toy size (interpret mode)
        cfg = TransformerConfig(vocab_size=128, hidden_size=32,
                                num_layers=2, num_heads=2,
                                mlp_dim=64, max_len=64,
                                dtype=jnp.float32, dropout_rate=0.0,
                                attention_dropout_rate=0.0)
        slots, windows = 2, 2
    telemetry.annotate(bench="flash_decode_speedup", devices=n,
                       chip=rs.chip.name, kernel=["flash_decode"])
    params = make_pipeline_lm_trainable(
        cfg, optax.adam(1e-3), jax.random.PRNGKey(0)).params
    r = np.random.RandomState(0)
    prompt_len = min(16, cfg.max_len // 2)
    prompts = r.randint(1, cfg.vocab_size, (slots, prompt_len)) \
        .astype(np.int32)
    p_lens = np.full((slots,), prompt_len, np.int32)

    def timed(kernel):
        engine = ServingEngine(cfg, params, num_slots=slots,
                               max_len=cfg.max_len,
                               prefill_len=prompt_len, decode_steps=8,
                               kernel=kernel)
        active = np.ones((slots,), bool)
        engine.prefill(prompts, p_lens, active)
        engine.decode(active)                    # compile + warm
        t0 = time.perf_counter()
        for _ in range(windows):
            toks = engine.decode(active)
        float(np.asarray(toks)[0, 0])
        return (time.perf_counter() - t0) / (windows
                                             * engine.decode_steps)

    dog.stage = f"flash bench composed decode ({n} dev)"
    try:
        dt_einsum = timed(None)
        dog.stage = f"flash bench fused decode ({n} dev)"
        dt_flash = timed(("flash_decode",))
    except Exception as e:
        dog.disarm()
        if "UNAVAILABLE" in str(e) or "Connection" in str(e):
            _unavailable_exit(f"transport: {e}")
        print(json.dumps({
            "metric": "flash_decode_speedup", "value": 0.0,
            "unit": "ratio", "vs_baseline": 0.0,
            "error": f"flash bench failed: {e}",
            "provenance": _provenance()}))
        sys.exit(4)
    ratio = dt_einsum / dt_flash if dt_flash > 0 else 0.0
    cm = CostModel(rs)
    kp = cm.kernel_profile
    trainable = make_pipeline_lm_trainable(
        cfg, optax.adam(1e-3), jax.random.PRNGKey(0))
    pred_flash = cm.decode_cost(trainable,
                                {"kernel": ("flash_decode",)},
                                batch_slots=slots, max_len=cfg.max_len)
    pred_einsum = cm.decode_cost(trainable, {}, batch_slots=slots,
                                 max_len=cfg.max_len)
    record = {
        "metric": "flash_decode_speedup",
        "value": round(ratio, 4), "unit": "ratio",
        "vs_baseline": round(ratio, 4), "devices": n,
        "chip": rs.chip.name, "slots": slots,
        "max_len": cfg.max_len, "windows": windows,
        "token_ms_einsum": round(dt_einsum * 1e3, 4),
        "token_ms_flash": round(dt_flash * 1e3, 4),
        "predicted_crossover_len": kp["flash_decode_crossover_len"],
        "predicted_speedup": round(
            pred_einsum.attn_time_s
            / max(pred_flash.attn_time_s, 1e-12), 4),
        "measured_favors_flash": ratio > 1.0,
        "predicted_favors_flash":
            cfg.max_len >= kp["flash_decode_crossover_len"],
        "scored": True, "provenance": _provenance(),
    }
    dog.disarm()
    print(json.dumps(record), flush=True)
    telemetry.gauge("bench/flash_decode_speedup").set(ratio)
    telemetry.flush()


def _bench_moe(dog):
    """`bench.py moe`: fused-vs-composed dispatch/combine step ratio —
    the measured half of the a2a_ring kernel claim (the interpreter
    goldens prove the ring numerics, ADT120 proves the s8 ppermute wire
    is in the program; this puts a wall-clock number on the q/dq-fusion
    trade).  Both legs run the SAME int8 moe_a2a wire policy so the
    ratio isolates the kernel (fused in-hop q/dq vs composed
    quantize→all_to_all→dequantize), and the record carries the cost
    model's predicted a2a split beside the measurement so a hardware
    window can recalibrate `"kernel"` (a2a_ring_wire_factor /
    a2a_ring_qdq_factor) mechanically.  Same provenance-stamped
    one-line record shape and UNAVAILABLE fresh-process backoff as the
    other modes."""
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist, telemetry
    from autodist_tpu.models.moe_transformer import (MoeConfig,
                                                     make_moe_lm_trainable)
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator.cost_model import CostModel

    on_accel = jax.default_backend() != "cpu"
    rs = ResourceSpec({})
    n = rs.num_devices()
    if on_accel:
        cfg = MoeConfig(vocab_size=32768, hidden_size=1024,
                        num_layers=2, num_heads=16, expert_hidden=4096,
                        num_experts=8, max_len=512, dtype=jnp.bfloat16)
        per_dev, steps = 2, 20
    else:  # CPU dev smoke: same code path, toy size (interpret mode)
        cfg = MoeConfig(vocab_size=128, hidden_size=32, num_layers=1,
                        num_heads=2, expert_hidden=64, num_experts=4,
                        max_len=16, dtype=jnp.float32)
        per_dev, steps = 1, 3
    # The largest expert-axis degree this host supports: divides both
    # the device count and the expert count (the ring kernel needs >= 2
    # ranks to put anything on the wire).
    expert = max((d for d in range(1, n + 1)
                  if n % d == 0 and cfg.num_experts % d == 0),
                 default=1)
    if expert < 2:
        dog.disarm()
        print(json.dumps({
            "metric": "moe_a2a_ring_speedup", "value": 0.0,
            "unit": "ratio", "vs_baseline": 0.0, "skipped": True,
            "error": f"need an expert axis >= 2 ({n} device(s), "
                     f"{cfg.num_experts} experts)",
            "provenance": _provenance()}))
        return
    dp = n // expert
    spec = {"topology": {"num_devices": n},
            "mesh": ({"data": dp, "expert": expert} if dp > 1
                     else {"expert": expert})}
    # The batch dim shards over data x expert, so it must divide the
    # full device count.
    batch = per_dev * n
    telemetry.annotate(bench="moe_a2a_ring_speedup", devices=n,
                       chip=rs.chip.name, kernel=["a2a_ring"])
    r = np.random.RandomState(0)
    b = {"x": r.randint(0, cfg.vocab_size, (batch, cfg.max_len))
         .astype(np.int32),
         "y": r.randint(0, cfg.vocab_size, (batch, cfg.max_len))
         .astype(np.int32)}

    def timed(kernel):
        trainable = make_moe_lm_trainable(
            cfg, optax.adam(1e-3), jax.random.PRNGKey(0),
            batch_size=batch, seq_len=cfg.max_len)
        ad = AutoDist(spec, "ExpertParallel",
                      num_experts=cfg.num_experts,
                      capacity_factor=cfg.capacity_factor,
                      collective_precision={"moe_a2a": "int8"},
                      kernel=kernel)
        strategy = ad.build_or_load_strategy(trainable)
        runner = ad.build(trainable, strategy)
        try:
            float(np.asarray(runner.step(b)["loss"]))     # compile+warm
            t0 = time.perf_counter()
            for _ in range(steps):
                metrics = runner.step(b)
            float(np.asarray(metrics["loss"]))
            dt = (time.perf_counter() - t0) / steps
        finally:
            runner.close()
        cost = CostModel(ResourceSpec(spec)).strategy_cost(trainable,
                                                           strategy)
        return dt, cost

    dog.stage = f"moe bench composed a2a (ex{expert}/dp{dp}: " \
                "build+compile+steps)"
    try:
        dt_composed, cost_c = timed(None)
        dog.stage = f"moe bench fused a2a_ring (ex{expert}/dp{dp}: " \
                    "build+compile+steps)"
        dt_ring, cost_r = timed(("a2a_ring",))
    except Exception as e:
        dog.disarm()
        if "UNAVAILABLE" in str(e) or "Connection" in str(e):
            _unavailable_exit(f"transport: {e}")
        print(json.dumps({
            "metric": "moe_a2a_ring_speedup", "value": 0.0,
            "unit": "ratio", "vs_baseline": 0.0,
            "error": f"moe bench failed: {e}",
            "provenance": _provenance()}))
        sys.exit(4)
    ratio = dt_composed / dt_ring if dt_ring > 0 else 0.0
    kp = CostModel(rs).kernel_profile
    record = {
        "metric": "moe_a2a_ring_speedup",
        "value": round(ratio, 4), "unit": "ratio",
        "vs_baseline": round(ratio, 4), "devices": n,
        "chip": rs.chip.name, "expert_axis": expert, "dp": dp,
        "num_experts": cfg.num_experts,
        "capacity_factor": cfg.capacity_factor,
        "batch": batch, "steps": steps,
        "step_ms_composed": round(dt_composed * 1e3, 3),
        "step_ms_ring": round(dt_ring * 1e3, 3),
        "predicted_a2a_ms_composed": round(cost_c.a2a_time_s * 1e3, 4),
        "predicted_a2a_ms_ring": round(cost_r.a2a_time_s * 1e3, 4),
        "predicted_a2a_bytes_composed": round(cost_c.a2a_bytes, 1),
        "predicted_a2a_bytes_ring": round(cost_r.a2a_bytes, 1),
        "a2a_ring_wire_factor": kp["a2a_ring_wire_factor"],
        "a2a_ring_qdq_factor": kp["a2a_ring_qdq_factor"],
        "measured_favors_ring": ratio > 1.0,
        "predicted_favors_ring": cost_r.a2a_time_s < cost_c.a2a_time_s,
        "scored": True, "provenance": _provenance(),
    }
    dog.disarm()
    print(json.dumps(record), flush=True)
    telemetry.gauge("bench/moe_a2a_ring_speedup").set(ratio)
    telemetry.flush()


def _kv_layout_arg() -> str:
    """`bench.py serve --kv-layout {dense,paged}` (sys.argv scan like
    the mode words — the UNAVAILABLE fresh-process retry re-execs the
    argv verbatim, so the flag survives the backoff)."""
    from autodist_tpu.strategy.ir import normalize_kv_layout

    argv = sys.argv[1:]
    for i, a in enumerate(argv):
        if a == "--kv-layout" and i + 1 < len(argv):
            return normalize_kv_layout(argv[i + 1])
        if a.startswith("--kv-layout="):
            return normalize_kv_layout(a.split("=", 1)[1])
    return "dense"


def _replicas_arg() -> int:
    """`bench.py serve --replicas N` (same argv-scan contract)."""
    argv = sys.argv[1:]
    for i, a in enumerate(argv):
        if a == "--replicas" and i + 1 < len(argv):
            return max(int(argv[i + 1]), 1)
        if a.startswith("--replicas="):
            return max(int(a.split("=", 1)[1]), 1)
    return 1


def _prompt_mix_arg() -> str:
    """`bench.py serve --prompt-mix {random,shared-prefix}` (same
    argv-scan contract)."""
    argv = sys.argv[1:]
    mix = "random"
    for i, a in enumerate(argv):
        if a == "--prompt-mix" and i + 1 < len(argv):
            mix = argv[i + 1]
        elif a.startswith("--prompt-mix="):
            mix = a.split("=", 1)[1]
    if mix not in ("random", "shared-prefix"):
        raise SystemExit(f"unknown --prompt-mix {mix!r}; expected "
                         "'random' or 'shared-prefix'")
    return mix


def _speculative_arg() -> int:
    """`bench.py serve --speculative [K]` (same argv-scan contract);
    0 = off, bare flag defaults to K=4."""
    argv = sys.argv[1:]
    for i, a in enumerate(argv):
        if a == "--speculative":
            if i + 1 < len(argv) and argv[i + 1].isdigit():
                return max(int(argv[i + 1]), 0)
            return 4
        if a.startswith("--speculative="):
            return max(int(a.split("=", 1)[1]), 0)
    return 0


def _bench_serve_shared_prefix(dog):
    """`bench.py serve --prompt-mix shared-prefix`: the prefix-caching
    rung's capacity story, measured.  Every request in the mix opens
    with the SAME system-prompt-style prefix; the mix runs twice at
    EQUAL pool bytes — paged-alone, then paged + ``prefix_caching`` —
    and the record carries both peak concurrently-admitted counts plus
    the summed ``prefix_hit_blocks``.  The acceptance bar: the caching
    run admits strictly more requests per pool byte."""
    import jax.numpy as jnp
    import optax

    from autodist_tpu import serving, telemetry
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.resource import ResourceSpec

    on_accel = jax.default_backend() != "cpu"
    rs = ResourceSpec({})
    n = rs.num_devices()
    if on_accel:
        cfg = TransformerConfig(vocab_size=32768, hidden_size=1024,
                                num_layers=8, num_heads=16, mlp_dim=4096,
                                max_len=1024, dtype=jnp.bfloat16,
                                dropout_rate=0.0,
                                attention_dropout_rate=0.0)
        dense_slots, K, prefill_len, max_new, requests = 8, 16, 512, 64, 24
        bl, shared_len = 16, 256
    else:  # CPU dev smoke: same code path, toy size
        cfg = TransformerConfig(vocab_size=128, hidden_size=32,
                                num_layers=2, num_heads=2, mlp_dim=64,
                                max_len=64, dtype=jnp.float32,
                                dropout_rate=0.0,
                                attention_dropout_rate=0.0)
        dense_slots, K, prefill_len, max_new, requests = 2, 4, 40, 8, 8
        bl, shared_len = 8, 16
    pool_blocks = dense_slots * (-(-cfg.max_len // bl))
    slots = dense_slots * 4
    lane = 2.0 * cfg.num_layers * cfg.hidden_size \
        * jnp.dtype(cfg.dtype).itemsize
    pool_bytes = int(pool_blocks * bl * lane)
    telemetry.annotate(bench="serve_prefix_capacity_requests", devices=n,
                       chip=rs.chip.name, prompt_mix="shared-prefix")
    dog.stage = (f"serve shared-prefix bench (slots{slots}/"
                 f"pool{pool_blocks}x{bl}: paged-alone vs prefix-cached)")

    def run_mix(prefix_caching: bool):
        trainable = make_pipeline_lm_trainable(
            cfg, optax.adam(1e-3), jax.random.PRNGKey(0))
        engine = serving.ServingEngine(
            cfg, trainable.params, num_slots=slots, max_len=cfg.max_len,
            prefill_len=prefill_len, decode_steps=K, kv_layout="paged",
            kv_block_len=bl, kv_num_blocks=pool_blocks,
            prefix_caching=prefix_caching)
        batcher = serving.ContinuousBatcher(engine)
        r = np.random.RandomState(0)
        shared = r.randint(0, cfg.vocab_size, (shared_len,)).tolist()
        t0 = time.perf_counter()
        for _ in range(requests):
            suffix_len = int(r.randint(1, prefill_len - shared_len + 1))
            prompt = shared + r.randint(0, cfg.vocab_size,
                                        (suffix_len,)).tolist()
            # staggered decode budgets: completions interleave, so
            # later admissions overlap resident holders of the shared
            # prefix (a lockstep mix would release every reference
            # between waves and no hit could ever occur)
            batcher.submit(prompt,
                           max_new_tokens=int(r.randint(2, max_new + 1)))
        capacity = 0
        before = set(batcher.completions)
        while batcher._queue or batcher.active_slots:
            batcher.step()
            capacity = max(capacity, batcher.active_slots)
        done = {rid: c for rid, c in batcher.completions.items()
                if rid not in before}
        wall = time.perf_counter() - t0
        tokens = sum(len(c.tokens) for c in done.values())
        hits = sum(c.prefix_hit_blocks for c in done.values())
        return (capacity, hits,
                tokens / wall if wall > 0 else 0.0)

    try:
        cap_alone, _, rate_alone = run_mix(prefix_caching=False)
        cap_cached, hit_blocks, rate_cached = run_mix(prefix_caching=True)
    except Exception as e:
        dog.disarm()
        if "UNAVAILABLE" in str(e) or "Connection" in str(e):
            _unavailable_exit(f"transport: {e}")
        print(json.dumps({
            "metric": "serve_prefix_capacity_requests", "value": 0.0,
            "unit": "requests", "vs_baseline": 0.0,
            "prompt_mix": "shared-prefix",
            "error": f"shared-prefix bench failed: {e}",
            "provenance": _provenance()}))
        sys.exit(4)
    record = {
        "metric": "serve_prefix_capacity_requests",
        "value": float(cap_cached), "unit": "requests",
        "vs_baseline": float(cap_alone),
        "devices": n, "chip": rs.chip.name, "prompt_mix": "shared-prefix",
        "kv_layout": "paged", "prefix_caching": True,
        "slots": slots, "pool_blocks": pool_blocks,
        "kv_block_len": bl, "pool_bytes": pool_bytes,
        "shared_prefix_len": shared_len, "requests": requests,
        "prefix_hit_blocks": hit_blocks,
        "capacity_paged_alone": cap_alone,
        "capacity_prefix_cached": cap_cached,
        "requests_per_pool_gb": round(cap_cached / (pool_bytes / 1e9), 2),
        "requests_per_pool_gb_paged_alone":
            round(cap_alone / (pool_bytes / 1e9), 2),
        "ladder": {"paged": round(rate_alone, 2),
                   "paged+prefix_caching": round(rate_cached, 2)},
        "scored": True, "provenance": _provenance(),
    }
    dog.disarm()
    print(json.dumps(record), flush=True)
    telemetry.gauge("serve/bench_prefix_capacity").set(float(cap_cached))
    telemetry.flush()


def _bench_serve_speculative(dog, spec_k: int):
    """`bench.py serve --speculative [K]`: the speculative rung,
    measured — the same mix through a vanilla engine and through a
    target + 1-layer-draft speculative engine, recording the ladder's
    tokens/sec pair and the MEASURED acceptance rate (the
    ``spec_acceptance`` number ``rank_serving`` prices candidates
    with; the ROADMAP recipe feeds it back via
    ``calibration.json``)."""
    import jax.numpy as jnp
    import optax

    from autodist_tpu import serving, telemetry
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.resource import ResourceSpec

    on_accel = jax.default_backend() != "cpu"
    rs = ResourceSpec({})
    n = rs.num_devices()
    if on_accel:
        cfg = TransformerConfig(vocab_size=32768, hidden_size=1024,
                                num_layers=8, num_heads=16, mlp_dim=4096,
                                max_len=1024, dtype=jnp.bfloat16,
                                dropout_rate=0.0,
                                attention_dropout_rate=0.0)
        slots, K, prefill_len, max_new, requests = 8, 16, 64, 128, 16
    else:  # CPU dev smoke: same code path, toy size
        cfg = TransformerConfig(vocab_size=128, hidden_size=32,
                                num_layers=2, num_heads=2, mlp_dim=64,
                                max_len=64, dtype=jnp.float32,
                                dropout_rate=0.0,
                                attention_dropout_rate=0.0)
        slots, K, prefill_len, max_new, requests = 2, 4, 8, 8, 4
    import dataclasses as _dc

    draft_cfg = _dc.replace(cfg, num_layers=1)
    telemetry.annotate(bench="serve_spec_tokens_per_sec", devices=n,
                       chip=rs.chip.name, speculative=spec_k)
    dog.stage = (f"serve speculative bench (k={spec_k}/slots{slots}: "
                 "vanilla vs draft-verify)")

    def run_mix(engine_kwargs):
        trainable = make_pipeline_lm_trainable(
            cfg, optax.adam(1e-3), jax.random.PRNGKey(0))
        if "speculative" in engine_kwargs:
            draft = make_pipeline_lm_trainable(
                draft_cfg, optax.adam(1e-3), jax.random.PRNGKey(1))
            engine_kwargs = dict(engine_kwargs, draft_cfg=draft_cfg,
                                 draft_params=draft.params)
        engine = serving.ServingEngine(
            cfg, trainable.params, num_slots=slots, max_len=cfg.max_len,
            prefill_len=prefill_len, decode_steps=K, kv_layout="paged",
            kv_block_len=16, **engine_kwargs)
        batcher = serving.ContinuousBatcher(engine)
        r = np.random.RandomState(0)
        batcher.submit(
            r.randint(0, cfg.vocab_size, (4,)).tolist(), max_new_tokens=K)
        batcher.run()
        t0 = time.perf_counter()
        for _ in range(requests):
            plen = int(r.randint(1, prefill_len + 1))
            batcher.submit(r.randint(0, cfg.vocab_size, (plen,)).tolist(),
                           max_new_tokens=max_new)
        before = set(batcher.completions)
        while batcher._queue or batcher.active_slots:
            batcher.step()
        done = {rid: c for rid, c in batcher.completions.items()
                if rid not in before}
        wall = time.perf_counter() - t0
        tokens = sum(len(c.tokens) for c in done.values())
        proposed = sum(c.spec_proposed for c in done.values())
        accepted = sum(c.spec_accepted for c in done.values())
        return tokens / wall if wall > 0 else 0.0, proposed, accepted

    try:
        rate_vanilla, _, _ = run_mix({})
        rate_spec, proposed, accepted = run_mix({"speculative": spec_k})
    except Exception as e:
        dog.disarm()
        if "UNAVAILABLE" in str(e) or "Connection" in str(e):
            _unavailable_exit(f"transport: {e}")
        print(json.dumps({
            "metric": "serve_spec_tokens_per_sec", "value": 0.0,
            "unit": "tokens_per_sec", "vs_baseline": 0.0,
            "speculative": spec_k,
            "error": f"speculative bench failed: {e}",
            "provenance": _provenance()}))
        sys.exit(4)
    acceptance = accepted / proposed if proposed else 0.0
    record = {
        "metric": "serve_spec_tokens_per_sec",
        "value": round(rate_spec, 2), "unit": "tokens_per_sec",
        "vs_baseline": round(rate_vanilla, 2),
        "devices": n, "chip": rs.chip.name, "kv_layout": "paged",
        "speculative": spec_k, "requests": requests,
        "spec_proposed": proposed, "spec_accepted": accepted,
        "spec_acceptance": round(acceptance, 4),
        "ladder": {"paged": round(rate_vanilla, 2),
                   f"paged+speculative_k{spec_k}": round(rate_spec, 2)},
        "scored": True, "provenance": _provenance(),
    }
    dog.disarm()
    print(json.dumps(record), flush=True)
    telemetry.gauge("serve/bench_spec_acceptance").set(acceptance)
    telemetry.flush()


def _bench_serve_fleet(dog, replicas: int):
    """`bench.py serve --replicas N`: the fleet record — aggregate
    tokens/sec through the router over N replicas, and the robustness
    number the fleet exists for: TTFT p99 over the same mix WITH and
    WITHOUT one replica killed mid-run (the failover path's latency
    cost, measured not promised).  Same provenance-stamped one-line
    JSON shape and UNAVAILABLE fresh-process backoff as every bench
    mode."""
    import jax.numpy as jnp
    import optax

    from autodist_tpu import serving, telemetry
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.resource import ResourceSpec

    kv_layout = _kv_layout_arg()
    on_accel = jax.default_backend() != "cpu"
    rs = ResourceSpec({})
    n = rs.num_devices()
    if on_accel:
        cfg = TransformerConfig(vocab_size=32768, hidden_size=1024,
                                num_layers=8, num_heads=16, mlp_dim=4096,
                                max_len=1024, dtype=jnp.bfloat16,
                                dropout_rate=0.0,
                                attention_dropout_rate=0.0)
        slots, K, prefill_len, max_new, requests = 8, 16, 512, 128, 24
    else:  # CPU dev smoke: same code path, toy size
        cfg = TransformerConfig(vocab_size=128, hidden_size=32,
                                num_layers=2, num_heads=2, mlp_dim=64,
                                max_len=64, dtype=jnp.float32,
                                dropout_rate=0.0,
                                attention_dropout_rate=0.0)
        slots, K, prefill_len, max_new, requests = 2, 4, 24, 8, 8
    telemetry.annotate(bench="serve_fleet_tokens_per_sec", devices=n,
                       chip=rs.chip.name, kv_layout=kv_layout,
                       replicas=replicas)
    dog.stage = (f"serve fleet bench (replicas={replicas}/"
                 f"{kv_layout}: build+compile+route)")
    engine_kwargs = {}
    if kv_layout == "paged":
        engine_kwargs = {"kv_layout": "paged", "kv_block_len": 16}

    def run_mix(kill: bool):
        trainable = make_pipeline_lm_trainable(
            cfg, optax.adam(1e-3), jax.random.PRNGKey(0))

        def factory():
            return serving.ServingEngine(
                cfg, trainable.params, num_slots=slots,
                max_len=cfg.max_len, prefill_len=prefill_len,
                decode_steps=K, **engine_kwargs)

        fleet = serving.ServingFleet(factory, replicas=replicas)
        router = serving.Router(fleet)
        r = np.random.RandomState(0)
        t0 = time.perf_counter()
        for _ in range(requests):
            plen = int(r.randint(1, prefill_len - max_new + 1))
            router.submit(
                r.randint(0, cfg.vocab_size, (plen,)).tolist(),
                max_new_tokens=max_new)
        rounds = 0
        while router._open:
            router.step()
            rounds += 1
            if kill and rounds == 2 and fleet.has_replica("replica-0"):
                fleet.inject("replica-0", "crash")
        wall = time.perf_counter() - t0
        done = router.completions
        tokens = sum(len(c.tokens) for c in done.values())
        ttfts = sorted(c.ttft_s for c in done.values())
        p99 = float(np.percentile(np.asarray(ttfts), 99)) * 1e3
        failovers = sum(c.failovers for c in done.values())
        traced = sum(1 for c in done.values() if c.trace_id)
        return (tokens / wall if wall > 0 else 0.0, p99, failovers,
                len(done), traced)

    try:
        rate, ttft_p99, _, _, _ = run_mix(kill=False)
        (rate_killed, ttft_p99_killed, failovers, sampled,
         traced) = run_mix(kill=True)
    except Exception as e:
        dog.disarm()
        if "UNAVAILABLE" in str(e) or "Connection" in str(e):
            _unavailable_exit(f"transport: {e}")
        print(json.dumps({
            "metric": "serve_fleet_tokens_per_sec", "value": 0.0,
            "unit": "tokens_per_sec", "vs_baseline": 0.0,
            "replicas": replicas, "kv_layout": kv_layout,
            "error": f"serve fleet bench failed: {e}",
            "provenance": _provenance()}))
        sys.exit(4)
    record = {
        "metric": "serve_fleet_tokens_per_sec", "value": round(rate, 2),
        "unit": "tokens_per_sec", "vs_baseline": round(rate, 2),
        "devices": n, "chip": rs.chip.name, "replicas": replicas,
        "kv_layout": kv_layout, "requests": requests,
        "ttft_ms_p99": round(ttft_p99, 2),
        "ttft_ms_p99_replica_killed": round(ttft_p99_killed, 2),
        "tokens_per_sec_replica_killed": round(rate_killed, 2),
        "failovers_on_kill": failovers,
        # Trace provenance: every routed request is minted a trace id
        # at submit; resolved counts completions that kept theirs
        # across dispatch (and the kill run's failover re-dispatch).
        "trace_sample": {"sampled": sampled, "resolved": traced},
        "scored": True, "provenance": _provenance(),
    }
    dog.disarm()
    print(json.dumps(record), flush=True)
    telemetry.gauge("fleet/bench_tokens_per_sec").set(rate)
    telemetry.flush()


def _bench_serve(dog):
    """`bench.py serve`: decode tokens/sec + TTFT through the serving
    engine, emitted as the same provenance-stamped one-line JSON record
    shape as the training bench (hw_session.sh greps the same keys;
    UNAVAILABLE backends take the same fresh-process backoff via
    main()).

    ``--kv-layout paged`` serves from the block-paged pool at the SAME
    pool bytes as the dense cache (``num_slots_dense`` full lanes) with
    4x the admission slots, so the recorded
    ``serve_capacity_requests`` — the peak concurrently-admitted
    requests over a short-request mix — measures the paged capacity
    multiplier directly against the dense run's slot ceiling.

    ``--replicas N`` (N > 1) switches to the fleet bench
    (:func:`_bench_serve_fleet`): the same mix through a
    ``ServingFleet`` + ``Router``, recorded with and without one
    injected replica kill mid-run.

    ``--prompt-mix shared-prefix`` switches to the prefix-caching rung
    (:func:`_bench_serve_shared_prefix`); ``--speculative [K]`` to the
    speculative rung (:func:`_bench_serve_speculative`)."""
    replicas = _replicas_arg()
    if replicas > 1:
        return _bench_serve_fleet(dog, replicas)
    if _prompt_mix_arg() == "shared-prefix":
        return _bench_serve_shared_prefix(dog)
    spec_k = _speculative_arg()
    if spec_k:
        return _bench_serve_speculative(dog, spec_k)
    import jax.numpy as jnp
    import optax

    from autodist_tpu import serving, telemetry
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.resource import ResourceSpec

    kv_layout = _kv_layout_arg()
    on_accel = jax.default_backend() != "cpu"
    rs = ResourceSpec({})
    n = rs.num_devices()
    if on_accel:
        cfg = TransformerConfig(vocab_size=32768, hidden_size=1024,
                                num_layers=8, num_heads=16, mlp_dim=4096,
                                max_len=1024, dtype=jnp.bfloat16,
                                dropout_rate=0.0,
                                attention_dropout_rate=0.0)
        slots, K, prefill_len, max_new, requests = 8, 16, 64, 128, 16
        tp = 2 if n >= 2 else 1
    else:  # CPU dev smoke: same code path, toy size
        cfg = TransformerConfig(vocab_size=128, hidden_size=32,
                                num_layers=2, num_heads=2, mlp_dim=64,
                                max_len=64, dtype=jnp.float32,
                                dropout_rate=0.0,
                                attention_dropout_rate=0.0)
        slots, K, prefill_len, max_new, requests = 2, 4, 8, 8, 4
        tp = 1
    telemetry.annotate(bench="serve_decode_tokens_per_sec", devices=n,
                       chip=rs.chip.name, kv_layout=kv_layout)

    # Paged: same pool bytes (`slots` full max_len lanes), 4x the
    # admission slots — short requests reserve only their own blocks,
    # so the peak concurrency the pool carries is the capacity story.
    engine_kwargs = {}
    if kv_layout == "paged":
        engine_kwargs = {"kv_layout": "paged",
                         "kv_num_blocks": None,   # resolved below
                         "kv_block_len": 16}
        bl = engine_kwargs["kv_block_len"]
        engine_kwargs["kv_num_blocks"] = slots * (-(-cfg.max_len // bl))
        slots = slots * 4

    dog.stage = (f"serve bench (tp{tp}/slots{slots}/{kv_layout}: "
                 "build+compile+decode)")
    try:
        trainable = make_pipeline_lm_trainable(
            cfg, optax.adam(1e-3), jax.random.PRNGKey(0))
        engine = serving.ServingEngine(
            cfg, trainable.params, tensor_parallel=tp,
            vocab_parallel=tp > 1, num_slots=slots, max_len=cfg.max_len,
            prefill_len=prefill_len, decode_steps=K, **engine_kwargs)
        batcher = serving.ContinuousBatcher(engine)
        r = np.random.RandomState(0)
        # warm the two compiled programs before the timed run (run()
        # returns only the completions of each call, so the warm-up
        # request never leaks into the timed tally)
        batcher.submit(
            r.randint(0, cfg.vocab_size, (4,)).tolist(), max_new_tokens=K)
        batcher.run()
        t0 = time.perf_counter()
        # Short-request mix: every request's prompt + budget spans well
        # under max_len, the shape where dense reservation wastes lane
        # bytes and paged admission (free blocks, not slots) wins.
        for _ in range(requests):
            plen = int(r.randint(1, prefill_len + 1))
            batcher.submit(r.randint(0, cfg.vocab_size, (plen,)).tolist(),
                           max_new_tokens=max_new,
                           trace_id=telemetry.mint_trace_id())
        # Step the scheduler by hand so the peak concurrently-admitted
        # count is observable between rounds (run() loops internally).
        capacity = 0
        before = set(batcher.completions)
        while batcher._queue or batcher.active_slots:
            batcher.step()
            capacity = max(capacity, batcher.active_slots)
        done = {rid: c for rid, c in batcher.completions.items()
                if rid not in before}
        wall = time.perf_counter() - t0
    except Exception as e:
        dog.disarm()
        if "UNAVAILABLE" in str(e) or "Connection" in str(e):
            _unavailable_exit(f"transport: {e}")
        print(json.dumps({
            "metric": "serve_decode_tokens_per_sec", "value": 0.0,
            "unit": "tokens_per_sec", "vs_baseline": 0.0,
            "kv_layout": kv_layout,
            "error": f"serve bench failed: {e}",
            "provenance": _provenance()}))
        sys.exit(4)
    tokens = sum(len(c.tokens) for c in done.values())
    ttfts = sorted(c.ttft_s for c in done.values())
    itls = [ms for c in done.values() for ms in c.inter_token_ms]
    rate = tokens / wall if wall > 0 else 0.0
    record = {
        "metric": "serve_decode_tokens_per_sec", "value": round(rate, 2),
        "unit": "tokens_per_sec", "vs_baseline": round(rate, 2),
        "devices": n, "chip": rs.chip.name, "tensor_parallel": tp,
        "vocab_parallel": tp > 1, "slots": slots, "decode_steps": K,
        "kv_layout": kv_layout,
        "serve_capacity_requests": capacity,
        "requests": len(done), "tokens": tokens,
        "ttft_ms_p50": round(ttfts[len(ttfts) // 2] * 1e3, 2),
        "inter_token_ms_p50": round(float(np.percentile(itls, 50)), 3)
        if itls else None,
        "inter_token_ms_p99": round(float(np.percentile(itls, 99)), 3)
        if itls else None,
        # Trace provenance: each timed submit carried a minted trace
        # id; resolved counts completions that kept theirs end to end.
        "trace_sample": {"sampled": len(done),
                         "resolved": sum(1 for c in done.values()
                                         if c.trace_id)},
        "scored": True, "provenance": _provenance(),
    }
    dog.disarm()
    print(json.dumps(record), flush=True)
    telemetry.gauge("serve/bench_tokens_per_sec").set(rate)
    telemetry.flush()


def _bench(dog):
    from autodist_tpu import AllReduce, AutoDist
    from autodist_tpu.models import bert
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.utils import profiling

    on_accel = jax.default_backend() != "cpu"
    # Measured on v5e (seq 512): plain einsum attention beats the Pallas
    # flash kernel (whose win starts at longer sequences), and synthetic
    # MLM batches are unpadded, so the padding mask — a full [B, H, L, L]
    # elementwise pass over the score tensor — is dropped entirely.
    if on_accel:
        cfg = bert.bert_base(dropout_rate=0.0, attention_dropout_rate=0.0)
        batch_per_chip, seq_len, num_masked, steps = 16, 512, 76, 30
    else:  # CPU dev smoke: same code path, toy size
        from autodist_tpu.models.transformer import TransformerConfig
        cfg = TransformerConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                                num_heads=2, mlp_dim=128, max_len=64,
                                dropout_rate=0.0, attention_dropout_rate=0.0)
        batch_per_chip, seq_len, num_masked, steps = 4, 64, 8, 3

    rs = ResourceSpec({})
    n = rs.num_devices()

    rng = jax.random.PRNGKey(0)
    import dataclasses
    import jax.numpy as jnp

    def fence(x):
        """Force a host round-trip: on proxied/async backends
        ``block_until_ready`` may return before execution, so honest
        timing requires fetching a value that depends on every prior
        step."""
        return float(np.asarray(x))

    def make_batches(b, k):
        """k DISTINCT synthetic batches stacked [k, B, ...] for one
        ``run_steps`` dispatch (steps-per-loop: the whole timed window is
        one RPC to the device, so tunnel/dispatch latency is paid once,
        not per step)."""
        from autodist_tpu import stack_steps

        def one(i):
            data = bert.synthetic_mlm_batch(i, b * n, seq_len, num_masked,
                                            cfg.vocab_size)
            data.pop("input_mask", None)  # unpadded: no mask pass on scores
            return data
        return stack_steps([one(i) for i in range(k)])

    def build_runner(attention_fn):
        # init batch is shape-only (params are batch-size independent);
        # keep it tiny so startup doesn't scale with device count
        trainable = bert.make_mlm_trainable(
            dataclasses.replace(cfg, attention_fn=attention_fn),
            optax.adamw(1e-4, weight_decay=0.01, mu_dtype=jnp.bfloat16),
            rng, batch_size=2, seq_len=seq_len, num_masked=num_masked,
            with_input_mask=False)
        # BERT chunk=256 (reference bert.py:62)
        return AutoDist(rs, AllReduce(chunk_size=256)).build(trainable)

    def timed(runner, stacked):
        """One warm dispatch (compile + k steps), then one timed
        dispatch of the same k-step program (k = the stack's leading
        dim).  The window is placed on device once — the timed dispatch
        re-transfers nothing."""
        stacked = runner.place_steps(stacked)
        fence(runner.run_steps(stacked)["loss"][-1])   # compile + warm
        t0 = time.perf_counter()
        metrics = runner.run_steps(stacked)
        fence(metrics["loss"][-1])
        return time.perf_counter() - t0

    # Score-first discipline (learned on round 5's degraded window:
    # remote compiles intermittently fail with INTERNAL/UNAVAILABLE and
    # can take >10 min each, so a probe-every-config-then-score plan
    # burned the whole watchdog budget before the scored run started and
    # the round's number was a 5-step probe flagged "partial").  Run the
    # FULL scored measurement at the known-good base config FIRST, then
    # spend whatever budget remains on the other configs — larger
    # batches fill the MXU until HBM runs out (an OOM just loses its
    # attempt); the flash kernel wins at longer sequences.  With
    # steps-per-loop every attempt IS a full scored window (the timed
    # steps cost seconds; only compiles cost minutes), so there is no
    # separate probe grade and no re-score stage.
    from autodist_tpu.ops import make_attention_fn
    from autodist_tpu.ops.flash_attention import flash_wins

    def time_left():
        # Measured against the watchdog's OWN clock: it was armed before
        # backend init, which can itself block for many minutes on a
        # degraded tunnel — a second clock started here would green-light
        # probes the watchdog is guaranteed to kill mid-run.
        return dog.seconds - (time.monotonic() - dog.armed_at)

    flops_per_example = mlm_model_flops_per_example(cfg, seq_len, num_masked)
    peak = rs.chip.peak_bf16_tflops * 1e12 * n

    provenance = _provenance()
    from autodist_tpu import telemetry
    telemetry.annotate(bench="bert_base_mlm_mfu", devices=n,
                       chip=rs.chip.name)
    # Fresh-process retries thread the attempt number through the env
    # (_unavailable_exit): surface it so a flushed run records how many
    # backend bring-ups this number cost.
    telemetry.gauge("bench/attempt").set(
        int(os.environ.get("AUTODIST_TPU_BENCH_ATTEMPT", "1")))

    def make_record(name, b, rate, dt_step=None):
        m = profiling.mfu(rate, flops_per_example, peak)
        rec = {"metric": "bert_base_mlm_mfu", "value": round(m, 4),
               "unit": "mfu", "vs_baseline": round(m / 0.45, 4),
               "examples_per_sec": round(rate, 2), "devices": n,
               "chip": rs.chip.name, "attention": name,
               "batch_per_chip": b, "provenance": provenance}
        if dt_step is not None:
            rec["step_ms"] = round(dt_step * 1e3, 2)
            rec["scored"] = True    # a completed scored window, not a probe
        return rec

    def save_snapshot(rec):
        # Best-so-far snapshot for the watchdog: a timeout later in the
        # run reports this measured record instead of a bare diagnostic
        # (un-flagged if already scored).  Written atomically — the
        # watchdog may read at any instant.
        tmp = dog.partial_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, dog.partial_path)

    attn_impls = {"einsum": None}
    if on_accel:
        attn_impls["flash"] = make_attention_fn(causal=False)

    # ---- Stage 1: scored run at the base config -----------------------
    dog.stage = f"scored run (einsum/b{batch_per_chip}: build+compile+steps)"
    runners = {}   # attention name -> runner (shared across batch sizes)
    batches = {batch_per_chip: make_batches(batch_per_chip, steps)}
    try:
        runners["einsum"] = build_runner(None)
        dt = timed(runners["einsum"], batches[batch_per_chip])
    except Exception as e:
        # Nothing has been measured yet, so every failure here must
        # still end in the one well-formed fail-record shape the driver
        # greps (see _fail_record) — never a bare traceback.  Transport
        # failures (observed: device enumeration succeeds while the
        # tunnel's remote-compile endpoint refuses connections, each
        # attempt burning ~20 min of retry backoff) exit immediately:
        # every config shares the same PJRT client, so nothing
        # downstream can fare better.
        dog.disarm()
        if "UNAVAILABLE" in str(e) or "Connection" in str(e):
            _unavailable_exit(f"transport: {e}")
        print(_fail_record(f"base scored run failed: {e}"))
        sys.exit(4)
    base_rate = batch_per_chip * n * steps / dt
    best = make_record("einsum", batch_per_chip, base_rate,
                       dt_step=dt / steps)
    save_snapshot(best)

    # ---- Stage 2: scored attempts at the other configs ----------------
    candidates = []
    if on_accel:
        # A committed flash_tuning.json settles whether this sequence
        # length is worth a flash attempt without burning one:
        # measured-lost drops the candidate, measured-won promotes it.
        candidates = [("einsum", 2 * batch_per_chip),
                      ("einsum", 4 * batch_per_chip)]
        fw = flash_wins(seq_len, causal=False)
        if fw is True:
            candidates += [("flash", batch_per_chip),
                           ("flash", 2 * batch_per_chip)]
        elif fw is None:
            candidates.append(("flash", 2 * batch_per_chip))
        else:
            print("# flash_tuning.json: einsum wins at this length; "
                  "skipping flash attempt", flush=True)
    # A cold compile on a degraded tunnel has been observed to take
    # >10 min; an attempt only starts with room for that compile plus
    # its two k-step dispatches.
    PROBE_FLOOR = 900.0
    retried = False
    best_rate = base_rate
    for name, b in candidates:
        if time_left() < PROBE_FLOOR:
            print(f"# skipping attempt {name}/b{b}: {int(time_left())}s "
                  "left in budget", flush=True)
            continue
        dog.stage = f"scored run ({name}/b{b}: build+compile+steps)"
        if b not in batches:
            batches[b] = make_batches(b, steps)
        for attempt in (0, 1):
            try:
                if name not in runners:
                    runners[name] = build_runner(attn_impls[name])
                dt = timed(runners[name], batches[b])
                rate = b * n * steps / dt
                if rate > best_rate:
                    best_rate = rate
                    best = make_record(name, b, rate, dt_step=dt / steps)
                    save_snapshot(best)
                break
            except Exception as e:  # pragma: no cover - must not kill bench
                print(f"# bench attempt {name}/b{b} failed: {e}", flush=True)
                # A failure mid-dispatch may have consumed the runner's
                # donated state buffers ("Array has been deleted" on any
                # later use): drop the runner so a retry — or a later
                # attempt sharing the name — rebuilds from scratch.
                bad = runners.pop(name, None)
                if bad is not None:
                    bad.close()
                # One retry for the whole stage: compile-transport
                # failures (INTERNAL/UNAVAILABLE) are often transient on
                # a flaky tunnel, but every attempt can burn minutes —
                # a failing flash build gets dropped, not drained.
                if (retried or attempt or time_left() < PROBE_FLOOR
                        or not ("INTERNAL" in str(e)
                                or "UNAVAILABLE" in str(e))):
                    break
                retried = True
                telemetry.counter("bench/retries").inc()
                print(f"# retrying attempt {name}/b{b} once", flush=True)

    # HLO-probe provenance AFTER the scored runs (it must never eat the
    # measurement budget) but BEFORE the record prints (it must be IN
    # the record): the structural claims the number rests on, verified
    # in the same session the number was measured.
    dog.stage = "hlo probe provenance (cpu subprocess)"
    best["hlo_probe"] = _probe_summary(min(480.0, time_left() - 120.0))
    save_snapshot(best)

    dog.stage = "memory stats + report"
    mfu = best["value"]
    # The best config's runner can be gone: a LATER failed attempt at
    # another batch size consumed its donated state (the record is
    # already measured and safe; only the optional profile re-run needs
    # the live runner).
    runner = runners.get(best["attention"])
    data = batches[best["batch_per_chip"]]
    for name in list(runners):
        if name != best["attention"]:
            del runners[name]  # free the loser's params/opt state in HBM
    record = dict(best)
    mem = profiling.memory_summary()
    if mem.get("bytes_in_use"):
        record["hbm_gb_in_use"] = round(mem["bytes_in_use"] / 1e9, 2)
    dog.disarm()
    print(json.dumps(record), flush=True)
    # Spans (build/compile/dispatch), step counters, retry counts, and
    # the run manifest — written only when AUTODIST_TPU_TELEMETRY_DIR is
    # set; never on the measurement path.
    telemetry.gauge("bench/mfu").set(mfu)
    telemetry.flush()

    # Optional trace capture AFTER the record is emitted (a timeout mid-
    # capture must never discard an already-completed measurement) and
    # only when the number is actionable: a sub-target MFU needs a
    # profile to close the gap, and the hardware window may not come
    # back for a second run.
    prof_dir = os.environ.get("AUTODIST_TPU_BENCH_PROFILE", "")
    if prof_dir and on_accel and mfu < 0.45 and runner is not None:
        dog.stage = "profile capture (post-report)"
        # The record above is already printed, so a wedged capture step
        # must not hang until the driver's outer timeout (observed
        # failure mode: un-interruptible C call in PJRT).  The printing
        # watchdog is disarmed for good — its error line would follow
        # the real record — so arm a KILL-ONLY child: sleep, then
        # SIGKILL the bench, printing nothing.
        reaper = subprocess.Popen(
            [sys.executable, "-c",
             "import os,sys,time\ntime.sleep(float(sys.argv[2]))\n"
             "try: os.kill(int(sys.argv[1]), 9)\nexcept OSError: pass",
             str(os.getpid()), "300"], stderr=subprocess.DEVNULL)
        try:
            with jax.profiler.trace(prof_dir):
                # one steps-per-loop dispatch: the exact scored program
                fence(runner.run_steps(data)["loss"][-1])
            print(f"# profile trace written to {prof_dir}", flush=True)
        except Exception as e:  # pragma: no cover - capture must not kill bench
            print(f"# profile capture failed: {e}", flush=True)
        finally:
            reaper.kill()
            reaper.wait()


if __name__ == "__main__":
    main()
