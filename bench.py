"""Benchmark driver: prints ONE JSON line with the headline metric.

Measured on whatever devices are visible (the driver runs this on real TPU
hardware).  Metric: training-step throughput (examples/sec) plus model FLOP
utilization on the flagship model, in the style of the reference's
``TimeHistory`` examples/sec meter (``examples/benchmark/imagenet.py:84-140``).
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def main():
    from autodist_tpu import AllReduce, AutoDist, Trainable
    from autodist_tpu.resource import ResourceSpec

    dim, hidden, out, batch = 1024, 4096, 1024, 4096
    rng = np.random.RandomState(0)
    params = {
        "l1": {"w": jnp.asarray(rng.randn(dim, hidden) * 0.02, jnp.bfloat16)},
        "l2": {"w": jnp.asarray(rng.randn(hidden, hidden) * 0.02, jnp.bfloat16)},
        "l3": {"w": jnp.asarray(rng.randn(hidden, out) * 0.02, jnp.bfloat16)},
    }

    def loss_fn(p, b):
        h = jax.nn.relu(b["x"] @ p["l1"]["w"])
        h = jax.nn.relu(h @ p["l2"]["w"])
        pred = h @ p["l3"]["w"]
        return jnp.mean((pred.astype(jnp.float32) - b["y"]) ** 2)

    trainable = Trainable.from_loss_fn(loss_fn, params, optax.adam(1e-3))
    rs = ResourceSpec({})
    ad = AutoDist(rs, AllReduce(chunk_size=8))
    runner = ad.build(trainable)
    n = rs.num_devices()
    data = {"x": rng.randn(batch, dim).astype(np.float32),
            "y": rng.randn(batch, out).astype(np.float32)}

    runner.step(data)  # compile
    jax.block_until_ready(runner.state)
    steps = 20
    t0 = time.perf_counter()
    for _ in range(steps):
        runner.step(data)
    jax.block_until_ready(runner.state)
    dt = time.perf_counter() - t0

    examples_per_sec = batch * steps / dt
    # fwd+bwd matmul FLOPs: 3 matmuls * 2 mn k * 3 (fwd + 2x bwd)
    flops_per_example = 6 * (dim * hidden + hidden * hidden + hidden * out)
    mfu = (examples_per_sec * flops_per_example
           / (rs.chip.peak_bf16_tflops * 1e12 * n))
    print(json.dumps({
        "metric": "mlp_train_examples_per_sec",
        "value": round(examples_per_sec, 1),
        "unit": "examples/sec",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
