"""BERT MLM pretraining benchmark (≙ reference ``examples/benchmark/bert.py``:
BERT-large MLM with chunk-size 256).  Reports examples/sec and MFU.

    python examples/benchmark/bert.py --bert-config base --train-steps 30
    python examples/benchmark/bert.py --bert-config tiny --preset tiny
    python examples/benchmark/bert.py --flash-attention   # causal-free fused path
"""
from common import BenchmarkLogger, base_parser, run_benchmark


def main():
    ap = base_parser("BERT MLM pretraining benchmark")
    ap.add_argument("--bert-config", default="base",
                    choices=["tiny", "base", "large"])
    ap.add_argument("--seq-len", type=int, default=None)
    ap.add_argument("--num-masked", type=int, default=None)
    ap.add_argument("--flash-attention", action="store_true",
                    help="use the Pallas flash-attention kernel (no padding "
                         "mask: synthetic batches are unpadded)")
    args = ap.parse_args()

    import jax
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.models import bert
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.strategy import builders

    rs = ResourceSpec({})
    n = rs.num_devices()

    attention_fn = None
    if args.flash_attention:
        from autodist_tpu.ops import make_attention_fn
        attention_fn = make_attention_fn(causal=False)

    kw = dict(dropout_rate=0.0, attention_dropout_rate=0.0,
              attention_fn=attention_fn)
    if args.bert_config == "tiny" or args.preset == "tiny":
        cfg = TransformerConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                                num_heads=2, mlp_dim=128, max_len=128, **kw)
        seq_len, num_masked, batch = 64, 8, 4 * n
    else:
        cfg = (bert.bert_base if args.bert_config == "base"
               else bert.bert_large)(**kw)
        seq_len = args.seq_len or 512
        num_masked = args.num_masked or int(seq_len * 0.15)
        batch = args.batch_size or 16 * n
    chunk = args.chunk_size or 256  # reference bert.py:62

    trainable = bert.make_mlm_trainable(
        cfg, optax.adamw(1e-4, weight_decay=0.01), jax.random.PRNGKey(0),
        batch_size=2, seq_len=seq_len, num_masked=num_masked,
        with_input_mask=not args.flash_attention)
    builder = builders.create(args.strategy, **(
        {"chunk_size": chunk} if args.strategy == "AllReduce" else {}))
    runner = AutoDist(rs, builder).build(trainable)

    # Flash attention cannot honor the padding mask; synthetic batches are
    # unpadded (input_mask all ones) so drop it entirely on that path.
    data = bert.synthetic_mlm_batch(0, batch, seq_len, num_masked,
                                    cfg.vocab_size)
    if args.flash_attention:
        data = {k: v for k, v in data.items() if k != "input_mask"}

    import bench  # repo-root bench.py: the analytic FLOP model
    flops_per_example = bench.mlm_model_flops_per_example(
        cfg, seq_len, num_masked)
    peak = rs.chip.peak_bf16_tflops * 1e12 * n

    logger = BenchmarkLogger(args.benchmark_log_dir)
    summary = run_benchmark(
        runner, lambda step: data, batch_size=batch,
        train_steps=args.train_steps, warmup_steps=args.warmup_steps,
        log_steps=args.log_steps, logger=logger,
        steps_per_loop=args.steps_per_loop, static_data=True,
        flops_per_example=flops_per_example, peak_flops=peak)
    mfu = summary.get("mfu")
    print(f"bert-{args.bert_config}/{args.strategy}: "
          f"{summary['examples_per_sec']:.1f} examples/s"
          + (f", MFU={mfu:.3f}" if mfu is not None else "")
          + f" ({n}x {rs.chip.name})")
    logger.close()


if __name__ == "__main__":
    main()
