"""Shared benchmark harness (≙ reference ``examples/benchmark/utils/``:
absl flags system + benchmark logger + ``TimeHistory`` meter).

Provides the common flag set, a JSON-lines benchmark logger, and the
timed training loop all benchmark drivers share.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Callable, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))  # repo root when run as a script


def base_parser(description: str) -> argparse.ArgumentParser:
    """Common flags (≙ ``utils/flags/_base.py``/``_performance.py``)."""
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument("--strategy", default="AllReduce",
                    help="strategy builder name (AllReduce, PS, "
                         "PSLoadBalancing, PartitionedPS, Parallax, ZeRO, ...)")
    ap.add_argument("--batch-size", type=int, default=None,
                    help="global batch size (default: per-model)")
    ap.add_argument("--train-steps", type=int, default=30)
    ap.add_argument("--warmup-steps", type=int, default=2)
    ap.add_argument("--log-steps", type=int, default=10,
                    help="steps between throughput reports (TimeHistory)")
    ap.add_argument("--chunk-size", type=int, default=None,
                    help="allreduce bucketing chunk size (default: per-model)")
    ap.add_argument("--benchmark-log-dir", default=None,
                    help="write benchmark JSON lines here")
    ap.add_argument("--preset", choices=["tiny", "full"], default="full",
                    help="tiny = smoke-test sizes for CPU")
    return ap


class BenchmarkLogger:
    """JSON-lines metric logger (≙ ``utils/logs/logger.py``)."""

    def __init__(self, log_dir: Optional[str] = None):
        self._f = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._f = open(os.path.join(log_dir, "metric.log"), "a")

    def log_metric(self, name: str, value, unit: str = "", step: int = 0,
                   extras: Optional[dict] = None):
        record = {"name": name, "value": float(value), "unit": unit,
                  "timestamp": time.time(), "step": step,
                  **(extras or {})}
        line = json.dumps(record)
        print(line)
        if self._f:
            self._f.write(line + "\n")
            self._f.flush()

    def close(self):
        if self._f:
            self._f.close()


def run_benchmark(runner, make_batch: Callable[[int], dict], *,
                  batch_size: int, train_steps: int, warmup_steps: int,
                  log_steps: int, logger: BenchmarkLogger,
                  flops_per_example: Optional[float] = None,
                  peak_flops: Optional[float] = None) -> dict:
    """Timed training loop with windowed examples/sec reports
    (≙ ``TimeHistory``: examples/sec = batch_size × log_steps / elapsed,
    reference ``examples/benchmark/imagenet.py:84-140``).

    Batches ride the prefetching :class:`~autodist_tpu.data.DataLoader`
    (host→HBM transfer overlaps compute) and each timed step is fenced by
    fetching a metric scalar to the host — proxied/async backends may
    return from ``block_until_ready`` before execution finishes."""
    from autodist_tpu.data import DataLoader

    def fence(metrics):
        return float(np.asarray(next(iter(metrics.values()))))

    loader = iter(DataLoader(make_batch, runner.mesh, buffer_size=2,
                             num_batches=warmup_steps + train_steps))
    for step in range(warmup_steps):
        runner.step(next(loader))
    # Fence the *state*, not just metrics: the donated-state update can
    # outlive the metrics buffers and must not bleed into the timed window.
    state = getattr(runner, "state", None)
    if state is not None:
        float(np.asarray(state["step"]))

    times = []
    window_start = time.perf_counter()
    for step in range(train_steps):
        t0 = time.perf_counter()
        metrics = runner.step(next(loader))
        fence(metrics)
        times.append(time.perf_counter() - t0)
        if (step + 1) % log_steps == 0:
            elapsed = time.perf_counter() - window_start
            logger.log_metric("examples_per_sec",
                              batch_size * log_steps / elapsed, "examples/s",
                              step=step + 1)
            window_start = time.perf_counter()

    mean_s = float(np.mean(times))
    summary = {
        "examples_per_sec": batch_size / mean_s,
        "step_ms_mean": mean_s * 1e3,
        "step_ms_p50": float(np.percentile(times, 50) * 1e3),
    }
    if flops_per_example and peak_flops:
        summary["mfu"] = summary["examples_per_sec"] * flops_per_example / peak_flops
    logger.log_metric("examples_per_sec_final", summary["examples_per_sec"],
                      "examples/s", step=train_steps,
                      extras={k: v for k, v in summary.items()
                              if k != "examples_per_sec"})
    return summary
