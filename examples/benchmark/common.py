"""Shared benchmark harness (≙ reference ``examples/benchmark/utils/``:
absl flags system + benchmark logger + ``TimeHistory`` meter).

Provides the common flag set, a JSON-lines benchmark logger, and the
timed training loop all benchmark drivers share.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Callable, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))  # repo root when run as a script


def base_parser(description: str) -> argparse.ArgumentParser:
    """Common flags (≙ ``utils/flags/_base.py``/``_performance.py``)."""
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument("--strategy", default="AllReduce",
                    help="strategy builder name (AllReduce, PS, "
                         "PSLoadBalancing, PartitionedPS, Parallax, ZeRO, ...)")
    ap.add_argument("--batch-size", type=int, default=None,
                    help="global batch size (default: per-model)")
    ap.add_argument("--train-steps", type=int, default=30)
    ap.add_argument("--warmup-steps", type=int, default=2)
    ap.add_argument("--log-steps", type=int, default=10,
                    help="steps between throughput reports (TimeHistory)")
    ap.add_argument("--steps-per-loop", type=int, default=None,
                    help="steps fused into one device dispatch per report "
                         "window (default: --log-steps; 1 = legacy "
                         "per-step loop with per-step latency stats)")
    ap.add_argument("--chunk-size", type=int, default=None,
                    help="allreduce bucketing chunk size (default: per-model)")
    ap.add_argument("--benchmark-log-dir", default=None,
                    help="write benchmark JSON lines here")
    ap.add_argument("--preset", choices=["tiny", "full"], default="full",
                    help="tiny = smoke-test sizes for CPU")
    return ap


class BenchmarkLogger:
    """JSON-lines metric logger (≙ ``utils/logs/logger.py``)."""

    def __init__(self, log_dir: Optional[str] = None):
        self._f = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._f = open(os.path.join(log_dir, "metric.log"), "a")

    def log_metric(self, name: str, value, unit: str = "", step: int = 0,
                   extras: Optional[dict] = None):
        record = {"name": name, "value": float(value), "unit": unit,
                  "timestamp": time.time(), "step": step,
                  **(extras or {})}
        line = json.dumps(record)
        print(line)
        if self._f:
            self._f.write(line + "\n")
            self._f.flush()

    def close(self):
        if self._f:
            self._f.close()


def run_benchmark(runner, make_batch: Callable[[int], dict], *,
                  batch_size: int, train_steps: int, warmup_steps: int,
                  log_steps: int, logger: BenchmarkLogger,
                  flops_per_example: Optional[float] = None,
                  peak_flops: Optional[float] = None,
                  steps_per_loop: Optional[int] = None,
                  static_data: bool = False) -> dict:
    """Timed training loop with windowed examples/sec reports
    (≙ ``TimeHistory``: examples/sec = batch_size × log_steps / elapsed,
    reference ``examples/benchmark/imagenet.py:84-140``).

    When the runner supports :meth:`run_steps`, each report window runs
    as ONE fused device dispatch of ``steps_per_loop`` (default
    ``log_steps``) steps — host dispatch cost and the fencing round-trip
    are paid once per window instead of once per step, which on
    remote/tunneled backends is the difference between measuring the
    chip and measuring the transport.  Pass ``steps_per_loop=1`` to
    force the legacy per-step loop (per-step latency percentiles).
    Every window reuses one executable shape: warmup is one fused
    window, and ``train_steps`` is measured in ``train_steps //
    steps_per_loop`` whole windows.

    On the per-step path batches ride the prefetching
    :class:`~autodist_tpu.data.DataLoader` (host→HBM transfer overlaps
    compute) and each timed step is fenced by fetching a metric scalar —
    proxied/async backends may return from ``block_until_ready`` before
    execution finishes."""
    import jax

    def fence(metrics):
        leaf = np.asarray(next(iter(metrics.values())))
        return float(leaf if leaf.ndim == 0 else leaf[-1])

    fused = steps_per_loop != 1 and hasattr(runner, "run_steps")
    if fused:
        # One executable shape for warmup and every window: k is capped
        # by train_steps so a tiny run is not inflated to a full
        # log_steps window, and the warmup dispatch (which is also the
        # compile) replaces warmup_steps — it is always exactly k steps.
        from autodist_tpu import stack_steps

        k = min(int(steps_per_loop or log_steps), train_steps)
        windows = max(train_steps // k, 1)
        if windows * k != train_steps:
            print(f"# fused loop measures {windows * k} of "
                  f"{train_steps} requested steps ({windows} whole "
                  f"windows of {k}); pass --steps-per-loop 1 for exact "
                  "per-step counts", flush=True)

        def stacked(i0):
            return stack_steps([make_batch(i0 + j) for j in range(k)])

        # Static-source fast path: drivers that feed a constant batch
        # declare it (static_data=True), so one window serves warmup and
        # every timed window — placed on device ONCE instead of
        # re-transferring an identical stack per window (through a
        # tunneled backend that transfer IS the step time).
        static = static_data
        if static and hasattr(runner, "place_steps"):
            data = runner.place_steps(stacked(0))
        else:
            data = stacked(0)

        fence(runner.run_steps(data))   # compile + warmup window
        # Fence the *state* too: the donated-state update can outlive
        # the metrics buffers and must not bleed into the timed window.
        state = getattr(runner, "state", None)
        if state is not None:
            float(np.asarray(state["step"]))
        times = []
        if not static:
            data = stacked(k)
        for w in range(windows):
            t0 = time.perf_counter()
            metrics = runner.run_steps(data)
            if not static and w + 1 < windows:
                # Build the next window while the device runs this one
                # (the dispatch above is async until the fence): the
                # fused path's substitute for the DataLoader's prefetch.
                data = stacked(k * (w + 2))
            fence(metrics)
            dt = time.perf_counter() - t0
            times.append(dt)
            logger.log_metric("examples_per_sec", batch_size * k / dt,
                              "examples/s", step=k * (w + 1))
        mean_s = float(np.sum(times)) / (windows * k)
        summary = {
            "examples_per_sec": batch_size / mean_s,
            "step_ms_mean": mean_s * 1e3,
            # Deliberately NOT step_ms_p50: that key is the per-step
            # path's true per-step percentile; a window-derived stat
            # under the same name would corrupt cross-run comparisons.
            "step_ms_window_p50": float(np.percentile(times, 50) / k * 1e3),
            "steps_per_loop": k,
            "steps_measured": windows * k,
        }
        if flops_per_example and peak_flops:
            summary["mfu"] = (summary["examples_per_sec"]
                              * flops_per_example / peak_flops)
        logger.log_metric("examples_per_sec_final",
                          summary["examples_per_sec"], "examples/s",
                          step=windows * k,
                          extras={kk: v for kk, v in summary.items()
                                  if kk != "examples_per_sec"})
        return summary

    from autodist_tpu.data import DataLoader

    loader = iter(DataLoader(make_batch, runner.mesh, buffer_size=2,
                             num_batches=warmup_steps + train_steps))
    for step in range(warmup_steps):
        runner.step(next(loader))
    # Fence the *state*, not just metrics: the donated-state update can
    # outlive the metrics buffers and must not bleed into the timed window.
    state = getattr(runner, "state", None)
    if state is not None:
        float(np.asarray(state["step"]))

    times = []
    window_start = time.perf_counter()
    for step in range(train_steps):
        t0 = time.perf_counter()
        metrics = runner.step(next(loader))
        fence(metrics)
        times.append(time.perf_counter() - t0)
        if (step + 1) % log_steps == 0:
            elapsed = time.perf_counter() - window_start
            logger.log_metric("examples_per_sec",
                              batch_size * log_steps / elapsed, "examples/s",
                              step=step + 1)
            window_start = time.perf_counter()

    mean_s = float(np.mean(times))
    summary = {
        "examples_per_sec": batch_size / mean_s,
        "step_ms_mean": mean_s * 1e3,
        "step_ms_p50": float(np.percentile(times, 50) * 1e3),
    }
    if flops_per_example and peak_flops:
        summary["mfu"] = summary["examples_per_sec"] * flops_per_example / peak_flops
    logger.log_metric("examples_per_sec_final", summary["examples_per_sec"],
                      "examples/s", step=train_steps,
                      extras={k: v for k, v in summary.items()
                              if k != "examples_per_sec"})
    return summary
