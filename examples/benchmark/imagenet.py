"""ImageNet CNN benchmark (≙ reference ``examples/benchmark/imagenet.py``):
ResNet50/ResNet101/VGG16/InceptionV3/DenseNet121 with a strategy flag and
the reference's per-model allreduce chunk-size tuning
(``imagenet.py:151-158``: vgg16=25, resnet101=200, inceptionv3=30,
default=512).  Synthetic ImageNet-shaped data.

    python examples/benchmark/imagenet.py --model resnet50 --train-steps 50
    python examples/benchmark/imagenet.py --model resnet18 --preset tiny
"""
import numpy as np

from common import BenchmarkLogger, base_parser, run_benchmark

# Reference-tuned collective bucketing per model (imagenet.py:151-158).
CHUNK_SIZES = {"vgg16": 25, "resnet101": 200, "inceptionv3": 30}
DEFAULT_CHUNK = 512

# Textbook forward-pass GFLOPs per image at the canonical input size
# (224px; inception 299px), for MFU estimation (training ~ 3x fwd).
FWD_GFLOPS = {"resnet50": 4.1, "resnet101": 7.8, "vgg16": 15.5,
              "densenet121": 2.9, "inceptionv3": 5.7}


def build_model(name: str):
    from autodist_tpu.models import densenet, inception, resnet, vgg
    zoo = {
        "resnet18": resnet.ResNet18, "resnet50": resnet.ResNet50,
        "resnet101": resnet.ResNet101, "vgg16": vgg.VGG16,
        "densenet121": densenet.DenseNet121,
        "inceptionv3": inception.InceptionV3,
    }
    return zoo[name](num_classes=1000)


def main():
    ap = base_parser("ImageNet CNN benchmark")
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet18", "resnet50", "resnet101", "vgg16",
                             "densenet121", "inceptionv3"])
    ap.add_argument("--json", action="store_true",
                    help="also print one machine-readable headline line")
    args = ap.parse_args()

    import jax
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.models.resnet import make_image_trainable
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.strategy import builders

    rs = ResourceSpec({})
    n = rs.num_devices()
    on_accel = jax.default_backend() != "cpu"
    if args.preset == "tiny":
        image_size, candidates = 32, [8 * n]
    else:
        image_size = 299 if args.model == "inceptionv3" else 224
        if args.batch_size:
            candidates = [args.batch_size]
        elif on_accel:
            # Self-tune the per-chip batch: conv utilization keeps
            # climbing until HBM runs out, and the knee is
            # hardware/model dependent — measure a few steps of each
            # size and score the examples/sec winner (an OOM just
            # loses its probe).  Ascending order so the riskiest
            # allocation comes last.
            candidates = [32 * n, 128 * n, 256 * n]
        else:
            candidates = [32 * n]
    import os
    env_cands = os.environ.get("AUTODIST_TPU_BATCH_CANDIDATES")
    if env_cands and not args.batch_size:
        # Per-chip candidate list override: lets a hardware session
        # re-scope the probe (and CPU tests exercise the probe path)
        # without editing code.
        try:
            candidates = [int(s) * n for s in env_cands.split(",")]
        except ValueError:
            raise SystemExit(
                f"AUTODIST_TPU_BATCH_CANDIDATES={env_cands!r} is not a "
                f"comma-separated list of per-chip batch sizes")
    # Ascending: the probe loop stops at the first failure on the grounds
    # that every LARGER size shares its fate.
    candidates = sorted(candidates)
    if len(candidates) > 1 and jax.process_count() > 1:
        # Each process would pick from its own wall-clock timings; within
        # noise two hosts could choose different global batches and issue
        # shape-mismatched collectives.  Self-tuning is a single-host
        # convenience — multi-host runs state their batch explicitly.
        print("# multi-host run: skipping batch self-tune "
              f"(using {candidates[0] // n}/chip; set --batch-size to override)")
        candidates = candidates[:1]
    chunk = args.chunk_size or CHUNK_SIZES.get(args.model, DEFAULT_CHUNK)

    def build_runner():
        trainable = make_image_trainable(
            build_model(args.model), optax.sgd(0.1, momentum=0.9),
            jax.random.PRNGKey(0), image_size=image_size, batch_size=2,
            name=args.model)
        builder = builders.create(args.strategy, **(
            {"chunk_size": chunk} if args.strategy == "AllReduce" else {}))
        return AutoDist(rs, builder).build(trainable)

    runner = build_runner()
    rng = np.random.RandomState(0)

    def make_data(b):
        return {"x": rng.rand(b, image_size, image_size, 3).astype(np.float32),
                "y": rng.randint(0, 1000, (b,)).astype(np.int32)}

    batch = candidates[0]
    if len(candidates) > 1:
        import time
        rates, failed = {}, False
        for b in candidates:
            try:
                data = make_data(b)
                m = runner.step(data)                      # compile
                float(np.asarray(m["loss"]))
                t0 = time.perf_counter()
                for _ in range(3):
                    m = runner.step(data)
                float(np.asarray(m["loss"]))
                rates[b] = 3 * b / (time.perf_counter() - t0)
                print(f"# probe batch {b // n}/chip: {rates[b]:.1f} ex/s")
            except Exception as e:
                print(f"# probe batch {b // n}/chip failed: {e}")
                failed = True
                break  # larger sizes can only fail the same way
        if not rates:
            raise SystemExit("every batch-size probe failed")
        batch = max(rates, key=rates.get)
        if failed:
            # An OOM'd step may have consumed donated state buffers;
            # rebuild from the deterministic seed for the scored run.
            runner.close()
            runner = build_runner()

    data = make_data(batch)

    logger = BenchmarkLogger(args.benchmark_log_dir)
    flops_per_example = peak_flops = None
    if args.model in FWD_GFLOPS and args.preset != "tiny":
        flops_per_example = 3.0 * FWD_GFLOPS[args.model] * 1e9
        peak_flops = rs.chip.peak_bf16_tflops * 1e12 * n
    summary = run_benchmark(
        runner, lambda step: data, batch_size=batch,
        train_steps=args.train_steps, warmup_steps=args.warmup_steps,
        log_steps=args.log_steps, logger=logger,
        steps_per_loop=args.steps_per_loop, static_data=True,
        flops_per_example=flops_per_example, peak_flops=peak_flops)
    print(f"{args.model}/{args.strategy}: "
          f"{summary['examples_per_sec']:.1f} examples/s "
          f"({summary['step_ms_mean']:.1f} ms/step, {n} devices)")
    if args.json:
        import json
        record = {
            "metric": f"{args.model}_images_per_sec_per_chip",
            "value": round(summary["examples_per_sec"] / n, 2),
            "unit": "examples/sec/chip", "strategy": args.strategy,
            "devices": n, "chip": rs.chip.name, "image_size": image_size,
            "batch_per_chip": batch // n}
        if summary.get("mfu") is not None:
            record["mfu_est"] = round(summary["mfu"], 4)
        print(json.dumps(record))
    logger.close()


if __name__ == "__main__":
    main()
