"""ImageNet CNN benchmark (≙ reference ``examples/benchmark/imagenet.py``):
ResNet50/ResNet101/VGG16/InceptionV3/DenseNet121 with a strategy flag and
the reference's per-model allreduce chunk-size tuning
(``imagenet.py:151-158``: vgg16=25, resnet101=200, inceptionv3=30,
default=512).  Synthetic ImageNet-shaped data.

    python examples/benchmark/imagenet.py --model resnet50 --train-steps 50
    python examples/benchmark/imagenet.py --model resnet18 --preset tiny
"""
import numpy as np

from common import BenchmarkLogger, base_parser, run_benchmark

# Reference-tuned collective bucketing per model (imagenet.py:151-158).
CHUNK_SIZES = {"vgg16": 25, "resnet101": 200, "inceptionv3": 30}
DEFAULT_CHUNK = 512

# Textbook forward-pass GFLOPs per image at the canonical input size
# (224px; inception 299px), for MFU estimation (training ~ 3x fwd).
FWD_GFLOPS = {"resnet50": 4.1, "resnet101": 7.8, "vgg16": 15.5,
              "densenet121": 2.9, "inceptionv3": 5.7}


def build_model(name: str):
    from autodist_tpu.models import densenet, inception, resnet, vgg
    zoo = {
        "resnet18": resnet.ResNet18, "resnet50": resnet.ResNet50,
        "resnet101": resnet.ResNet101, "vgg16": vgg.VGG16,
        "densenet121": densenet.DenseNet121,
        "inceptionv3": inception.InceptionV3,
    }
    return zoo[name](num_classes=1000)


def main():
    ap = base_parser("ImageNet CNN benchmark")
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet18", "resnet50", "resnet101", "vgg16",
                             "densenet121", "inceptionv3"])
    ap.add_argument("--json", action="store_true",
                    help="also print one machine-readable headline line")
    args = ap.parse_args()

    import jax
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.models.resnet import make_image_trainable
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.strategy import builders

    rs = ResourceSpec({})
    n = rs.num_devices()
    if args.preset == "tiny":
        image_size, batch = 32, 8 * n
    else:
        image_size = 299 if args.model == "inceptionv3" else 224
        batch = args.batch_size or 32 * n
    chunk = args.chunk_size or CHUNK_SIZES.get(args.model, DEFAULT_CHUNK)

    trainable = make_image_trainable(
        build_model(args.model), optax.sgd(0.1, momentum=0.9),
        jax.random.PRNGKey(0), image_size=image_size, batch_size=2,
        name=args.model)
    builder = builders.create(args.strategy, **(
        {"chunk_size": chunk} if args.strategy == "AllReduce" else {}))
    runner = AutoDist(rs, builder).build(trainable)

    rng = np.random.RandomState(0)
    x = rng.rand(batch, image_size, image_size, 3).astype(np.float32)
    y = rng.randint(0, 1000, (batch,)).astype(np.int32)

    logger = BenchmarkLogger(args.benchmark_log_dir)
    flops_per_example = peak_flops = None
    if args.model in FWD_GFLOPS and args.preset != "tiny":
        flops_per_example = 3.0 * FWD_GFLOPS[args.model] * 1e9
        peak_flops = rs.chip.peak_bf16_tflops * 1e12 * n
    summary = run_benchmark(
        runner, lambda step: {"x": x, "y": y}, batch_size=batch,
        train_steps=args.train_steps, warmup_steps=args.warmup_steps,
        log_steps=args.log_steps, logger=logger,
        flops_per_example=flops_per_example, peak_flops=peak_flops)
    print(f"{args.model}/{args.strategy}: "
          f"{summary['examples_per_sec']:.1f} examples/s "
          f"({summary['step_ms_mean']:.1f} ms/step, {n} devices)")
    if args.json:
        import json
        record = {
            "metric": f"{args.model}_images_per_sec_per_chip",
            "value": round(summary["examples_per_sec"] / n, 2),
            "unit": "examples/sec/chip", "strategy": args.strategy,
            "devices": n, "chip": rs.chip.name, "image_size": image_size,
            "batch_per_chip": batch // n}
        if summary.get("mfu") is not None:
            record["mfu_est"] = round(summary["mfu"], 4)
        print(json.dumps(record))
    logger.close()


if __name__ == "__main__":
    main()
