"""NCF (NeuMF) recommendation benchmark
(≙ reference ``examples/benchmark/ncf.py``: NeuMF on MovieLens with
LazyAdam).  Synthetic MovieLens-1M-shaped interactions; the embedding
tables take the sparse/sharded path under PS-family strategies.

    python examples/benchmark/ncf.py --train-steps 50
    python examples/benchmark/ncf.py --preset tiny
"""
import numpy as np

from common import BenchmarkLogger, base_parser, run_benchmark


def main():
    ap = base_parser("NCF recommendation benchmark")
    ap.add_argument("--num-users", type=int, default=None)
    ap.add_argument("--num-items", type=int, default=None)
    args = ap.parse_args()

    import jax
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.models.ncf import make_ncf_trainable
    from autodist_tpu.resource import ResourceSpec

    rs = ResourceSpec({})
    n = rs.num_devices()
    if args.preset == "tiny":
        num_users, num_items, mf_dim, mlp_dims = 500, 200, 8, (32, 16, 8)
        batch = args.batch_size or 64 * n
    else:  # MovieLens-1M scale (reference ncf defaults)
        num_users = args.num_users or 6040
        num_items = args.num_items or 3706
        mf_dim, mlp_dims = 64, (256, 128, 64)
        batch = args.batch_size or 1024 * n

    trainable = make_ncf_trainable(
        # adam stands in for LazyAdam: with the sparse/sharded embedding
        # path only touched rows move, which is what LazyAdam bought on TF
        optax.adam(1e-3), jax.random.PRNGKey(0),
        num_users=num_users, num_items=num_items, mf_dim=mf_dim,
        mlp_dims=mlp_dims)
    runner = AutoDist(rs, args.strategy).build(trainable)

    rng = np.random.RandomState(0)

    def make_batch(step):
        return {
            "users": rng.randint(0, num_users, (batch,)).astype(np.int32),
            "items": rng.randint(0, num_items, (batch,)).astype(np.int32),
            "labels": rng.randint(0, 2, (batch,)).astype(np.int32),
        }

    logger = BenchmarkLogger(args.benchmark_log_dir)
    summary = run_benchmark(
        runner, make_batch, batch_size=batch,
        train_steps=args.train_steps, warmup_steps=args.warmup_steps,
        log_steps=args.log_steps, logger=logger,
        steps_per_loop=args.steps_per_loop)
    print(f"ncf/{args.strategy}: {summary['examples_per_sec']:.0f} "
          f"examples/s ({summary['step_ms_mean']:.2f} ms/step, {n} devices)")
    logger.close()


if __name__ == "__main__":
    main()
