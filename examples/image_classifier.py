"""MNIST-scale CNN, data-parallel (≙ reference ``examples/image_classifier.py``).

Runs on synthetic MNIST-shaped data (no dataset downloads in this image)::

    python examples/image_classifier.py --steps 30
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import jax
import numpy as np
import optax

from autodist_tpu import AutoDist
from autodist_tpu.models.cnn import make_cnn_trainable


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--strategy", default="AllReduce")
    args = ap.parse_args()

    trainable = make_cnn_trainable(optax.adam(1e-3), jax.random.PRNGKey(0))
    runner = AutoDist({}, args.strategy).build(trainable)

    rng = np.random.RandomState(0)
    for step in range(args.steps):
        x = rng.rand(args.batch_size, 28, 28, 1).astype(np.float32)
        y = rng.randint(0, 10, (args.batch_size,)).astype(np.int32)
        metrics = runner.step({"x": x, "y": y})
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step}: loss={float(np.asarray(metrics['loss'])):.4f}")


if __name__ == "__main__":
    main()
