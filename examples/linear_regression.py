"""Minimal end-to-end example (≙ reference ``examples/linear_regression.py``).

Train a linear model with the default strategy on whatever devices are
visible::

    python examples/linear_regression.py --steps 50
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np
import optax

from autodist_tpu import AutoDist
from autodist_tpu.models.cnn import make_linear_regression_trainable


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--strategy", default="AllReduce")
    args = ap.parse_args()

    trainable = make_linear_regression_trainable(optax.sgd(0.1), dim=13)
    ad = AutoDist({}, args.strategy)
    runner = ad.build(trainable)

    rng = np.random.RandomState(0)
    true_w = rng.randn(13, 1)
    for step in range(args.steps):
        x = rng.randn(args.batch_size, 13).astype(np.float32)
        y = (x @ true_w + 0.01 * rng.randn(args.batch_size, 1)).astype(np.float32)
        metrics = runner.step({"x": x, "y": y})
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step}: loss={float(np.asarray(metrics['loss'])):.5f}")


if __name__ == "__main__":
    main()
