"""lm1b-style word language model with sampled softmax
(≙ reference ``examples/lm1b/lm1b_train.py``), Parallax hybrid strategy:
dense LSTM weights go over allreduce, the embedding and softmax tables
take the sharded sparse path.

    python examples/lm1b_train.py --steps 20
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import jax
import numpy as np
import optax

from autodist_tpu import AutoDist
from autodist_tpu.models.lm1b import make_lm1b_trainable


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=20)
    ap.add_argument("--vocab-size", type=int, default=10_000)
    ap.add_argument("--strategy", default="Parallax")
    ap.add_argument("--data", default=None,
                    help="flat binary int32 token file (native mmap "
                         "reader); default: synthetic tokens")
    args = ap.parse_args()

    trainable = make_lm1b_trainable(
        optax.adagrad(0.2), jax.random.PRNGKey(0),
        vocab_size=args.vocab_size, seq_len=args.seq_len,
        batch_size=args.batch_size)
    runner = AutoDist({}, args.strategy).build(trainable)

    if args.data:
        from autodist_tpu.data import lm_window_loader
        # The embedding gather clamps out-of-range ids silently; scan the
        # whole file's max once up front (a streaming pass over the mmap)
        # so a bad id in ANY window fails loudly, not just step 0's.
        mm = np.memmap(args.data, dtype=np.int32, mode="r")
        hi = int(mm.max()) if len(mm) else 0
        del mm
        if hi >= args.vocab_size:
            raise SystemExit(
                f"--data contains token id {hi} >= --vocab-size "
                f"{args.vocab_size}; pass the tokenizer's size")
        source = lm_window_loader(args.data, batch_size=args.batch_size,
                                  seq_len=args.seq_len, seed=0)
    else:
        rng = np.random.RandomState(0)

        def source(step):
            x = rng.randint(0, args.vocab_size,
                            (args.batch_size, args.seq_len)).astype(np.int32)
            return {"x": x, "y": np.roll(x, -1, axis=1)}

    for step in range(args.steps):
        metrics = runner.step(source(step))
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss={float(np.asarray(metrics['loss'])):.4f}")


if __name__ == "__main__":
    main()
