"""lm1b-style word language model with sampled softmax
(≙ reference ``examples/lm1b/lm1b_train.py``), Parallax hybrid strategy:
dense LSTM weights go over allreduce, the embedding and softmax tables
take the sharded sparse path.

    python examples/lm1b_train.py --steps 20
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import jax
import numpy as np
import optax

from autodist_tpu import AutoDist
from autodist_tpu.models.lm1b import make_lm1b_trainable


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=20)
    ap.add_argument("--vocab-size", type=int, default=10_000)
    ap.add_argument("--strategy", default="Parallax")
    args = ap.parse_args()

    trainable = make_lm1b_trainable(
        optax.adagrad(0.2), jax.random.PRNGKey(0),
        vocab_size=args.vocab_size, seq_len=args.seq_len,
        batch_size=args.batch_size)
    runner = AutoDist({}, args.strategy).build(trainable)

    rng = np.random.RandomState(0)
    for step in range(args.steps):
        x = rng.randint(0, args.vocab_size,
                        (args.batch_size, args.seq_len)).astype(np.int32)
        y = np.roll(x, -1, axis=1)
        metrics = runner.step({"x": x, "y": y})
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss={float(np.asarray(metrics['loss'])):.4f}")


if __name__ == "__main__":
    main()
