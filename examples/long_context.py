"""Long-context training with sequence parallelism (beyond reference
parity — the reference never sharded the sequence dimension, SURVEY.md
§5.7).

Shards the token dimension over a ``seq`` mesh axis: ring attention
rotates k/v blocks so every token attends globally while activation
memory per device scales as O(L/seq).  Positions come from
``sequence.global_positions`` so shards embed their true offsets::

    python examples/long_context.py --seq-len 2048 --seq-parallel 4
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--seq-parallel", type=int, default=None,
                    help="seq-axis size (default: all devices)")
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--flash", action="store_true",
                    help="Pallas flash kernel per ring chunk")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import flax.linen as nn
    import optax
    from jax.sharding import Mesh

    from autodist_tpu.capture import Trainable
    from autodist_tpu.parallel.ring_attention import ring_self_attention
    from autodist_tpu.parallel.sequence import (global_positions,
                                                lower_sequence_parallel)

    n = len(jax.devices())
    sp = args.seq_parallel or n
    dp = n // sp
    if dp * sp != n:
        raise SystemExit(f"{n} devices != data {dp} x seq {sp}")
    axes = ("data", "seq") if dp > 1 else ("seq",)
    shape = (dp, sp) if dp > 1 else (sp,)
    mesh = Mesh(np.array(jax.devices()).reshape(shape), axes)
    H, L, V = args.hidden, args.seq_len, 1024
    heads = 4

    class Block(nn.Module):
        sharded: bool = True

        @nn.compact
        def __call__(self, x):
            B, Ll, _ = x.shape
            qkv = nn.Dense(3 * H, name="qkv")(x).reshape(
                B, Ll, 3, heads, H // heads)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            if self.sharded:
                if args.flash:
                    from autodist_tpu.parallel.ring_attention import (
                        ring_flash_attention)
                    o = ring_flash_attention(q, k, v, axis_name="seq",
                                             causal=True)
                else:
                    o = ring_self_attention(q, k, v, axis_name="seq",
                                            causal=True)
            else:  # init-time trace outside the mesh
                s = jnp.einsum("blhd,bmhd->bhlm", q, k) / np.sqrt(
                    H // heads)
                mask = jnp.tril(jnp.ones((Ll, Ll), bool))
                s = jnp.where(mask[None, None], s, -1e30)
                o = jnp.einsum("bhlm,bmhd->blhd",
                               jax.nn.softmax(s, axis=-1), v)
            o = o.reshape(B, Ll, H)
            x = nn.LayerNorm()(x + nn.Dense(H, name="out")(o))
            h = nn.gelu(nn.Dense(4 * H, name="wi")(x))
            return nn.LayerNorm()(x + nn.Dense(H, name="wo")(h))

    class LM(nn.Module):
        # Positions are pluggable: plain arange at init time (outside the
        # mesh), shard-aware global_positions inside the sharded step.
        sharded: bool = True

        @nn.compact
        def __call__(self, tokens):
            B, Ll = tokens.shape
            embed = nn.Embed(V, H, name="embed")
            pos = self.param("pos", nn.initializers.normal(0.02), (L, H))
            ids = global_positions(Ll) if self.sharded else jnp.arange(Ll)
            x = embed(tokens) + pos[ids]
            for i in range(args.layers):
                x = Block(sharded=self.sharded, name=f"layer_{i}")(x)
            return embed.attend(x)

    model = LM(sharded=True)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, batch["y"][..., None], axis=-1)
        return -jnp.mean(ll)

    # Init outside the mesh with the unsharded variant (same params).
    params = LM(sharded=False).init(
        jax.random.PRNGKey(0), jnp.zeros((2, L), jnp.int32))["params"]
    trainable = Trainable.from_loss_fn(loss_fn, params, optax.adamw(3e-4))

    init_fn, step_fn, _ = lower_sequence_parallel(trainable, mesh)
    state = init_fn(params, None)
    rng = np.random.RandomState(0)

    def batch(_):
        x = rng.randint(0, V, (args.batch_size, L)).astype(np.int32)
        return {"x": x, "y": np.roll(x, -1, axis=1)}

    state, m = step_fn(state, batch(0), jax.random.PRNGKey(0))  # compile
    float(np.asarray(m["loss"]))
    t0 = time.perf_counter()
    for i in range(args.steps):
        state, m = step_fn(state, batch(i), jax.random.PRNGKey(i))
    loss = float(np.asarray(m["loss"]))
    dt = time.perf_counter() - t0
    tokens_per_sec = args.batch_size * L * args.steps / dt
    print(f"long-context: seq={L} dp={dp} sp={sp} "
          f"attn={'flash' if args.flash else 'einsum'} "
          f"loss={loss:.4f} tokens/s={tokens_per_sec:,.0f}")


if __name__ == "__main__":
    main()
