"""Mixture-of-Experts LM training through the ExpertParallel strategy.

Beyond reference parity (SURVEY.md §2.10 lists expert parallelism as
absent): the bundled MoE transformer LM with GShard top-2 routing,
experts sharded over the ``expert`` mesh axis, tokens traveling by
``all_to_all`` — with the dispatch/combine wire joining the
per-collective precision policy (``--collective-precision int8``) and
the fused quantized ring kernel (``--a2a-ring``) on top.

    python examples/moe_train.py --steps 20
    python examples/moe_train.py --num-experts 8 --capacity-factor 1.5
    python examples/moe_train.py --collective-precision int8 --a2a-ring
    python examples/moe_train.py --auto-search --num-slices 2

``--auto-search`` hands the factorization to the topology-aware
search: the MoE trainable declares its expert count and capacity
factor, so the candidate family sweeps the expert-axis degree (1 = the
dense point), its placement (within a slice vs deliberately across
DCN), the dispatch/combine wire precision, and the a2a_ring kernel —
and trains the frontier winner.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--num-experts", "--experts", type=int, default=8,
                    dest="num_experts",
                    help="expert tables in every MoE block (the model "
                         "shape; the expert-axis degree that shards "
                         "them is the topology's largest compatible "
                         "divisor, or the search's election under "
                         "--auto-search)")
    ap.add_argument("--capacity-factor", type=float, default=2.0,
                    help="per-expert slot headroom: each expert keeps "
                         "capacity_factor x (tokens/experts) slots per "
                         "routing pass; overflow tokens drop (GShard "
                         "semantics) and the dispatch/combine payload "
                         "the cost model prices scales with it")
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--collective-precision", default="off",
                    choices=["off", "bf16", "int8"],
                    help="moe_a2a wire precision: quantize the "
                         "dispatch/combine all_to_all payload to this "
                         "width (permute-shaped, so int8 is TRUE s8 on "
                         "the wire); the drift report breaks out the "
                         "predicted a2a bytes/time")
    ap.add_argument("--a2a-ring", action="store_true",
                    help="fuse the q/dq into the dispatch/combine ring "
                         "kernel (EQuARX-style per-hop VMEM passes; "
                         "needs --collective-precision int8)")
    ap.add_argument("--zero-stage", type=int, default=0,
                    choices=[0, 1, 2, 3],
                    help="ZeRO stage over the replicated (dense) "
                         "parameters' sync axes")
    ap.add_argument("--auto-search", action="store_true",
                    help="replace the explicit flags with the "
                         "topology-aware strategy search (the expert "
                         "family: expert-axis degree x placement x "
                         "wire precision x kernel), print the search "
                         "report, and train the winner")
    ap.add_argument("--num-slices", type=int, default=1,
                    help="declare a multi-slice topology (with "
                         "--auto-search): the search keeps the expert "
                         "axis within a slice unless this topology's "
                         "link constants invert the trade")
    ap.add_argument("--telemetry-dir", default=None,
                    help="flush telemetry here: metrics.jsonl, "
                         "manifest.json (run.moe annotation), "
                         "drift.json (predicted-vs-measured with the "
                         "comm/a2a_bytes breakout)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from autodist_tpu import AutoDist, analysis, telemetry
    from autodist_tpu.models.moe_transformer import (MoeConfig,
                                                     make_moe_lm_trainable)
    from autodist_tpu.simulator.cost_model import CostModel
    from autodist_tpu.strategy.parallel_builders import ExpertParallel

    n = jax.device_count()
    # Largest expert-axis degree this topology supports: divides both
    # the device count and the expert count (1 = dense fallback).
    expert_axis = max((d for d in range(1, n + 1)
                       if n % d == 0 and args.num_experts % d == 0),
                      default=1)
    dp = n // expert_axis
    if args.batch % n:
        raise SystemExit(f"--batch {args.batch} must divide over the "
                         f"{n} visible devices (batch shards over "
                         "data x expert)")
    precision = None if args.collective_precision == "off" \
        else args.collective_precision
    if args.a2a_ring and precision != "int8":
        raise SystemExit("--a2a-ring fuses the int8 q/dq into the ring "
                         "hops; pass --collective-precision int8")

    cfg = MoeConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=4,
                    expert_hidden=2 * args.hidden,
                    num_experts=args.num_experts,
                    capacity_factor=args.capacity_factor,
                    max_len=args.seq_len, dtype=jnp.float32)
    trainable = make_moe_lm_trainable(cfg, optax.adam(1e-3),
                                      jax.random.PRNGKey(0),
                                      batch_size=args.batch,
                                      seq_len=args.seq_len)
    builder = ExpertParallel(
        num_experts=args.num_experts,
        capacity_factor=args.capacity_factor,
        zero_stage=args.zero_stage,
        collective_precision=({"moe_a2a": precision} if precision
                              else None),
        kernel=(("a2a_ring",) if args.a2a_ring else None))

    if args.telemetry_dir:
        telemetry.configure(out_dir=args.telemetry_dir)
    if args.auto_search:
        # The search owns the factorization (expert degree, placement,
        # wire, kernel); the spec declares only the topology.
        topo = {"num_devices": n}
        if args.num_slices > 1:
            topo["num_slices"] = args.num_slices
        ad = AutoDist({"topology": topo}, builder)
        from autodist_tpu.simulator.search import search_strategies

        result = search_strategies(trainable, ad.resource_spec,
                                   global_batch=args.batch)
        print(result.report())
        if result.winner is None:
            raise SystemExit("auto-search: no candidate priced — "
                             "widen the SearchSpace or check the "
                             "topology")
        strategy = result.winner.strategy
        cost_spec = result.winner.spec
        runner = ad.build(trainable, strategy)
    else:
        mesh = {"expert": expert_axis} if dp == 1 \
            else {"data": dp, "expert": expert_axis}
        ad = AutoDist({"topology": {"num_devices": n}, "mesh": mesh},
                      builder)
        # The strategy stays in hand so the drift report below joins
        # the cost model's prediction for exactly the program that ran.
        strategy = ad.build_or_load_strategy(trainable)
        cost_spec = ad.resource_spec
        runner = ad.build(trainable, strategy)

    plan_report = analysis.lint_plan(
        strategy, resource_spec=cost_spec, trainable=trainable,
        lowered=getattr(runner, "lowered", None))
    if plan_report.diagnostics:
        print(f"plan lint ({len(plan_report.errors)} error(s), "
              f"{len(plan_report.warnings)} warning(s)):")
        for diag in plan_report.sorted():
            print(f"  {diag}")
    else:
        print("plan lint: clean")

    gc = strategy.graph_config
    run_expert_axis = int((gc.mesh_axes or {}).get("expert", 1) or 1)
    run_over_dcn = bool((gc.parallel or {}).get("expert_over_dcn",
                                                False))
    if args.auto_search:
        print(f"auto-search winner: {result.winner.name} "
              f"(mesh {gc.mesh_axes})")
    else:
        print(f"MoE LM: {args.num_experts} experts over the "
              f"{run_expert_axis}-way expert axis (dp={dp}), "
              f"capacity_factor={args.capacity_factor}, "
              f"moe_a2a={precision or 'fp32'}"
              f"{' + a2a_ring' if args.a2a_ring else ''}, "
              f"zero_stage={args.zero_stage}")

    cost = CostModel(cost_spec).strategy_cost(trainable, strategy)

    from autodist_tpu.utils import profiling

    timer = profiling.StepTimer(args.batch,
                                warmup=min(2, max(args.steps - 1, 0)))
    import time

    r = np.random.RandomState(0)
    for step in range(args.steps):
        x = r.randint(0, args.vocab,
                      (args.batch, args.seq_len)).astype(np.int32)
        batch = {"x": x, "y": np.roll(x, -1, axis=1)}
        t_step = time.perf_counter()
        with timer:
            metrics = runner.step(batch)
            if args.telemetry_dir:
                jax.block_until_ready(metrics)
        telemetry.record_step(step=step,
                              duration_s=time.perf_counter() - t_step,
                              examples=args.batch)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: "
                  f"loss={float(np.asarray(metrics['loss'])):.4f} "
                  f"nll={float(np.asarray(metrics['nll'])):.4f} "
                  f"aux={float(np.asarray(metrics['aux'])):.4f}")

    summary = timer.summary()
    if args.telemetry_dir:
        from autodist_tpu.utils.profiling import memory_summary

        # The manifest describes the program that RAN: under
        # --auto-search the winner's expert degree/placement, not the
        # CLI flags (which only sized the model there).  The run.moe
        # annotation is what `tools/telemetry_report.py --check` joins
        # the comm/a2a_bytes gauge and drift breakout against.
        telemetry.annotate(
            mesh=dict(gc.mesh_axes or {}),
            auto_search=args.auto_search, batch=args.batch,
            moe=dict(num_experts=args.num_experts,
                     capacity_factor=args.capacity_factor,
                     expert_axis=run_expert_axis,
                     expert_over_dcn=run_over_dcn),
            collective_precision=dict(gc.precision),
            kernel=sorted(gc.kernel or ()),
            zero_stage=args.zero_stage,
            step_summary=summary)
        report = telemetry.drift_report(
            strategy, CostModel(cost_spec),
            {"step": summary, "memory": memory_summary(),
             "examples_per_sec": summary.get("examples_per_sec")},
            trainable=trainable)
        paths = telemetry.flush()
        print(f"telemetry artifacts in {args.telemetry_dir}: "
              f"{sorted(os.path.basename(p) for p in paths.values())}")
        ratios = {k: round(v, 3) for k, v in report["ratios"].items()}
        print(f"drift (measured/predicted): {ratios}")
        if cost.a2a_bytes:
            print(f"dispatch/combine: predicted "
                  f"{cost.a2a_bytes / 1e6:.3f} MB/step on the a2a wire "
                  f"({cost.a2a_time_s * 1e6:.1f} us/step"
                  f"{', over DCN' if run_over_dcn else ''})")


if __name__ == "__main__":
    main()
