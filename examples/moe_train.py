"""Mixture-of-Experts LM training through the ExpertParallel strategy.

Beyond reference parity (SURVEY.md §2.10 lists expert parallelism as
absent): the bundled MoE transformer LM with GShard top-2 routing,
experts sharded over the ``expert`` mesh axis, tokens traveling by
``all_to_all``.

    python examples/moe_train.py --steps 20
    python examples/moe_train.py --experts 8 --layers 2
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.models.moe_transformer import (MoeConfig,
                                                     make_moe_lm_trainable)

    n = jax.device_count()
    expert_axis = n  # all devices carry experts; they double as batch
    if args.experts % expert_axis:
        raise SystemExit(f"--experts {args.experts} must divide the "
                         f"{expert_axis}-device expert axis")

    cfg = MoeConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=4,
                    expert_hidden=2 * args.hidden,
                    num_experts=args.experts, max_len=args.seq_len,
                    dtype=jnp.float32)
    trainable = make_moe_lm_trainable(cfg, optax.adam(1e-3),
                                      jax.random.PRNGKey(0),
                                      batch_size=2, seq_len=args.seq_len)
    runner = AutoDist({"topology": {"num_devices": n},
                       "mesh": {"expert": expert_axis}},
                      "ExpertParallel").build(trainable)

    r = np.random.RandomState(0)
    print(f"MoE LM: {args.experts} experts over {expert_axis} devices, "
          f"{args.layers} layers")
    for step in range(args.steps):
        x = r.randint(0, args.vocab,
                      (args.batch, args.seq_len)).astype(np.int32)
        batch = {"x": x, "y": np.roll(x, -1, axis=1)}
        m = runner.step(batch)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss={float(np.asarray(m['loss'])):.4f} "
                  f"nll={float(np.asarray(m['nll'])):.4f} "
                  f"aux={float(np.asarray(m['aux'])):.4f}")


if __name__ == "__main__":
    main()
