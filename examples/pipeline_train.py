"""Pipeline-parallel training through the Strategy IR.

Beyond reference parity (the reference declared pipeline parallelism
future work, ``docs/design/architecture.rst:49-51``): a stage-stacked
Megatron MLP trained over the ``pipe`` mesh axis, GPipe or interleaved
(``--virtual-stages 2``), with gradient accumulation composing on top —
and tensor parallelism *inside* each stage (``--tensor-parallel 2``):
the mesh factors as dp×pp×tp and each stage's wi/wo matmuls run
column/row-parallel over the ``model`` axis with one activation
all-reduce per stage.

    python examples/pipeline_train.py --steps 20
    python examples/pipeline_train.py --virtual-stages 2 --microbatches 4
    python examples/pipeline_train.py --tensor-parallel 2 --stages 2
    python examples/pipeline_train.py --tensor-parallel 2 --stages 2 \
        --comm-overlap matmul --profile-dir /tmp/pp_trace
    python examples/pipeline_train.py --tensor-parallel 2 --stages 2 \
        --vocab-parallel --vocab 512

``--vocab-parallel`` switches the workload to the pipelined
transformer LM (the MLP has no embedding to shard) and shards its tied
embedding/unembedding over the ``model`` axis: the prologue runs the
masked-lookup psum and the loss head the streaming fused cross-entropy
epilogue, so embedding state and peak logits memory drop by 1/tp.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--stages", type=int, default=4,
                    help="pipe-axis devices")
    ap.add_argument("--virtual-stages", type=int, default=1)
    ap.add_argument("--microbatches", type=int, default=2)
    ap.add_argument("--accum-steps", type=int, default=1)
    ap.add_argument("--tensor-parallel", type=int, default=1,
                    help="model-axis devices per stage (Megatron TP "
                         "inside the pipeline: dp x pp x tp)")
    ap.add_argument("--comm-overlap", choices=["off", "rsag", "matmul"],
                    default="off",
                    help="latency-hiding decomposition of the model-axis "
                         "activation collectives (with --tensor-parallel "
                         "> 1): rsag = reduce-scatter + all-gather pairs, "
                         "matmul = chunked collective-matmul ppermute ring")
    ap.add_argument("--vocab-parallel", action="store_true",
                    help="shard the tied embedding/unembedding's vocab "
                         "dim over the model axis (with --tensor-parallel "
                         "> 1) and run the streaming fused cross-entropy "
                         "epilogue; switches the workload to the "
                         "pipelined transformer LM")
    ap.add_argument("--vocab", type=int, default=256,
                    help="LM vocab size (with --vocab-parallel; odd "
                         "values exercise the zero-pad path)")
    ap.add_argument("--seq", type=int, default=16,
                    help="LM sequence length (with --vocab-parallel)")
    ap.add_argument("--collective-precision", default="off",
                    choices=["off", "bf16", "int8"],
                    help="per-collective precision policy: narrow every "
                         "policied boundary (TP activation psums, "
                         "decomposed rs/ag halves, vocab-epilogue "
                         "stats, ZeRO-3 gathers, dp grad sync via the "
                         "EF compressors) to this wire precision; the "
                         "drift report breaks out the predicted "
                         "bytes-on-wire delta")
    ap.add_argument("--zero-stage", type=int, default=0,
                    choices=[0, 1, 2, 3],
                    help="ZeRO stage over the data axes (stage vars) / "
                         "pipe x data (shared vars): 1 shards optimizer "
                         "state, 2 accounts gradients sharded (same "
                         "reduce-scatter program), 3 stores parameters "
                         "sharded with per-layer on-demand all-gathers")
    ap.add_argument("--zero1", action="store_true",
                    help="deprecated alias for --zero-stage 1")
    ap.add_argument("--remat", action="store_true",
                    help="jax.checkpoint each chunk (memory for compute)")
    ap.add_argument("--auto-search", action="store_true",
                    help="replace the explicit knob flags with the "
                         "topology-aware strategy search: enumerate "
                         "the (dp, pp, tp, vocab, zero, overlap, "
                         "precision, microbatch, compressor) "
                         "cross-product for the visible topology, "
                         "print the search report (configs enumerated/"
                         "pruned/priced, frontier top-10 with "
                         "per-level comm breakdown, winner knob "
                         "string), and train the winner")
    ap.add_argument("--preempt-demo", action="store_true",
                    help="simulate a mid-run preemption: at the halfway "
                         "step a SIGTERM triggers a blocking elastic "
                         "checkpoint, the run shrinks to half the "
                         "devices, the topology-aware search re-elects "
                         "a winner on the survivors, the checkpoint is "
                         "resharded onto it, and training resumes "
                         "(docs/usage/elasticity.md)")
    ap.add_argument("--preempt-ckpt-dir", default=None,
                    help="checkpoint directory for --preempt-demo "
                         "(default: a temp dir)")
    ap.add_argument("--chaos", default=None, metavar="PLAN_JSON",
                    help="run under a fault plan (runtime/faults.py "
                         "JSON: ckpt_write_fail retries/degrades on the "
                         "Saver, preempt_signal takes the elastic "
                         "shrink-resume path, slow_host stalls the "
                         "chief) — the single-process demo of what "
                         "tools/chaos_run.py sweeps against a "
                         "LocalCluster; docs/usage/robustness.md")
    ap.add_argument("--num-slices", type=int, default=1,
                    help="declare a multi-slice topology (with "
                         "--auto-search): the outer dp axis rides DCN "
                         "and the search keeps tp/pp within a slice; "
                         "simulated CPU meshes lower it too")
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--profile-dir", default=None,
                    help="capture an xplane trace of the step loop here; "
                         "also implies --telemetry-dir here, so a "
                         "hardware window yields the trace plus step "
                         "records/manifest/drift report with zero extra "
                         "typing")
    ap.add_argument("--telemetry-dir", default=None,
                    help="flush telemetry here: trace.json (chrome "
                         "trace of build/compile/step spans), "
                         "metrics.jsonl (per-step records + counters), "
                         "manifest.json (git SHA/jax versions/run "
                         "config), drift.json (cost-model predicted vs "
                         "measured step time + memory)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from autodist_tpu import AutoDist, PipelineTrainable
    from autodist_tpu.parallel.pipeline import bubble_fraction
    from autodist_tpu.parallel.tensor import column_parallel, row_parallel
    from autodist_tpu.resource import factor_3d
    from autodist_tpu.strategy.builders import GradAccumulation
    from autodist_tpu.strategy.parallel_builders import Pipeline

    n = jax.device_count()
    tp = args.tensor_parallel
    if tp < 1 or n % tp or n // tp < 1:
        raise SystemExit(
            f"--tensor-parallel {tp} must divide the {n} visible devices")
    pp = min(args.stages, n // tp)
    if (n // tp) % pp:
        raise SystemExit(
            f"--stages resolves to pipe={pp}, which must divide the "
            f"{n // tp} devices left after tp={tp}")
    dp = n // (pp * tp)
    mesh = factor_3d(dp * pp * tp, pipe=pp, model=tp, data=dp)
    C = pp * args.virtual_stages
    HID, FF = args.hidden, 2 * args.hidden
    r = np.random.RandomState(0)
    # Megatron block per stage: wi column-parallel, wo row-parallel —
    # the same variable naming the Pipeline builder's tp rule table keys
    # on (qkv/out/wi/wo).
    stacked = {
        "wi": {"kernel": jnp.asarray(
                   r.randn(C, HID, FF) * (2.0 / HID) ** 0.5, jnp.float32),
               "bias": jnp.zeros((C, FF), jnp.float32)},
        "wo": {"kernel": jnp.asarray(
                   r.randn(C, FF, HID) * (2.0 / FF) ** 0.5, jnp.float32),
               "bias": jnp.zeros((C, HID), jnp.float32)},
    }

    def stage(p, x, model_axis=None, comm_overlap=None):
        h = jax.nn.relu(column_parallel(x, p["wi"]["kernel"],
                                        p["wi"]["bias"],
                                        model_axis=model_axis,
                                        comm_overlap=comm_overlap))
        return row_parallel(h, p["wo"]["kernel"], p["wo"]["bias"],
                            model_axis=model_axis,
                            comm_overlap=comm_overlap)

    def head(outputs, batch):
        loss = jnp.mean((outputs - batch["y"]) ** 2)
        return loss, {}

    if args.vocab_parallel:
        # Vocab parallelism shards the shared embedding/unembedding —
        # the MLP has neither, so this mode trains the pipelined
        # transformer LM (one encoder layer per chunk, tied table).
        from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
        from autodist_tpu.models.transformer import TransformerConfig

        cfg = TransformerConfig(
            vocab_size=args.vocab, hidden_size=HID, num_layers=C,
            num_heads=2, mlp_dim=FF, max_len=args.seq,
            dtype=jnp.float32, dropout_rate=0.0,
            attention_dropout_rate=0.0)
        trainable = make_pipeline_lm_trainable(
            cfg, optax.adam(1e-3), jax.random.PRNGKey(0))
        # activation hints so the cost model prices the epilogue
        # (peak-logits memory, psums) for the drift report below
        trainable.tokens_per_step = args.batch * args.seq
        trainable.act_bytes_per_token = float(4 * HID)

        def make_batch():
            x = r.randint(0, args.vocab,
                          (args.batch, args.seq)).astype(np.int32)
            y = np.concatenate([x[:, 1:], x[:, :1]], axis=1)
            return {"x": x, "y": y}
    else:
        trainable = PipelineTrainable(stage, stacked, head,
                                      optax.adam(1e-3), num_stages=C)
        target = r.randn(HID, HID).astype(np.float32) * 0.1

        def make_batch():
            x = r.randn(args.batch, HID).astype(np.float32)
            return {"x": x, "y": x @ target}
    overlap = None if args.comm_overlap == "off" else args.comm_overlap
    precision = None if args.collective_precision == "off" \
        else args.collective_precision
    zero_stage = max(args.zero_stage, 1 if args.zero1 else 0)
    builder = Pipeline(num_microbatches=args.microbatches,
                       virtual_stages=args.virtual_stages,
                       tensor_parallel=tp, comm_overlap=overlap,
                       vocab_parallel=args.vocab_parallel,
                       zero_stage=zero_stage, remat=args.remat,
                       collective_precision=precision)
    if args.accum_steps > 1:
        builder = GradAccumulation(builder, steps=args.accum_steps)

    from autodist_tpu import telemetry

    tel_dir = args.telemetry_dir or args.profile_dir
    if tel_dir:
        telemetry.configure(out_dir=tel_dir)
    if args.auto_search:
        # The search owns the factorization: the spec declares only the
        # topology (device count, slice count); every (dcn, data, pipe,
        # model) mesh the search elects carries in the winner
        # strategy's mesh_axes, which AutoDist honors at lowering.
        topo = {"num_devices": dp * pp * tp}
        if args.num_slices > 1:
            topo["num_slices"] = args.num_slices
        ad = AutoDist({"topology": topo}, builder)
        from autodist_tpu.simulator.search import search_strategies

        result = search_strategies(trainable, ad.resource_spec,
                                   global_batch=args.batch)
        print(result.report())
        if result.winner is None:
            raise SystemExit("auto-search: no candidate priced — "
                             "widen the SearchSpace or check the "
                             "topology")
        if not result.winner.cost.feasible:
            raise SystemExit(
                f"auto-search: best candidate {result.winner.name} "
                f"needs {result.winner.cost.mem_bytes_per_device / 1e9:.2f}"
                " GB/device — nothing fits in memory")
        strategy = result.winner.strategy
        # Lint/price against the winner's own factorization below.
        cost_spec = result.winner.spec
        runner = ad.build(trainable, strategy)
    else:
        ad = AutoDist({"topology": {"num_devices": dp * pp * tp},
                       "mesh": mesh}, builder)
        # The strategy is kept in hand (instead of letting build()
        # resolve it internally) so the drift report below can join the
        # cost model's prediction for exactly the program that ran.
        strategy = ad.build_or_load_strategy(trainable)
        cost_spec = ad.resource_spec
        runner = ad.build(trainable, strategy)

    # Plan lint at build: every silent degrade (ZeRO on a tp shard,
    # vocab no-op at tp=1, orphan precision slot, ...) surfaces as a
    # coded ADT diagnostic instead of a buried log line (the same rules
    # `tools/lint_strategy.py --zoo` gates CI on).
    from autodist_tpu import analysis

    plan_report = analysis.lint_plan(
        strategy, resource_spec=cost_spec, trainable=trainable,
        lowered=getattr(runner, "lowered", None))
    if plan_report.diagnostics:
        print(f"plan lint ({len(plan_report.errors)} error(s), "
              f"{len(plan_report.warnings)} warning(s)):")
        for diag in plan_report.sorted():
            print(f"  {diag}")
    else:
        print("plan lint: clean")

    if args.auto_search:
        print(f"auto-search winner: {result.winner.name} "
              f"(mesh {strategy.graph_config.mesh_axes})")
    else:
        print(f"pipe={pp} x virtual={args.virtual_stages} "
              f"(C={C} chunks), dp={dp}, tp={tp}, M={args.microbatches}, "
              f"comm_overlap={overlap}, "
              f"vocab_parallel={args.vocab_parallel}, "
              f"zero_stage={zero_stage}, "
              f"collective_precision={precision or 'fp32'}; "
              f"schedule bubble = "
              f"{bubble_fraction(args.microbatches, pp, args.virtual_stages):.3f}")

    from autodist_tpu.simulator.cost_model import CostModel

    # Predicted peak-logits buffer (the memory term vocab parallelism
    # divides by tp) rides every step record + a gauge, so a hardware
    # window's metrics.jsonl can join it against measured HBM.
    cost = CostModel(cost_spec).strategy_cost(trainable, strategy)
    peak_logits = cost.peak_logits_bytes or None
    if peak_logits:
        telemetry.get().gauge("memory/peak_logits_bytes").set(peak_logits)
    # The terms the ZeRO stages divide (stage 2: grads /n, stage 3:
    # params /n too) ride the run as gauges so a hardware window can
    # attribute the measured HBM delta between --zero-stage settings.
    if cost.param_shard_bytes:
        telemetry.get().gauge("memory/param_shard_bytes").set(
            cost.param_shard_bytes)
    if cost.grad_shard_bytes:
        telemetry.get().gauge("memory/grad_shard_bytes").set(
            cost.grad_shard_bytes)

    from contextlib import nullcontext

    from autodist_tpu.utils import profiling

    # warmup must leave at least one recorded step or the summary is all
    # None (short smoke runs with --profile-dir).
    timer = profiling.StepTimer(args.batch,
                                warmup=min(2, max(args.steps - 1, 0)))
    trace_cm = (profiling.trace(args.profile_dir) if args.profile_dir
                else nullcontext())
    import time

    controller = None
    injector = None
    if args.preempt_demo or args.chaos:
        import tempfile

        from autodist_tpu.checkpoint.saver import Saver
        from autodist_tpu.elastic import ElasticController
        from autodist_tpu.runtime.retry import RetryPolicy

        ckpt_dir = args.preempt_ckpt_dir or tempfile.mkdtemp(
            prefix="elastic_ckpt_")
        saver = Saver(ckpt_dir,
                      retry=RetryPolicy(max_attempts=2, base_delay_s=0.1,
                                        cap_delay_s=1.0),
                      degrade_on_failure=bool(args.chaos))
        controller = ElasticController(trainable, saver,
                                       global_batch=args.batch)
        controller.install(runner)
    if args.chaos:
        from autodist_tpu.runtime.faults import FaultInjector, load_fault_plan

        plan = load_fault_plan("@" + args.chaos)
        # Baseline checkpoint BEFORE any fault can fire: every degrade/
        # recovery path falls back to "the last good checkpoint", so a
        # chaos-armed run must have one from step 0.
        saver.save(runner)
        injector = FaultInjector(plan, self_target="chief", saver=saver)
        print(f"chaos plan armed: {[f.kind for f in plan.faults]} "
              f"(seed {plan.seed})")

    with trace_cm:
        for step in range(args.steps):
            if injector is not None:
                injector.maybe_fire(step)
                if controller.preempted:
                    survivors = max(jax.device_count() // 2, 1)
                    runner = controller.resume({"num_devices": survivors})
                    print(f"chaos preemption at step {step}: resumed on "
                          f"{survivors} device(s)")
                if step % 5 == 2:
                    # Periodic checkpoints give the armed
                    # ckpt_write_fail something to hit (and every later
                    # fault a fresher "last good" to fall back to); the
                    # cadence avoids the mid-run preemption step so the
                    # two saves never collide on one step number.
                    saver.save(runner)
            if args.preempt_demo and step == max(args.steps // 2, 1):
                # Simulated preemption: the SIGTERM handler writes a
                # blocking elastic checkpoint; the survivors (here:
                # half the devices) re-elect via the topology-aware
                # search and resume from the resharded checkpoint.
                import signal as _signal

                os.kill(os.getpid(), _signal.SIGTERM)
                assert controller.preempted
                survivors = max(jax.device_count() // 2, 1)
                runner = controller.resume({"num_devices": survivors})
                print(f"preemption at step {step}: resumed on "
                      f"{survivors} device(s), mesh "
                      f"{dict(runner.lowered.mesh.shape)}, strategy "
                      f"{controller.last_result.winner.name}")
            batch = make_batch()
            t_step = time.perf_counter()
            with timer:
                metrics = runner.step(batch)
                if tel_dir:
                    # Honest per-step timing needs the device work done;
                    # without a telemetry/profile sink, keep the
                    # dispatch async.
                    jax.block_until_ready(metrics)
            extra = {"peak_logits_bytes": peak_logits} if peak_logits \
                else {}
            if zero_stage:
                extra["zero_stage"] = zero_stage
                extra["param_shard_bytes"] = cost.param_shard_bytes
                extra["grad_shard_bytes"] = cost.grad_shard_bytes
            telemetry.record_step(step=step,
                                  duration_s=time.perf_counter() - t_step,
                                  examples=args.batch, **extra)
            if step % 5 == 0 or step == args.steps - 1:
                print(f"step {step}: "
                      f"loss={float(np.asarray(metrics['loss'])):.5f}")

    summary = timer.summary()
    if tel_dir:
        from autodist_tpu.utils.profiling import memory_summary

        # The manifest must describe the program that RAN: under
        # --auto-search the winner's Strategy-IR knobs, not the CLI
        # flags (which only sized the topology there).
        if args.auto_search:
            par = strategy.graph_config.parallel or {}
            knobs = dict(
                microbatches=int(par.get("num_microbatches", 1) or 1),
                virtual_stages=int(par.get("virtual_stages", 1) or 1),
                comm_overlap=par.get("comm_overlap") or None,
                tensor_parallel=int(par.get("tensor_parallel", 1) or 1),
                zero_stage=int(par.get("zero_stage", 0) or 0),
                vocab_parallel=bool(par.get("vocab_parallel", False)),
                remat=bool(par.get("remat", False)))
        else:
            knobs = dict(microbatches=args.microbatches,
                         virtual_stages=args.virtual_stages,
                         comm_overlap=overlap, tensor_parallel=tp,
                         zero_stage=zero_stage,
                         vocab_parallel=args.vocab_parallel,
                         remat=args.remat)
        telemetry.annotate(mesh=dict(strategy.graph_config.mesh_axes),
                           auto_search=args.auto_search,
                           batch=args.batch, **knobs,
                           # The normalized per-boundary dict, so
                           # `tools/telemetry_report.py --check` can
                           # gate the precision/<boundary>_bits gauges
                           # the lowering emitted against it.
                           collective_precision=dict(
                               strategy.graph_config.precision),
                           peak_logits_bytes=peak_logits,
                           param_shard_bytes=cost.param_shard_bytes,
                           grad_shard_bytes=cost.grad_shard_bytes,
                           step_summary=summary)
        report = telemetry.drift_report(
            strategy, CostModel(cost_spec),
            {"step": summary, "memory": memory_summary(),
             "examples_per_sec": summary.get("examples_per_sec")},
            trainable=trainable)
        paths = telemetry.flush()
        print(f"telemetry artifacts in {tel_dir}: "
              f"{sorted(os.path.basename(p) for p in paths.values())}")
        ratios = {k: round(v, 3) for k, v in report["ratios"].items()}
        print(f"drift (measured/predicted): {ratios}")
        if cost.wire_bytes_saved:
            print(f"precision policy: predicted "
                  f"{cost.wire_bytes_saved / 1e6:.3f} MB/step saved on "
                  f"the wire vs fp32 (q/dq compute charged: "
                  f"{cost.quant_dq_time_s * 1e6:.1f} us/step)")
    mean = summary["mean_ms"]
    if args.profile_dir and mean is not None:
        print(f"xplane trace in {args.profile_dir} "
              f"(mean {mean:.2f} ms/step)")


if __name__ == "__main__":
    main()
