"""Pipeline-parallel training through the Strategy IR.

Beyond reference parity (the reference declared pipeline parallelism
future work, ``docs/design/architecture.rst:49-51``): a stage-stacked
MLP trained over the ``pipe`` mesh axis, GPipe or Megatron-interleaved
(``--virtual-stages 2``), with gradient accumulation composing on top.

    python examples/pipeline_train.py --steps 20
    python examples/pipeline_train.py --virtual-stages 2 --microbatches 4
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--stages", type=int, default=4,
                    help="pipe-axis devices")
    ap.add_argument("--virtual-stages", type=int, default=1)
    ap.add_argument("--microbatches", type=int, default=2)
    ap.add_argument("--accum-steps", type=int, default=1)
    ap.add_argument("--zero1", action="store_true",
                    help="ZeRO-1: shard optimizer state over the data "
                         "axes (stage vars) / pipe x data (shared vars)")
    ap.add_argument("--remat", action="store_true",
                    help="jax.checkpoint each chunk (memory for compute)")
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from autodist_tpu import AutoDist, PipelineTrainable
    from autodist_tpu.parallel.pipeline import bubble_fraction
    from autodist_tpu.strategy.builders import GradAccumulation
    from autodist_tpu.strategy.parallel_builders import Pipeline

    n = jax.device_count()
    pp = min(args.stages, n)
    dp = n // pp
    C = pp * args.virtual_stages
    HID = args.hidden
    r = np.random.RandomState(0)
    stacked = {"w": jnp.asarray(r.randn(C, HID, HID) * (2.0 / HID) ** 0.5,
                                jnp.float32),
               "b": jnp.zeros((C, HID), jnp.float32)}

    def stage(p, x):
        return jax.nn.relu(x @ p["w"] + p["b"])

    def head(outputs, batch):
        loss = jnp.mean((outputs - batch["y"]) ** 2)
        return loss, {}

    trainable = PipelineTrainable(stage, stacked, head, optax.adam(1e-3),
                                  num_stages=C)
    builder = Pipeline(num_microbatches=args.microbatches,
                       virtual_stages=args.virtual_stages,
                       zero1=args.zero1, remat=args.remat)
    if args.accum_steps > 1:
        builder = GradAccumulation(builder, steps=args.accum_steps)
    mesh = {"data": dp, "pipe": pp} if dp > 1 else {"pipe": pp}
    runner = AutoDist({"topology": {"num_devices": dp * pp}, "mesh": mesh},
                      builder).build(trainable)

    print(f"pipe={pp} x virtual={args.virtual_stages} "
          f"(C={C} chunks), dp={dp}, M={args.microbatches}; "
          f"schedule bubble = "
          f"{bubble_fraction(args.microbatches, pp, args.virtual_stages):.3f}")
    target = r.randn(HID, HID).astype(np.float32) * 0.1
    for step in range(args.steps):
        x = r.randn(args.batch, HID).astype(np.float32)
        batch = {"x": x, "y": x @ target}
        metrics = runner.step(batch)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss={float(np.asarray(metrics['loss'])):.5f}")


if __name__ == "__main__":
    main()
