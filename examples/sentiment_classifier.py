"""Sentiment classifier with a sharded embedding table
(≙ reference ``examples/sentiment_classifier.py``, which used
PartitionedPS to shard its embedding).

The embedding is the sparse/sharded path: under ``PartitionedPS`` or
``Parallax`` its rows are split across the data axis and synchronized
with the sparse gather/scatter lowering; the dense classifier head is
replicated.

    python examples/sentiment_classifier.py --steps 30
"""
import argparse

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist, Trainable


def make_trainable(vocab_size=20_000, embed_dim=64, hidden=64, seq_len=64):
    rng = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(rng, 3)
    params = {
        "embedding": jax.random.normal(k1, (vocab_size, embed_dim)) * 0.05,
        "dense": {"w": jax.random.normal(k2, (embed_dim, hidden)) * 0.1,
                  "b": jnp.zeros((hidden,))},
        "head": {"w": jax.random.normal(k3, (hidden, 2)) * 0.1,
                 "b": jnp.zeros((2,))},
    }

    def loss_fn(p, batch):
        emb = p["embedding"][batch["tokens"]]          # [B, L, E] gather
        pooled = emb.mean(axis=1)                      # mean-pool
        h = jax.nn.relu(pooled @ p["dense"]["w"] + p["dense"]["b"])
        logits = h @ p["head"]["w"] + p["head"]["b"]
        labels = batch["labels"]
        logp = jax.nn.log_softmax(logits)
        loss = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
        acc = (logits.argmax(-1) == labels).mean()
        return loss, {"accuracy": acc}

    return Trainable.from_loss_fn(loss_fn, params, optax.adagrad(0.1),
                                  sparse_params=("embedding",),
                                  name="sentiment")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--strategy", default="PartitionedPS")
    ap.add_argument("--vocab-size", type=int, default=20_000)
    ap.add_argument("--seq-len", type=int, default=64)
    args = ap.parse_args()

    trainable = make_trainable(vocab_size=args.vocab_size,
                               seq_len=args.seq_len)
    runner = AutoDist({}, args.strategy).build(trainable)

    rng = np.random.RandomState(0)
    for step in range(args.steps):
        tokens = rng.randint(0, args.vocab_size,
                             (args.batch_size, args.seq_len)).astype(np.int32)
        # Synthetic rule: label = parity of the first token.
        labels = (tokens[:, 0] % 2).astype(np.int32)
        metrics = runner.step({"tokens": tokens, "labels": labels})
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step}: loss={float(np.asarray(metrics['loss'])):.4f} "
                  f"acc={float(np.asarray(metrics['accuracy'])):.3f}")


if __name__ == "__main__":
    main()
