"""Batched inference through the serving engine (ROADMAP: the serving
path on the same Strategy IR).

Drives the pipelined transformer LM family through
``autodist_tpu/serving/``: a continuous batcher admits synthetic
requests into TP-sharded KV-cache slots, prefill emits each request's
first token, and fused multi-token decode windows stream the rest —
with TTFT / inter-token / tokens-per-sec telemetry through the
``telemetry/`` sink.

    python examples/serve.py --requests 8 --max-new 32
    python examples/serve.py --tensor-parallel 2 --vocab-parallel \
        --vocab 513                       # odd vocab: the zero-pad path
    python examples/serve.py --train-steps 4 --tensor-parallel 2 \
        --telemetry-dir /tmp/serve_run    # serve a freshly trained runner
    python examples/serve.py --smoke      # tier-1 CI subprocess

``--train-steps > 0`` first trains the LM through the ``Pipeline``
strategy on the visible mesh and serves ``runner.get_params()`` —
the live-runner path; otherwise the engine serves the freshly
initialized parameters directly.  ``--artifact DIR`` round-trips
through ``checkpoint/export.py`` instead (export, reload, serve).
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _serve_fleet(args, cfg, trainable):
    """The ``--replicas N`` path: serve the request mix through a
    fleet behind the router, then kill one replica mid-run to show the
    failover path re-homing its in-flight requests (the fleet lint is
    printed first, the launch-gate habit).

    ``--processes`` runs the same mix against REAL replica processes
    (:class:`ProcessFleet` over the tiny shared worker engine — the
    model-size flags don't ship to workers) and stitches every
    process's telemetry shard into ONE ``trace.json``: open it in
    Perfetto and each request's distributed trace reads across the
    chief's dispatch instants and both workers' prefill/decode spans."""
    import time

    import numpy as np

    from autodist_tpu import serving, telemetry
    from autodist_tpu.resource import ResourceSpec

    if args.processes:
        # The tiny worker engine's admission budget, not the CLI's.
        args.vocab, args.max_new = 33, min(args.max_new, 6)
        prompt_cap = 16 - args.max_new
        fleet = serving.ProcessFleet(
            {"factory": "autodist_tpu.serving.remote:"
                        "tiny_engine_factory"},
            config=serving.FleetConfig(replicas=args.replicas),
            telemetry_dir=args.telemetry_dir)
    else:
        prompt_cap = max(args.prefill_len - args.max_new, 1)

        def factory():
            return serving.ServingEngine(
                cfg, trainable.params,
                tensor_parallel=args.tensor_parallel,
                vocab_parallel=args.vocab_parallel,
                num_slots=args.slots,
                max_len=args.max_len, prefill_len=args.prefill_len,
                decode_steps=args.decode_steps)

        fleet = serving.ServingFleet(factory, replicas=args.replicas)
    report = fleet.lint(resource_spec=ResourceSpec(
        {"topology": {"num_devices":
                      max(args.replicas * args.tensor_parallel, 1)}}))
    print(report.render("fleet lint") if not report.ok
          else "fleet lint: clean")
    router = serving.Router(fleet)
    r = np.random.RandomState(7)
    t0 = time.perf_counter()
    rids = []
    for _ in range(args.requests):
        plen = int(r.randint(1, max(prompt_cap, 1) + 1))
        prompt = r.randint(0, args.vocab, (plen,)).tolist()
        rids.append(router.submit(prompt, max_new_tokens=args.max_new))
    router.step()
    if fleet.has_replica("replica-0"):
        fleet.inject("replica-0", "crash")   # the failover demo
    done = router.run()
    wall = time.perf_counter() - t0
    tokens = sum(len(c.tokens) for c in done.values())
    failovers = sum(c.failovers for c in done.values())
    print(f"fleet served {len(done)} requests / {tokens} tokens in "
          f"{wall:.2f}s across {args.replicas} replicas "
          f"({failovers} failover(s) after the mid-run replica kill); "
          f"replicas: "
          f"{[(x.name, x.incarnation, x.state) for x in fleet.replicas]}")
    if args.telemetry_dir:
        telemetry.annotate(serve=True, replicas=args.replicas,
                           requests=len(done), tokens=tokens)
        telemetry.flush()
    if args.processes:
        # Workers flush their telemetry shards on the stop op: close
        # first and wait for the processes to exit, so the stitch
        # below sees every shard.
        fleet.close()
        deadline = time.perf_counter() + 30.0
        while any(x.handle.running for x in fleet.replicas) \
                and time.perf_counter() < deadline:
            time.sleep(0.05)
    stitched = None
    if args.telemetry_dir:
        stitched = telemetry.stitch_trace(args.telemetry_dir)
        traced = {t for ev in stitched["traceEvents"]
                  for t in telemetry.tracing.event_trace_ids(ev)}
        print(f"stitched trace.json: "
              f"{len(stitched['traceEvents'])} events from "
              f"{stitched['stitched']['shards']} process shard(s) "
              f"(pids {stitched['stitched']['pids']}), "
              f"{len(traced)} traced request(s)")
    if args.smoke:
        assert len(done) == args.requests
        assert all(c.finish_reason in ("eos", "max_tokens", "max_len")
                   for c in done.values())
        assert all(c.trace_id for c in done.values())
        if not args.processes:
            acc = fleet.block_accounting()
            assert all(u == 0 for _, u, _ in acc.values()), acc
        if stitched is not None:
            traced = {t for ev in stitched["traceEvents"]
                      for t in telemetry.tracing.event_trace_ids(ev)}
            assert all(c.trace_id in traced for c in done.values()), \
                "a completion's trace id resolves to no stitched event"
            if args.processes:
                assert len(stitched["stitched"]["pids"]) >= 2, \
                    stitched["stitched"]
        print("fleet serve smoke ok")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4,
                    help="continuous-batching slots (the decode batch)")
    ap.add_argument("--decode-steps", type=int, default=8,
                    help="tokens per fused decode dispatch (K)")
    ap.add_argument("--prefill-len", type=int, default=16,
                    help="prompt bucket (prompts pad up to it)")
    ap.add_argument("--max-len", type=int, default=64,
                    help="KV-cache capacity per slot")
    ap.add_argument("--tensor-parallel", type=int, default=1)
    ap.add_argument("--vocab-parallel", action="store_true",
                    help="shard the tied unembedding's vocab dim over "
                         "the model axis (with --tensor-parallel > 1); "
                         "decode never materializes full-vocab logits")
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--train-steps", type=int, default=0,
                    help="train the LM this many steps first and serve "
                         "the live runner's parameters")
    ap.add_argument("--artifact", default=None,
                    help="export to this directory and serve the "
                         "reloaded artifact (the checkpoint/export.py "
                         "round trip)")
    ap.add_argument("--telemetry-dir", default=None,
                    help="flush serving telemetry here (per-request "
                         "serve records, TTFT/inter-token histograms, "
                         "manifest)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="> 1 serves through a ServingFleet + Router "
                         "(N replica engine+batcher groups, queue-"
                         "depth-aware dispatch, failover/hedging) and "
                         "prints the fleet-objective ranking + a "
                         "mid-run replica-kill failover demo")
    ap.add_argument("--processes", action="store_true",
                    help="with --replicas > 1: real replica worker "
                         "processes (ProcessFleet over the tiny shared "
                         "worker engine) — with --telemetry-dir the "
                         "per-process telemetry shards are stitched "
                         "into ONE distributed trace.json")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI preset: shrink everything and assert "
                         "the serve loop end to end")
    args = ap.parse_args()
    if args.smoke:
        args.requests = min(args.requests, 4)
        args.max_new = 6
        args.slots = 2
        args.decode_steps = 3
        args.prefill_len = 8
        args.max_len = 24
        args.vocab = 33 if args.vocab_parallel else 32
        args.hidden = 16
        args.layers = 2

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from autodist_tpu import serving, telemetry
    from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
    from autodist_tpu.models.transformer import TransformerConfig
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator import rank_serving

    if args.telemetry_dir:
        telemetry.configure(out_dir=args.telemetry_dir)

    cfg = TransformerConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_heads=args.heads,
        mlp_dim=2 * args.hidden, max_len=args.max_len,
        dtype=jnp.float32, dropout_rate=0.0, attention_dropout_rate=0.0)
    trainable = make_pipeline_lm_trainable(
        cfg, optax.adam(1e-3), jax.random.PRNGKey(0))

    # AutoStrategy's serving objective: rank the (tp, vocab_parallel)
    # zoo by predicted per-token latency before committing devices.
    rs = ResourceSpec({"topology": {"num_devices": jax.device_count()}})
    ranked = rank_serving(trainable, rs, batch_slots=args.slots,
                          max_len=args.max_len)
    print("serving configs by predicted token latency:")
    for cand, cost in ranked[:4]:
        print(f"  tp={cand['tensor_parallel']} "
              f"vocab_parallel={cand['vocab_parallel']} "
              f"kv={cand.get('kv_layout', 'dense')}: "
              f"{cost.token_time_s * 1e6:.2f} us/token "
              f"(comm {cost.comm_time_s * 1e6:.2f})")

    if args.replicas > 1:
        # The fleet objective: rank (replicas x tp x kv_layout) by
        # aggregate throughput for a short-request mix before
        # committing devices (replicas priced across DCN, tp held
        # within a slice's ICI).
        fleet_ranked = rank_serving(
            trainable, rs, objective="fleet", batch_slots=args.slots,
            max_len=args.max_len, mean_request_len=args.max_new * 2)
        print("fleet shapes by predicted aggregate throughput:")
        for cand, cost in fleet_ranked[:4]:
            print(f"  replicas={cand.get('replicas', 1)} "
                  f"tp={cand['tensor_parallel']} "
                  f"kv={cand.get('kv_layout', 'dense')}: "
                  f"fleet_score={cost.fleet_score:.3e}")
        return _serve_fleet(args, cfg, trainable)

    strategy = None
    if args.train_steps > 0:
        from autodist_tpu import AutoDist
        from autodist_tpu.resource import factor_3d

        n = jax.device_count()
        tp = args.tensor_parallel
        pp = cfg.num_layers
        dp = n // (pp * tp)
        if dp < 1:
            raise SystemExit(
                f"--train-steps needs layers x tp <= devices "
                f"({pp} x {tp} > {n})")
        ad = AutoDist({"topology": {"num_devices": dp * pp * tp},
                       "mesh": factor_3d(dp * pp * tp, pipe=pp, model=tp,
                                         data=dp)},
                      "Pipeline", num_microbatches=2, tensor_parallel=tp,
                      vocab_parallel=args.vocab_parallel)
        strategy = ad.build_or_load_strategy(trainable)
        runner = ad.build(trainable, strategy)
        r = np.random.RandomState(0)
        for _ in range(args.train_steps):
            x = r.randint(0, args.vocab, (8, 8)).astype(np.int32)
            runner.step({"x": x,
                         "y": np.concatenate([x[:, 1:], x[:, :1]], 1)})
        source = {"runner": runner}
    else:
        source = {"params": trainable.params}

    engine_kw = dict(tensor_parallel=args.tensor_parallel,
                     vocab_parallel=args.vocab_parallel,
                     num_slots=args.slots, max_len=args.max_len,
                     prefill_len=args.prefill_len,
                     decode_steps=args.decode_steps)
    if args.artifact:
        # Round-trip through the export artifact: params at logical
        # names/unpadded shapes + a real full-recompute apply program
        # (the artifact stays servable WITHOUT this framework, the
        # export_model contract), then serve the reloaded params.
        from autodist_tpu.checkpoint import export_model
        from autodist_tpu.models.pipeline_lm import sequential_logits

        params = source["runner"].get_params() if "runner" in source \
            else source["params"]

        def apply_fn(p, tokens):
            return sequential_logits(cfg, p, tokens)

        sample = np.zeros((1, args.prefill_len), np.int32)
        export_model(args.artifact, apply_fn, params, [sample],
                     platforms=None)
        engine = serving.serve(cfg, artifact=args.artifact,
                               strategy=strategy, **engine_kw)
    else:
        engine = serving.serve(cfg, strategy=strategy, **source,
                               **engine_kw)

    batcher = serving.ContinuousBatcher(engine)
    r = np.random.RandomState(7)
    t0 = time.perf_counter()
    rids = []
    for i in range(args.requests):
        plen = int(r.randint(1, args.prefill_len + 1))
        prompt = r.randint(0, args.vocab, (plen,)).tolist()
        rids.append(batcher.submit(prompt, max_new_tokens=args.max_new))
    done = batcher.run()
    wall = time.perf_counter() - t0

    total_tokens = sum(len(c.tokens) for c in done.values())
    ttfts = sorted(c.ttft_s for c in done.values())
    print(f"served {len(done)} requests / {total_tokens} tokens in "
          f"{wall:.2f}s ({total_tokens / wall:.1f} tokens/s aggregate), "
          f"ttft p50 {ttfts[len(ttfts) // 2] * 1e3:.1f} ms "
          f"[tp={args.tensor_parallel}, "
          f"vocab_parallel={args.vocab_parallel}, slots={args.slots}, "
          f"K={args.decode_steps}]")

    if args.telemetry_dir:
        telemetry.annotate(serve=True, slots=args.slots,
                           decode_steps=args.decode_steps,
                           tensor_parallel=args.tensor_parallel,
                           vocab_parallel=args.vocab_parallel,
                           requests=len(done), tokens=total_tokens)
        paths = telemetry.flush()
        print(f"telemetry artifacts in {args.telemetry_dir}: "
              f"{sorted(os.path.basename(p) for p in paths.values())}")

    if args.smoke:
        assert len(done) == args.requests, (len(done), args.requests)
        assert all(1 <= len(c.tokens) <= args.max_new
                   for c in done.values())
        assert all(0 <= t < args.vocab for c in done.values()
                   for t in c.tokens), "sampled a padded vocab row"
        print("serve smoke ok")


if __name__ == "__main__":
    main()
