"""Test harness: a simulated 8-device CPU mesh.

The reference tested multi-worker semantics against real TF servers over
SSH (SURVEY.md §4); this build exploits what the reference lacked — a
simulated mesh — so multi-"host" semantics are unit-testable without
hardware.
"""
import os

# Must run before the first jax backend initialization.
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import ast

import pytest

# Modules that only work against real TPU silicon (or its libraries).
# A test module importing one of these at top level would crash — or
# silently hang on a tunnel client — during CPU collection, so every
# test in such a module must be tier-2 (``slow``); collection itself
# fails otherwise, naming the offenders.  Static top-level imports only:
# an import buried inside a function is the test's own runtime gate.
TPU_ONLY_IMPORT_PREFIXES = (
    "jax.experimental.pallas.tpu",
    "jax.experimental.mosaic",
    "jax._src.pallas.mosaic",
    "pltpu",
    "libtpu",
    "torch_xla",
    # the repo's own Pallas-kernel modules: CPU runs them in interpret
    # mode, which is minutes-per-test — tier-2 by policy
    "autodist_tpu.ops.flash_attention",
)


def _iter_module_level(node):
    """AST nodes outside function bodies (a buried import is the test's
    own runtime gate, not a collection hazard)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from _iter_module_level(child)


def _tpu_only_imports(path: str) -> set:
    try:
        tree = ast.parse(open(path).read(), filename=path)
    except (OSError, SyntaxError):
        return set()
    found = set()
    for node in _iter_module_level(tree):
        names = []
        if isinstance(node, ast.Import):
            names = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module:
            names = [node.module] + [f"{node.module}.{a.name}"
                                     for a in node.names]
        for name in names:
            for prefix in TPU_ONLY_IMPORT_PREFIXES:
                if name == prefix or name.startswith(prefix + "."):
                    found.add(prefix)
    return found


def pytest_collection_modifyitems(config, items):
    cache: dict = {}
    offenders: dict = {}
    for item in items:
        path = str(getattr(item, "fspath", ""))
        if not path:
            continue
        if path not in cache:
            cache[path] = _tpu_only_imports(path)
        if cache[path] and item.get_closest_marker("slow") is None:
            offenders.setdefault(path, set()).update(cache[path])
    if offenders:
        lines = [f"  {p}: imports {sorted(mods)} but has unmarked tests"
                 for p, mods in sorted(offenders.items())]
        raise pytest.UsageError(
            "TPU-only imports in tier-1 test modules (mark the tests "
            "@pytest.mark.slow or move the import into the test):\n"
            + "\n".join(lines))
