"""Test harness: a simulated 8-device CPU mesh.

The reference tested multi-worker semantics against real TF servers over
SSH (SURVEY.md §4); this build exploits what the reference lacked — a
simulated mesh — so multi-"host" semantics are unit-testable without
hardware.
"""
import os

# Must run before the first jax backend initialization.
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
