"""The fused quantized dispatch/combine ring (PR 18).

Interpreter-mode goldens for ``kernel/pallas/a2a_ring.py`` against its
arithmetic mirror (bit-exact: per-chunk scales, own chunk never on the
wire) and the exact fp32 ``lax.all_to_all`` (one int8 rounding per
off-device chunk), across edge shapes: one row per peer (split dim ==
ring size), non-dividing split dims rejected loudly, the backward
riding the transposed ring, and GShard capacity-overflow drops staying
exact zeros through the quantized hops.

Kernel modules are imported inside tests (conftest guard: Pallas
modules are never top-level imports in a tier-1 module); shapes stay
tiny so the interpreter runs in seconds.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

pytestmark = pytest.mark.slow


def _ring_fn(n, split_axis=0, concat_axis=0, grad=False):
    from autodist_tpu.kernel.pallas.a2a_ring import (
        quantized_ring_all_to_all, ring_dispatch)

    mesh = Mesh(np.array(jax.devices()[:n]), ("expert",))
    if grad:
        def run(x, ct):
            y, vjp = jax.vjp(
                lambda a: ring_dispatch(a, "expert", split_axis,
                                        concat_axis), x)
            (gx,) = vjp(ct)
            return y, gx
        return jax.jit(jax.shard_map(
            run, mesh=mesh, in_specs=(P("expert"), P("expert")),
            out_specs=(P("expert"), P("expert")), check_vma=False))
    return jax.jit(jax.shard_map(
        lambda x: quantized_ring_all_to_all(
            x, "expert", split_axis=split_axis, concat_axis=concat_axis),
        mesh=mesh, in_specs=P("expert"), out_specs=P("expert"),
        check_vma=False))


@pytest.mark.parametrize("n,rows,cols", [(2, 4, 16), (4, 8, 5),
                                         (4, 4, 16)])
def test_a2a_ring_matches_reference(n, rows, cols):
    """Bit-exact vs the host mirror — per-chunk abs-max scales, the own
    chunk exact — including the one-row-per-peer edge (rows == n)."""
    from autodist_tpu.kernel.pallas.a2a_ring import reference_ring_all_to_all

    r = np.random.RandomState(0)
    shards = [jnp.asarray(r.randn(rows, cols), jnp.float32)
              for _ in range(n)]
    got = _ring_fn(n)(jnp.concatenate(shards, 0))
    refs = reference_ring_all_to_all(shards, split_axis=0, concat_axis=0)
    for i in range(n):
        np.testing.assert_array_equal(
            np.asarray(got[i * rows:(i + 1) * rows]), np.asarray(refs[i]))


def test_a2a_ring_within_int8_of_exact():
    """One int8 rounding per off-device chunk vs the exact all_to_all;
    the own chunk agrees exactly."""
    n, rows, cols = 4, 8, 16
    r = np.random.RandomState(1)
    shards = [jnp.asarray(r.randn(rows, cols), jnp.float32)
              for _ in range(n)]
    x = jnp.concatenate(shards, 0)
    got = np.asarray(_ring_fn(n)(x))

    mesh = Mesh(np.array(jax.devices()[:n]), ("expert",))
    exact = np.asarray(jax.jit(jax.shard_map(
        lambda a: jax.lax.all_to_all(a, "expert", 0, 0, tiled=True),
        mesh=mesh, in_specs=P("expert"), out_specs=P("expert"),
        check_vma=False))(x))
    per_chunk = rows // n
    for dev in range(n):
        blk = slice(dev * rows, (dev + 1) * rows)
        for src in range(n):
            sub = slice(dev * rows + src * per_chunk,
                        dev * rows + (src + 1) * per_chunk)
            chunk = exact[sub]
            tol = 0.0 if src == dev \
                else float(np.abs(chunk).max()) / 127.0 + 1e-7
            np.testing.assert_allclose(got[sub], chunk, atol=tol)
        assert np.abs(got[blk] - exact[blk]).max() > 0  # wire was s8


def test_a2a_ring_rejects_non_dividing_split():
    """A split dim the ring size doesn't divide fails loudly at trace
    time, not with silent truncation."""
    r = np.random.RandomState(2)
    x = jnp.asarray(r.randn(4 * 6, 8), jnp.float32)  # 6 rows/dev, n=4
    with pytest.raises(ValueError, match="must divide the 4-way"):
        _ring_fn(4)(x)


def test_ring_dispatch_backward_is_transposed_ring():
    """The custom-vjp backward is the ring with split/concat swapped —
    bit-exact vs the host mirror of the transposed exchange."""
    from autodist_tpu.kernel.pallas.a2a_ring import reference_ring_all_to_all

    n, rows, cols = 4, 8, 6
    r = np.random.RandomState(3)
    x_shards = [jnp.asarray(r.randn(rows, cols), jnp.float32)
                for _ in range(n)]
    # forward: split 0, concat 1 -> per-device (rows/n, n*cols);
    # cotangent rides the ring back with the axes swapped.
    ct_shards = [jnp.asarray(r.randn(rows // n, n * cols), jnp.float32)
                 for _ in range(n)]
    y, gx = _ring_fn(n, split_axis=0, concat_axis=1, grad=True)(
        jnp.concatenate(x_shards, 0), jnp.concatenate(ct_shards, 0))

    y_ref = reference_ring_all_to_all(x_shards, split_axis=0,
                                      concat_axis=1)
    gx_ref = reference_ring_all_to_all(ct_shards, split_axis=1,
                                       concat_axis=0)
    pr, gr = rows // n, rows
    for i in range(n):
        np.testing.assert_array_equal(
            np.asarray(y[i * pr:(i + 1) * pr]), np.asarray(y_ref[i]))
        np.testing.assert_array_equal(
            np.asarray(gx[i * gr:(i + 1) * gr]), np.asarray(gx_ref[i]))


def test_a2a_ring_capacity_overflow_drops_stay_exact_zero():
    """GShard overflow drops ride THROUGH the quantized ring unchanged:
    routing is decided in fp32 before the wire, so the kernel path drops
    exactly the tokens the dense reference drops, and a fully-dropped
    token's output row stays exactly zero (zero blocks quantize to
    exact zeros through the scale floor)."""
    from autodist_tpu.parallel.moe import (dense_moe_reference,
                                           expert_parallel_ffn)

    n, G, E, M, H = 4, 8, 4, 16, 32
    r = np.random.RandomState(4)
    # Adversarial gate: every token's top-2 is experts {0, 1} (tokens
    # carry a constant first feature), so capacity 4 < G drops the
    # overflow outright.
    gate_w = jnp.asarray(r.randn(M, E) * 0.01, jnp.float32)
    gate_w = gate_w.at[0, 0].set(10.0).at[0, 1].set(5.0)
    wi = jnp.asarray(r.randn(E, M, H) * 0.2, jnp.float32)
    wo = jnp.asarray(r.randn(E, H, M) * 0.2, jnp.float32)
    tokens = jnp.asarray(r.randn(n * G, M), jnp.float32)
    tokens = tokens.at[:, 0].set(1.0)

    mesh = Mesh(np.array(jax.devices()[:n]), ("expert",))
    fn = jax.jit(jax.shard_map(
        lambda t, g, a, b: expert_parallel_ffn(
            t, g, a, b, capacity_factor=1.0, a2a_precision="int8",
            a2a_kernel=True)[0],
        mesh=mesh,
        in_specs=(P("expert"), P(), P("expert"), P("expert")),
        out_specs=P("expert"), check_vma=False))
    out = np.asarray(fn(tokens, gate_w, wi, wo))

    capacity = max(int(np.ceil(2 * G * 1.0 / E)), 4)
    assert capacity < G  # the overflow is real
    dropped_any = False
    for p in range(n):
        shard = tokens[p * G:(p + 1) * G]
        ref = np.asarray(dense_moe_reference(shard, gate_w, wi, wo,
                                             capacity)[0])
        got = out[p * G:(p + 1) * G]
        dropped = ~np.any(ref != 0.0, axis=1)
        dropped_any |= bool(dropped.any())
        # dropped rows: exact zeros on BOTH paths; surviving rows:
        # within the quantized wire's tolerance of the fp32 reference.
        np.testing.assert_array_equal(got[dropped], 0.0)
        scale = np.abs(ref).max()
        np.testing.assert_allclose(got[~dropped], ref[~dropped],
                                   atol=0.05 * scale)
    assert dropped_any


def test_expert_count_must_divide_axis():
    """num_experts % expert-axis != 0 is rejected at build time with the
    shape in the message, not lowered into a ragged shard."""
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.models.moe_transformer import (MoeConfig,
                                                     make_moe_lm_trainable)

    cfg = MoeConfig(vocab_size=32, hidden_size=16, num_layers=1,
                    num_heads=2, expert_hidden=32, num_experts=6,
                    max_len=8, dtype=jnp.float32)
    tr = make_moe_lm_trainable(cfg, optax.adam(1e-2),
                               jax.random.PRNGKey(0), batch_size=4,
                               seq_len=8)
    spec = {"topology": {"platform": "cpu", "num_devices": 4},
            "mesh": {"expert": 4}}
    with pytest.raises(ValueError, match="num_experts=6 must divide"):
        AutoDist(spec, "ExpertParallel", num_experts=6).build(tr)
