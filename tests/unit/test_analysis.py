"""The static-analysis subsystem (autodist_tpu/analysis/): diagnostics
vocabulary, parsed-HLO facts extraction, plan-lint rules over the
Strategy IR, program-lint rules over compiled programs, and — the
falsifiability backbone — the mutation matrix proving every shipped
rule fires on its seeded violation and stays silent on the honest
artifact.

Program-mutation tests compile from the same memoized corpus the HLO
probes use (autodist_tpu/analysis/programs.py), so within one pytest
process each 8-device program compiles once for probes, rules, and
mutations alike.
"""
import json
import os

import pytest

from autodist_tpu.analysis import (CODES, Diagnostic, LintReport,
                                   ProgramFacts, lint_plan, lint_program,
                                   rules_for_decode, rules_for_strategy)
from autodist_tpu.analysis import program_rules as R
from autodist_tpu.analysis.diagnostics import ERROR, WARNING
from autodist_tpu.analysis.mutations import (_pipeline_fixture,
                                             all_mutations,
                                             run_mutations)

DATA = os.path.join(os.path.dirname(__file__), "data")


# --------------------------------------------------------------------------- #
# Diagnostics vocabulary
# --------------------------------------------------------------------------- #
def test_diagnostic_codes_are_registered_and_defaulted():
    d = Diagnostic("ADT105", "boom", where="prog")
    assert d.severity == ERROR           # the code's registered default
    assert "ADT105" in str(d) and "[prog]" in str(d)
    with pytest.raises(KeyError):
        Diagnostic("ADT999", "unregistered")


def test_lint_report_severity_accessors_and_json():
    rep = LintReport([Diagnostic("ADT105", "e"),
                      Diagnostic("ADT030", "w")])
    assert len(rep.errors) == 1 and len(rep.warnings) == 1
    assert not rep.ok
    payload = json.loads(rep.to_json())
    assert payload["errors"] == 1 and payload["ok"] is False
    assert payload["diagnostics"][0]["code"] == "ADT105"  # errors first


def test_every_code_has_severity_and_summary():
    for code, (severity, summary) in CODES.items():
        assert severity in (ERROR, WARNING, "info"), code
        assert summary, code


# --------------------------------------------------------------------------- #
# Facts extraction on synthetic HLO
# --------------------------------------------------------------------------- #
_SYNTHETIC = """
HloModule m, input_output_alias={ {0}: (0, {}, may-alias) }
%body (p: f32[8]) -> f32[8] {
  %p = f32[8]{0} parameter(0)
}
ENTRY %main (Arg_0: f32[2,116], Arg_1: s32[8]) -> (f32[2,116]) {
  %w = f32[2,116]{1,0} while(f32[2,116]{1,0} %init), body=%body
  %ar = f16[64]{0} all-reduce(f16[64]{0} %x), replica_groups={{0,1}}
  %sc = f32[] all-reduce(f32[] %s), to_apply=%max
  %ag = (s8[4]{0}, s8[8]{0}) all-gather-start(s8[4]{0} %y), dimensions={0}
  %ob = f32[8]{0} opt-barrier(f32[8]{0} %z)
  %snd = f32[8]{0} send(f32[8]{0} %z, token[] %tk), channel_id=3
  %dus = f32[3,57,8]{2,1,0} dynamic-update-slice(%a, %b, %i)
  %cp = f32[3,57,8]{2,1,0} copy(f32[3,57,8]{1,2,0} %t)
  %c1 = f16[64]{0} convert(f32[64]{0} %q)
}
"""


def test_program_facts_from_synthetic_hlo():
    f = ProgramFacts.from_hlo(_SYNTHETIC)
    assert f.counts["all-reduce"] == 2
    assert f.counts["all-gather"] == 1
    assert f.narrowed["all-reduce"] == 1       # the f16 payload one
    assert f.narrowed["all-gather"] == 1       # the s8 wire
    assert f.payload_all_reduces() == 1        # scalar pmax excluded
    assert f.converts == {"f16": 1}
    assert f.dus == 1
    assert f.host_transfers == 1               # the send
    assert f.barriers == 1
    assert f.fused_loop and f.io_alias
    assert f.entry.startswith("ENTRY ")
    assert f.boundary_buffers_with_dim(116) == 2
    assert f.boundary_buffers_with_dim(57) == 0  # step-internal only
    assert f.buffers_with_dim(57) == 3   # dus result + copy both sides
    assert f.large_copies_with_dim(57, 3 * 57 * 8) == 1
    assert f.gathers_larger_than(4) == 1


def test_host_transfer_variants_detected():
    from autodist_tpu.analysis.facts import host_transfers
    assert host_transfers("  %r = (f32[2]) recv(token[] %t)") == 1
    assert host_transfers("  %o = token[] outfeed(f32[2] %x)") == 1
    assert host_transfers(
        '  %h = f32[2] custom-call(%x), custom_call_target='
        '"MoveToHost"') == 1
    assert host_transfers("  %m = f32[2] multiply(%a, %b)") == 0


# --------------------------------------------------------------------------- #
# Program rules on synthetic text (each rule both ways, no compiles)
# --------------------------------------------------------------------------- #
def _clean_text():
    return """
ENTRY %main (Arg_0: f32[4,8]) -> f32[4,8] {
  %w = f32[4,8]{1,0} while(f32[4,8]{1,0} %x), body=%b
}
""" + "input_output_alias={}"


@pytest.mark.parametrize("rule,bad_line", [
    (R.no_host_transfer(),
     "  %s = f32[8]{0} send(f32[8]{0} %x, token[] %t), channel_id=1"),
    (R.no_buffer_with_dim((93,), "vocab"),
     "  %t = f32[8,93]{1,0} parameter(7)"),
    (R.no_score_square(57),
     "  %sq = f32[2,57,57]{2,1,0} multiply(%a, %b)"),
    (R.no_full_gather(100),
     "  %g = f32[4096]{0} all-gather(f32[1024]{0} %p), dimensions={0}"),
    (R.no_collectives(),
     "  %ar = f32[8]{0} all-reduce(f32[8]{0} %g), replica_groups={}"),
    (R.quantized_wire(clean=True),
     "  %ar = f16[8]{0} all-reduce(f16[8]{0} %g), replica_groups={}"),
])
def test_injection_rules_fire_exactly_on_the_violation(rule, bad_line):
    clean = _clean_text()
    assert lint_program(clean, [rule]).ok
    report = lint_program(clean + "\n" + bad_line, [rule])
    assert report.codes() == {rule.code}


def test_threshold_rules_both_ways():
    two_dus = ("%d1 = f32[8] dynamic-update-slice(%a,%b,%i)\n"
               "%d2 = f32[8] dynamic-update-slice(%c,%e,%j)\n")
    assert lint_program(two_dus, [R.min_dus(2)]).ok
    assert not lint_program(two_dus, [R.min_dus(3)]).ok
    gathers = "%g = f32[8]{0} all-gather(f32[4]{0} %p), dimensions={0}\n"
    assert lint_program(gathers * 3, [R.min_collectives(
        "all-gather", 3, "per-layer")]).ok
    assert not lint_program(gathers * 2, [R.min_collectives(
        "all-gather", 3, "per-layer")]).ok
    ar = "%r = f32[64]{0} all-reduce(f32[64]{0} %g), to_apply=%add\n"
    assert lint_program(ar * 2, [R.no_refused_pair(2)]).ok
    assert not lint_program(ar * 3, [R.no_refused_pair(2)]).ok
    assert not lint_program(ar, [R.no_refused_pair(2)]).ok


# --------------------------------------------------------------------------- #
# Plan lint
# --------------------------------------------------------------------------- #
def test_builder_strategies_plan_clean():
    """Every builder-produced fixture passes plan lint with zero
    ERRORs (warnings are allowed: degrades are promoted, not fatal)."""
    for kwargs in ({}, {"tensor_parallel": 2},
                   {"tensor_parallel": 2, "vocab_parallel": True},
                   {"tensor_parallel": 2, "zero_stage": 3,
                    "collective_precision": "int8"}):
        strategy, spec, trainable = _pipeline_fixture(**kwargs)
        report = lint_plan(strategy, resource_spec=spec,
                           trainable=trainable)
        assert report.ok, (kwargs, report.render())


def test_plan_lint_works_without_resource_spec():
    """A serialized plan lints standalone: the declared mesh_axes stand
    in for the topology (the hand-edited-JSON audit path)."""
    strategy, _, _ = _pipeline_fixture(tensor_parallel=2)
    report = lint_plan(strategy)
    assert report.ok
    d = json.loads(strategy.to_json())
    d["graph_config"]["parallel"]["tensor_parallel"] = 4
    from autodist_tpu.strategy.ir import Strategy
    mutated = lint_plan(Strategy.from_json(json.dumps(d)))
    assert "ADT005" in mutated.codes()


def test_plan_lint_golden_report():
    """Diagnostic golden: a deterministic everything-wrong-at-once plan
    renders byte-identically (message wording and ordering are part of
    the operator contract; regenerate deliberately when a rule
    sharpens its message)."""
    from autodist_tpu.strategy.ir import Strategy

    strategy, spec, trainable = _pipeline_fixture(tensor_parallel=2)
    d = json.loads(strategy.to_json())
    d["id"] = "golden"
    d["graph_config"]["replicas"] = 4
    d["graph_config"]["parallel"]["comm_overlap"] = "ring"
    d["graph_config"]["precision"] = {"vocab_stats": "int8"}
    for nc in d["node_configs"]:
        if nc["var_name"] == "stages/mlp/wi/kernel":
            nc["synchronizer"] = {
                "kind": "ps", "zero_stage": 3,
                "reduction_destination": "",
                "local_replication": False, "sync": True,
                "staleness": 0}
    report = lint_plan(Strategy.from_json(json.dumps(d)),
                       resource_spec=spec, trainable=trainable)
    golden = open(os.path.join(DATA, "plan_lint_golden.txt")).read()
    assert report.render(title="golden-plan") + "\n" == golden


def test_degraded_diagnostics_is_the_shared_code_path():
    """lowered.zero_degraded records surface as ADT034 — the one code
    path both lint_plan(lowered=...) and callers holding a lowered
    plan use."""
    from types import SimpleNamespace

    from autodist_tpu.analysis import degraded_diagnostics

    strategy, spec, trainable = _pipeline_fixture(tensor_parallel=2)
    lowered = SimpleNamespace(zero_degraded={"stages/x": "because"})
    report = lint_plan(strategy, resource_spec=spec,
                       trainable=trainable, lowered=lowered)
    assert [d.where for d in report.by_code("ADT034")] == ["stages/x"]
    direct = list(degraded_diagnostics({"stages/x": "because"}))
    assert direct[0].to_dict() == report.by_code("ADT034")[0].to_dict()


# --------------------------------------------------------------------------- #
# Deriving program contracts from the Strategy IR
# --------------------------------------------------------------------------- #
def _rule_codes(rules):
    return {r.code for r in rules}


def test_rules_for_strategy_derivation():
    plain, _, _ = _pipeline_fixture()
    codes = _rule_codes(rules_for_strategy(plain))
    assert {"ADT101", "ADT109"} <= codes       # host + fp32-clean wire

    vocab, _, _ = _pipeline_fixture(tensor_parallel=2,
                                    vocab_parallel=True)
    assert "ADT105" in _rule_codes(
        rules_for_strategy(vocab, vocab_size=93))

    z3, _, _ = _pipeline_fixture(tensor_parallel=2, zero_stage=3,
                                 collective_precision="int8")
    codes = _rule_codes(rules_for_strategy(z3, boundary_dim=29))
    assert {"ADT106", "ADT107", "ADT109"} <= codes

    overlap, _, _ = _pipeline_fixture(tensor_parallel=2,
                                      comm_overlap="rsag")
    assert "ADT107" in _rule_codes(rules_for_strategy(overlap))


def test_rules_for_decode_derivation():
    codes = _rule_codes(rules_for_decode(
        2, True, vocab_size=93, max_len=57, num_layers=2, num_slots=3,
        heads_local=1, head_dim=8))
    assert {"ADT102", "ADT103", "ADT104", "ADT105", "ADT111",
            "ADT112", "ADT114"} <= codes
    tp1 = _rule_codes(rules_for_decode(
        1, False, vocab_size=93, max_len=57, num_layers=2, num_slots=3,
        heads_local=2, head_dim=8))
    assert "ADT113" in tp1 and "ADT105" not in tp1


# --------------------------------------------------------------------------- #
# The mutation matrix (the acceptance harness)
# --------------------------------------------------------------------------- #
def test_mutation_matrix_covers_the_required_rules():
    codes = {m.code for m in all_mutations()}
    # the acceptance list: re-fusion barrier, full-vocab buffer,
    # full-param step boundary, quantized wire, host transfer,
    # donated copy — plus the rest of the shipped rules
    assert {"ADT108", "ADT105", "ADT106", "ADT109", "ADT101",
            "ADT103", "ADT104", "ADT115"} <= codes
    assert len(codes) >= 10


def test_plan_mutations_fire():
    """Every plan rule fires on its seeded hand-edit and stays silent
    on the builder's own output (cheap: no compiles)."""
    results = run_mutations(kinds=["plan"])
    assert results
    bad = [r for r in results if not r["ok"]]
    assert not bad, bad


def test_program_mutations_fire():
    """Every program rule fires on its seeded violation (doctored HLO
    or the broken-sibling program) and passes the honest compiled
    program — compiles ride the shared memoized corpus."""
    results = run_mutations(kinds=["program"])
    assert results
    bad = [r for r in results if not r["ok"]]
    assert not bad, bad


def test_supervision_mutations_fire():
    """The ADT08x matrix: every supervision rule fires on its doctored
    config and stays silent on the honest one (escalation without a
    saver, heartbeat interval >= timeout, restart backoff beyond the
    SSP staleness window)."""
    results = run_mutations(kinds=["supervision"])
    assert {r["code"] for r in results} == {"ADT080", "ADT081", "ADT082"}
    bad = [r for r in results if not r["ok"]]
    assert not bad, bad


def test_lint_supervision_clean_config_is_clean():
    from autodist_tpu.analysis import lint_supervision
    from autodist_tpu.analysis.mutations import _supervision_fixture

    config, strategy = _supervision_fixture()
    assert lint_supervision(config, strategy=strategy).ok
    # dict form (a serialized config) lints identically
    assert lint_supervision(config.to_dict(), strategy=strategy).ok
    # ADT082 needs SSP in the plan: without a strategy the backoff rule
    # cannot fire, the others still do
    import dataclasses as dc

    broken = dc.replace(config, saver=None)
    report = lint_supervision(broken)
    assert "ADT080" in report.codes() and not report.ok
