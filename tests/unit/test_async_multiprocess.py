"""Async PS across two real processes via the product's own launcher.

The reference ran async PS between TF workers pushing to PS tasks over
gRPC (``ps_synchronizer.py:216-230``); here the chief process hosts the
PS loop + coordination service, launches a worker process with
``Cluster.launch_clients``, and both push gradients asynchronously.  The
test asserts the PS applied every push and the chief observed progress —
exact values are inherently order-dependent under asynchrony.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCRIPT = """
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import optax
import jax.numpy as jnp

from autodist_tpu import AutoDist, PS, Trainable
from autodist_tpu.runtime.cluster import Cluster
from autodist_tpu.resource import ResourceSpec

IS_CHIEF = not os.environ.get("AUTODIST_TPU_WORKER")
OUT = os.environ["TEST_OUT"]
STEPS = 4

def make_trainable():
    rng = np.random.RandomState(0)
    params = {"w": rng.randn(6, 3).astype(np.float32)}
    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)
    return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.05))

def batch(seed):
    r = np.random.RandomState(seed)
    return {"x": r.randn(8, 6).astype(np.float32),
            "y": r.randn(8, 3).astype(np.float32)}

if IS_CHIEF:
    rs = ResourceSpec({})
    strategy = PS(sync=False).build(make_trainable(), rs)
    cluster = Cluster(rs, hosts=["localhost"])
    # Starts the authenticated coordination service, publishes the
    # strategy, launches the worker.
    cluster.launch_clients(strategy,
                           argv=[sys.executable, os.path.abspath(__file__)])
    runner = AutoDist(rs, PS(sync=False)).build(make_trainable(),
                                                strategy=strategy)
    losses = []
    for i in range(STEPS):
        losses.append(float(np.asarray(runner.step(batch(i))["loss"])))
    # Both processes pushed STEPS grads each; wait for all applied.
    runner.wait_applied(2 * STEPS, timeout_s=60)
    params = runner.get_params()
    assert runner._params_version >= 2 * STEPS
    assert all(np.isfinite(l) for l in losses), losses
    assert np.isfinite(np.asarray(params["w"])).all()
    np.savez(OUT, w=params["w"], versions=runner._params_version,
             losses=np.asarray(losses))
    cluster.join(timeout=60)
    runner.close()
else:
    runner = AutoDist({}, PS(sync=False)).build(make_trainable())
    for i in range(STEPS):
        runner.step(batch(100 + i))
    # Ensure our pushes landed before exiting (queue is server-side, but
    # confirm progress to make the test deterministic).
    runner.wait_applied(STEPS, timeout_s=60)
"""


def test_async_ps_two_processes(tmp_path):
    script = tmp_path / "async2.py"
    script.write_text(SCRIPT)
    out = tmp_path / "result.npz"
    env = dict(os.environ, PYTHONPATH=REPO_ROOT, TEST_OUT=str(out))
    for k in ("AUTODIST_TPU_WORKER", "AUTODIST_TPU_COORD_SERVICE",
              "AUTODIST_TPU_COORD_TOKEN", "XLA_FLAGS", "JAX_PLATFORMS"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, str(script)], env=env, cwd=str(tmp_path),
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (
        f"chief failed\nstdout:\n{proc.stdout[-3000:]}\n"
        f"stderr:\n{proc.stderr[-3000:]}")
    got = dict(np.load(out))
    assert int(got["versions"]) >= 8  # 2 processes x 4 pushes all applied
    assert np.isfinite(got["w"]).all()
    assert np.isfinite(got["losses"]).all()
