"""Async PS + SSP through the runner (≙ reference c9 + async sync flag).

The reference exposed ``PSSynchronizer{sync, staleness}``
(``synchronizers.proto:25-31``): ``sync=False`` = workers push grads and
proceed (``ps_synchronizer.py:216-230``); ``staleness>0`` = bounded-skew
SSP via depth-``staleness`` token queues (``ps_synchronizer.py:387-458``),
validated by the timing case ``tests/integration/cases/c9.py:92-126``.
These tests drive both through the public facade / ``runner.step``.
"""
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist, PS, Trainable
from autodist_tpu.runner import AsyncPSRunner, DistributedRunner


def make_trainable(optimizer=None, seed=0):
    rng = np.random.RandomState(seed)
    params = {"w": jnp.asarray(rng.randn(6, 3), jnp.float32),
              "b": jnp.zeros((3,), jnp.float32)}

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return Trainable.from_loss_fn(loss_fn, params,
                                  optimizer or optax.sgd(0.1))


def make_batch(seed=1):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(16, 6).astype(np.float32),
            "y": rng.randn(16, 3).astype(np.float32)}


def single_device_reference(trainable, batches):
    params = trainable.params
    opt_state = trainable.optimizer.init(params)

    def loss_for(p, b):
        l, _, _ = trainable.loss(p, None, b, jax.random.PRNGKey(0))
        return l

    for b in batches:
        grads = jax.grad(loss_for)(params, jax.tree.map(jnp.asarray, b))
        updates, opt_state = trainable.optimizer.update(grads, opt_state,
                                                        params)
        params = optax.apply_updates(params, updates)
    return params


def test_async_ps_single_worker_matches_sync():
    """One async worker that waits for each apply == synchronous SGD:
    exact equality with the single-device loop."""
    runner = AutoDist({}, PS(sync=False)).build(make_trainable())
    assert isinstance(runner, AsyncPSRunner)
    try:
        batches = [make_batch(s) for s in range(3)]
        for i, b in enumerate(batches):
            runner.step(b)
            runner.wait_applied(i + 1)
        got = runner.get_params()
        want = single_device_reference(make_trainable(), batches)
        np.testing.assert_allclose(np.asarray(got["w"]),
                                   np.asarray(want["w"]),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(got["b"]),
                                   np.asarray(want["b"]),
                                   rtol=1e-6, atol=1e-6)
    finally:
        runner.close()


def test_async_ps_metrics_and_progress():
    runner = AutoDist({}, PS(sync=False)).build(make_trainable())
    try:
        b = make_batch()
        losses = []
        for i in range(6):
            m = runner.step(b)
            runner.wait_applied(i + 1)
            losses.append(float(np.asarray(m["loss"])))
        assert runner.step_count == 6
        assert losses[-1] < losses[0]
    finally:
        runner.close()


def test_sync_lowering_rejects_async_config():
    """Direct lowering of sync=False must fail loudly, never silently
    train synchronously (round-1/2 verdict item)."""
    from autodist_tpu.kernel.lowering import lower
    from autodist_tpu.resource import ResourceSpec

    t = make_trainable()
    rs = ResourceSpec({})
    strategy = PS(sync=False).build(t, rs)
    with pytest.raises(NotImplementedError, match="sync=False"):
        lower(t, strategy, rs.make_mesh())


def test_ssp_gate_through_runner_step():
    """c9-style timing through ``runner.step``: staleness=1 lets the fast
    runner reach step 2 immediately but blocks step 2+k on the slow
    runner's step k."""
    from autodist_tpu.runtime.coordination import CoordServer

    server = CoordServer()
    import os
    os.environ["AUTODIST_TPU_COORD_SERVICE"] = f"127.0.0.1:{server.port}"
    try:
        ad = AutoDist({}, PS(sync=True, staleness=1))
        b = make_batch()
        starts = {}
        t0_box = {}
        # Each "worker" builds and steps on its own thread: the
        # coordination client is thread-local, and the SSPController's
        # registration barrier needs both workers registering
        # concurrently (a CoordClient must not be shared across threads).
        ready = threading.Barrier(2, timeout=60)

        def fast():
            runner = ad.build(make_trainable(), ssp_worker="fast",
                              ssp_num_workers=2)
            assert isinstance(runner, DistributedRunner)
            assert runner._ssp is not None
            runner.step(b)  # warm/compile; SSP cannot block at step 0
            ready.wait()
            t0_box["t0"] = time.monotonic()
            for step in range(1, 5):
                runner.step(b)  # the SSP gate waits inside step()
                starts[step] = time.monotonic()  # completion time

        def slow():
            runner = ad.build(make_trainable(), ssp_worker="slow",
                              ssp_num_workers=2)
            runner.step(b)
            ready.wait()
            for _ in range(1, 5):
                time.sleep(0.3)
                runner.step(b)

        threads = [threading.Thread(target=fast),
                   threading.Thread(target=slow)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)
        assert not any(th.is_alive() for th in threads), "threads hung"
        t0 = t0_box["t0"]
        # With staleness=1 and both at step 0: fast completes steps 1-2
        # immediately; step 3's gate waits for slow's step 1 (~0.3s) and
        # step 4's for slow's step 2 (~0.6s).
        assert starts[2] - t0 < 0.29, starts
        assert starts[3] - t0 > 0.29, starts
        assert starts[4] - t0 > 0.59, starts
    finally:
        os.environ.pop("AUTODIST_TPU_COORD_SERVICE", None)
        from autodist_tpu.runtime import coordination
        coordination.reset_service_client()
        server.stop()


def test_async_ps_burst_publishes_fewer_than_applies():
    """Publish gating (round-4 Weak #3): a backlog of queued gradients
    is applied with at most one params serialization per
    publish_max_lag updates (+ the drain publish) — deterministic: the
    backlog is enqueued BEFORE the PS loop exists, so the PS always
    sees a 24-deep queue."""
    import os

    from autodist_tpu.runner import _pack_tree
    from autodist_tpu.runtime import coordination

    t = make_trainable()
    server = coordination.CoordServer()
    prev = os.environ.get("AUTODIST_TPU_COORD_SERVICE")
    os.environ["AUTODIST_TPU_COORD_SERVICE"] = f"127.0.0.1:{server.port}"
    coordination.reset_service_client()
    runner = None
    try:
        client = coordination.service_client()
        g = jax.tree.map(lambda p: np.full(p.shape, 0.01, np.float32),
                         t.params)
        for i in range(24):
            client.queue_put(AsyncPSRunner.GRADS_QUEUE, _pack_tree(i, g))

        runner = AsyncPSRunner(t, publish_max_lag=8,
                               publish_max_interval_s=3600.0)
        runner.wait_applied(24, timeout_s=60.0)
        # lag publishes at versions 8, 16, 24; drain adds none (24 is
        # already published) — allow the one extra for scheduling skew.
        assert runner.ps_publish_count <= 4, runner.ps_publish_count
        # every update is SGD with the constant grad: exact expectation
        expected = jax.tree.map(
            lambda p, gg: np.asarray(p) - 0.1 * 24 * gg, t.params, g)
        jax.tree.map(
            lambda a, e: np.testing.assert_allclose(
                np.asarray(a), e, rtol=1e-5, atol=1e-6),
            runner.get_params(), expected)
    finally:
        if runner is not None:
            runner.close()
        if prev is None:
            os.environ.pop("AUTODIST_TPU_COORD_SERVICE", None)
        else:
            os.environ["AUTODIST_TPU_COORD_SERVICE"] = prev
        coordination.reset_service_client()
        server.stop()


def test_async_ps_exactness_survives_publish_gating():
    """The 1-worker == sync SGD exactness golden with gating active:
    pull-after-wait_applied sees the drain publish."""
    t = make_trainable()
    runner = AsyncPSRunner(t, publish_max_lag=8,
                           publish_max_interval_s=3600.0)
    try:
        bs = [make_batch(seed=i) for i in range(4)]
        for i, b in enumerate(bs):
            runner.step(b)
            runner.wait_applied(i + 1, timeout_s=30.0)
        expected = single_device_reference(make_trainable(), bs)
        jax.tree.map(
            lambda a, e: np.testing.assert_allclose(
                np.asarray(a), np.asarray(e), rtol=1e-5, atol=1e-6),
            runner.get_params(), jax.device_get(expected))
    finally:
        runner.close()
