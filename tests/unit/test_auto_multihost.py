"""Multihost AutoStrategy measured refinement (round-4 Weak #5).

The chief publishes top-k candidates on the coordination service,
workers launched *before* planning (``Cluster.launch_clients(None)``)
join the rendezvous, every process builds + times each candidate in
SPMD lockstep over the 2-process gloo mesh, and all adopt the chief's
measured winner.  The trained result must equal the single-process run
— proving the measured steps did not leak into training state and the
winner handoff is complete.
"""
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

pytestmark = pytest.mark.slow

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCRIPT = """
import os, sys, json

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np
import optax

from autodist_tpu import AutoDist, AllReduce, AutoStrategy, Trainable, ZeRO
from autodist_tpu.resource import ResourceSpec
from autodist_tpu.runtime.cluster import Cluster, make_global_batch

IS_CHIEF = not os.environ.get("AUTODIST_TPU_WORKER")
COORD_PORT = int(os.environ["TEST_COORD_PORT"])
OUT = os.environ["TEST_OUT"]
STEPS = 3

def make_trainable():
    rng = np.random.RandomState(0)
    params = {"w": rng.randn(6, 3).astype(np.float32),
              "b": np.zeros(3, np.float32)}
    def loss_fn(p, batch):
        import jax.numpy as jnp
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)
    return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1))

def global_batch(step):
    rng = np.random.RandomState(100 + step)
    return {"x": rng.randn(16, 6).astype(np.float32),
            "y": rng.randn(16, 3).astype(np.float32)}

trainable = make_trainable()
example = global_batch(999)  # same global example batch on every process
auto = AutoStrategy(candidates=[AllReduce(chunk_size=2), ZeRO()],
                    measure_top_k=2, example_batch=example)

if IS_CHIEF:
    os.environ["AUTODIST_TPU_NUM_PROCESSES"] = "2"
    os.environ["AUTODIST_TPU_PROCESS_ID"] = "0"
    os.environ["AUTODIST_TPU_COORDINATOR"] = f"127.0.0.1:{COORD_PORT}"
    rs = ResourceSpec({"topology": {"num_devices": 4}})
    cluster = Cluster(rs, hosts=["localhost"])
    # Workers join BEFORE any strategy exists: the winner is measured.
    cluster.launch_clients(None, argv=[sys.executable,
                                       os.path.abspath(__file__)])
else:
    rs = ResourceSpec({"topology": {"num_devices": 4}})

ad = AutoDist(rs, auto)
runner = ad.build(trainable)

pid = rs.process_id
for step in range(STEPS):
    g = global_batch(step)
    half = 16 // 2
    local = {k: v[pid * half:(pid + 1) * half] for k, v in g.items()}
    batch = make_global_batch(local, runner.mesh)
    metrics = runner.step(batch)

if IS_CHIEF:
    params = jax.device_get(runner.get_params())
    np.savez(OUT, **params)
    with open(OUT + ".measured.json", "w") as f:
        json.dump({k: float(v) for k, v in auto.measured.items()}, f)
jax.distributed.shutdown()
if IS_CHIEF:
    cluster.join(timeout=60)
"""


def test_multihost_measured_refinement_matches_single_process(tmp_path):
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()

    script = tmp_path / "auto2.py"
    script.write_text(SCRIPT)
    out = tmp_path / "params.npz"
    env = dict(os.environ,
               PYTHONPATH=REPO_ROOT,
               TEST_COORD_PORT=str(port),
               TEST_OUT=str(out))
    env["AUTODIST_TPU_WORKING_DIR"] = str(tmp_path / "scratch")
    for k in ("AUTODIST_TPU_WORKER", "AUTODIST_TPU_NUM_PROCESSES",
              "AUTODIST_TPU_PROCESS_ID", "XLA_FLAGS", "JAX_PLATFORMS",
              "PALLAS_AXON_POOL_IPS"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, str(script)], env=env, cwd=str(tmp_path),
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, (
        f"chief failed\nstdout:\n{proc.stdout}\nstderr:\n{proc.stderr}")
    got = dict(np.load(out))

    # Both candidates were really measured across the 2-process job.
    import json
    measured = json.loads(open(str(out) + ".measured.json").read())
    assert len(measured) == 2, measured
    assert all(v > 0 for v in measured.values())

    # Single-process reference: same global batches, plain optax SGD
    # (both candidates are exact DP realizations, so the winner's
    # identity does not change the numbers).
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(6, 3), jnp.float32),
              "b": jnp.zeros(3, jnp.float32)}
    opt = optax.sgd(0.1)
    opt_state = opt.init(params)

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    for step in range(3):
        r = np.random.RandomState(100 + step)
        b = {"x": jnp.asarray(r.randn(16, 6), jnp.float32),
             "y": jnp.asarray(r.randn(16, 3), jnp.float32)}
        grads = jax.grad(loss_fn)(params, b)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)

    for k in got:
        np.testing.assert_allclose(got[k], np.asarray(params[k]),
                                   rtol=1e-5, atol=1e-6)
