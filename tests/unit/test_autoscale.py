"""Trace-driven autoscaling goldens (ISSUE 17).

The bar: a bursty :mod:`tools.loadgen` trace replayed against a routed
in-process fleet makes the autoscaler grow under the burst backlog AND
shrink once it drains — with every transition a schema-gated
``kind="scale"`` record moving the replica count by exactly one, the
trigger gauges present in the same run, every request completed, and
the trace generators deterministic under a seed and loud about
nonsense shapes.
"""
import json
import os
import sys

import pytest

from autodist_tpu import telemetry
from autodist_tpu.serving import (AutoscaleConfig, Autoscaler,
                                  FleetConfig, Router, ServingFleet,
                                  tiny_engine_factory)
from autodist_tpu.serving.autoscale import run_trace

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "tools"))

import loadgen  # noqa: E402  (tools/ is scripts, not a package)


# --------------------------------------------------------------------- #
# the generators: deterministic, and loud about nonsense shapes
# --------------------------------------------------------------------- #
def test_traces_are_deterministic_under_a_seed():
    kw = dict(duration_s=5.0, idle_rps=1.0, burst_rps=20.0,
              burst_s=1.0, gap_s=1.0)
    a = loadgen.bursty_trace(seed=3, **kw)
    b = loadgen.bursty_trace(seed=3, **kw)
    assert [(x.t_s, x.prompt, x.max_new_tokens) for x in a] \
        == [(x.t_s, x.prompt, x.max_new_tokens) for x in b]
    c = loadgen.bursty_trace(seed=4, **kw)
    assert [x.t_s for x in a] != [x.t_s for x in c]
    assert all(0.0 <= x.t_s <= 5.0 for x in a)
    assert all(x.prompt and x.max_new_tokens >= 1 for x in a)


def test_trace_shape_validation():
    with pytest.raises(ValueError, match="burst_rps"):
        loadgen.bursty_trace(duration_s=1.0, idle_rps=5.0,
                             burst_rps=1.0, burst_s=0.5, gap_s=0.5)
    with pytest.raises(ValueError, match="peak_rps"):
        loadgen.diurnal_trace(duration_s=1.0, base_rps=5.0,
                              peak_rps=1.0)
    with pytest.raises(ValueError, match="alpha"):
        loadgen.heavy_tail_trace(duration_s=1.0, rps=5.0, alpha=1.0)


def test_autoscale_config_validation():
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscaleConfig(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscaleConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="hysteresis"):
        AutoscaleConfig(grow_queue_depth=2.0, shrink_queue_depth=2.0)


# --------------------------------------------------------------------- #
# the loop: grow under the burst, shrink after the drain — schema-gated
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_burst_grows_then_drain_shrinks_schema_gated(tmp_path):
    import telemetry_report as tr

    telemetry.configure(out_dir=str(tmp_path))
    trace = loadgen.bursty_trace(duration_s=3.0, idle_rps=1.0,
                                 burst_rps=40.0, burst_s=1.0,
                                 gap_s=0.8, seed=7)
    fleet = ServingFleet(
        tiny_engine_factory,
        config=FleetConfig(replicas=1, heartbeat_interval_s=0.05,
                           heartbeat_timeout_s=5.0,
                           heartbeat_startup_grace_s=30.0))
    router = Router(fleet)
    asc = Autoscaler(router, config=AutoscaleConfig(
        min_replicas=1, max_replicas=3, grow_queue_depth=3.0,
        shrink_queue_depth=0.5, cooldown_s=0.05))
    done = run_trace(router, asc, trace, speed=50.0)
    assert len(done) == len(trace)   # nothing dropped while scaling
    directions = [e["direction"] for e in asc.events]
    assert "grow" in directions, directions
    assert "shrink" in directions, directions
    # every transition moved the count by exactly one, within bounds
    for e in asc.events:
        assert abs(e["replicas_after"] - e["replicas_before"]) == 1
        assert 1 <= e["replicas_after"] <= 3
        assert e["trigger"] == "queue_depth"
    # the shrink never undercut the floor
    assert len(fleet.admitting) >= 1
    telemetry.flush()
    assert tr.check_schema(str(tmp_path)) == []
    with open(tmp_path / "metrics.jsonl") as f:
        recs = [json.loads(line) for line in f if line.strip()]
    scales = [r for r in recs if r.get("kind") == "scale"]
    assert [s["direction"] for s in scales] == directions
    gauges = {r["name"] for r in recs if r.get("kind") == "gauge"}
    assert "autoscale/queue_depth" in gauges
    rendered = tr.render(str(tmp_path))
    assert "## autoscaling" in rendered


@pytest.mark.slow
def test_cooldown_spaces_transitions():
    fleet = ServingFleet(tiny_engine_factory,
                         config=FleetConfig(replicas=1))
    router = Router(fleet)
    asc = Autoscaler(router, config=AutoscaleConfig(
        min_replicas=1, max_replicas=4, grow_queue_depth=0.5,
        shrink_queue_depth=0.1, cooldown_s=100.0),
        clock=lambda: 0.0)
    for _ in range(8):
        router.submit([1, 2], max_new_tokens=2)
    assert asc.step(now=0.0) is not None    # the backlog fires once
    assert asc.step(now=1.0) is None        # inside the cooldown
    assert asc.step(now=200.0) is not None  # past it
    router.run()
