"""bench.py watchdog monitor: the driver-facing failure reporter.

The monitor runs as a separate process (an in-process alarm cannot
preempt a wedged PJRT C call); these tests drive the extracted monitor
source directly — no jax, no accelerator.
"""
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _monitor_src():
    src = open(os.path.join(REPO, "bench.py")).read()
    return src.split('_MONITOR_SRC = r"""')[1].split('"""')[0]


def drive(partial_content, stage="probe x"):
    d = tempfile.mkdtemp()
    stage_path = os.path.join(d, "stage")
    with open(stage_path, "w") as f:
        f.write(stage)
    partial = os.path.join(d, "partial")
    if partial_content is not None:
        with open(partial, "w") as f:
            json.dump(partial_content, f)
    victim = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(30)"])
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _monitor_src(), str(victim.pid),
             stage_path, "1.0", partial],
            capture_output=True, text=True, timeout=30)
    finally:
        victim.poll() is None and victim.kill()
        victim.wait()
    return json.loads(proc.stdout.strip())


def test_scored_snapshot_reported_unflagged():
    """A record carrying "scored" IS a completed measurement (the bench
    scores first): the watchdog must report it without a partial flag."""
    rec = drive({"metric": "bert_base_mlm_mfu", "value": 0.41,
                 "scored": True})
    assert "partial" not in rec and rec["value"] == 0.41


def test_probe_snapshot_flagged_partial():
    rec = drive({"metric": "bert_base_mlm_mfu", "value": 0.32})
    assert "best probe rate" in rec["partial"] and rec["value"] == 0.32


def test_no_snapshot_yields_stage_diagnostic():
    rec = drive(None, stage="scored run (einsum/b16)")
    assert rec["value"] == 0.0
    assert "scored run (einsum/b16)" in rec["error"]
