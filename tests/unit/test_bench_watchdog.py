"""bench.py watchdog monitor: the driver-facing failure reporter.

The monitor runs as a separate process (an in-process alarm cannot
preempt a wedged PJRT C call); these tests drive the extracted monitor
source directly — no jax, no accelerator.
"""
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _monitor_src():
    src = open(os.path.join(REPO, "bench.py")).read()
    return src.split('_MONITOR_SRC = r"""')[1].split('"""')[0]


def drive(partial_content, stage="probe x"):
    d = tempfile.mkdtemp()
    stage_path = os.path.join(d, "stage")
    with open(stage_path, "w") as f:
        f.write(stage)
    partial = os.path.join(d, "partial")
    if partial_content is not None:
        with open(partial, "w") as f:
            json.dump(partial_content, f)
    victim = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(30)"])
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _monitor_src(), str(victim.pid),
             stage_path, "1.0", partial],
            capture_output=True, text=True, timeout=30)
    finally:
        victim.poll() is None and victim.kill()
        victim.wait()
    return json.loads(proc.stdout.strip())


def test_scored_snapshot_reported_unflagged():
    """A record carrying "scored" IS a completed measurement (the bench
    scores first): the watchdog must report it without a partial flag."""
    rec = drive({"metric": "bert_base_mlm_mfu", "value": 0.41,
                 "scored": True})
    assert "partial" not in rec and rec["value"] == 0.41


def test_probe_snapshot_flagged_partial():
    rec = drive({"metric": "bert_base_mlm_mfu", "value": 0.32})
    assert "best probe rate" in rec["partial"] and rec["value"] == 0.32


def test_no_snapshot_yields_stage_diagnostic():
    rec = drive(None, stage="scored run (einsum/b16)")
    assert rec["value"] == 0.0
    assert "scored run (einsum/b16)" in rec["error"]


# --------------------------------------------------------------------------- #
# UNAVAILABLE-backend handling: retry with capped exponential backoff,
# then a well-formed skipped record with rc=0 — never rc=3 (BENCH_r*.json
# must not record a missing backend as a crash).
# --------------------------------------------------------------------------- #
def _run_py(code, attempt, backoff="0.01"):
    env = dict(os.environ, AUTODIST_TPU_BENCH_ATTEMPT=str(attempt),
               AUTODIST_TPU_BENCH_BACKOFF=backoff, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=120,
                          env=env)


def test_backoff_delay_is_capped_exponential():
    proc = _run_py("import bench; print([bench._backoff_delay(a) "
                   "for a in (1, 2, 3, 5)])", attempt=1)
    assert proc.returncode == 0, proc.stderr
    assert "[5.0, 10.0, 20.0, 60.0]" in proc.stdout


def test_unavailable_final_attempt_exits_zero_with_skipped_record():
    proc = _run_py("import bench; "
                   "bench._unavailable_exit('boom UNAVAILABLE')",
                   attempt=3)
    assert proc.returncode == 0, proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["skipped"] is True
    assert rec["value"] == 0.0 and "UNAVAILABLE" in rec["error"]
    assert rec["metric"] == "bert_base_mlm_mfu"  # the greppable shape


def test_unavailable_early_attempt_backs_off_and_reexecs():
    code = ("import os, sys, bench\n"
            "def fake_execve(path, argv, env):\n"
            "    print('EXEC attempt', env['AUTODIST_TPU_BENCH_ATTEMPT'])\n"
            "    sys.exit(7)\n"
            "os.execve = fake_execve\n"
            "bench._unavailable_exit('boom UNAVAILABLE')\n")
    proc = _run_py(code, attempt=1)
    assert proc.returncode == 7, (proc.stdout, proc.stderr)
    assert "retrying" in proc.stdout
    assert "EXEC attempt 2" in proc.stdout
