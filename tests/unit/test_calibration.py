"""The committed analytic-default calibration.json: the calibration
mechanism exists as a *file* (loaded by ``cost_model.load_calibration``),
not just as the ``tools/calibrate_compressors.py`` writer."""
import json
import os

from autodist_tpu.simulator import cost_model as cm

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
CALIB = os.path.join(REPO, "calibration.json")


def test_repo_calibration_file_exists_and_is_well_formed():
    with open(CALIB) as f:
        data = json.load(f)
    assert data["meta"]["backend"] == "analytic"
    factors = data["compressor_factor"]
    # Every committed factor names a compressor the cost model knows,
    # and the analytic defaults agree with the in-code table (the file
    # is the serialization of the defaults until silicon measures them).
    assert set(factors) == set(cm.COMPRESSOR_FACTOR)
    for name, value in factors.items():
        assert 0.0 < value <= 1.0, (name, value)


def test_repo_calibration_autoloads(monkeypatch):
    """With no explicit path and no env override, load_calibration finds
    the repo-root file (analytic provenance passes the cpu gate)."""
    monkeypatch.delenv("AUTODIST_TPU_CALIBRATION", raising=False)
    applied = cm.load_calibration()
    with open(CALIB) as f:
        expected = json.load(f)["compressor_factor"]
    assert applied == expected
    for name, value in expected.items():
        assert cm.COMPRESSOR_FACTOR[name] == value


def test_explicit_path_beats_default(tmp_path, monkeypatch):
    other = tmp_path / "measured.json"
    other.write_text(json.dumps(
        {"meta": {"backend": "v5e"},
         "compressor_factor": {"bf16": 0.44}}))
    monkeypatch.setitem(cm.COMPRESSOR_FACTOR, "bf16", 0.5)
    assert cm.load_calibration(str(other)) == {"bf16": 0.44}
    assert cm.COMPRESSOR_FACTOR["bf16"] == 0.44
