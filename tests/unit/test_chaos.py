"""Chaos subsystem tests.

Fast tier: the FaultPlan/FaultSpec vocabulary (JSON + env shipping,
trigger semantics under a fake clock), the injector's record contract,
and the telemetry report's fault schema gate (an injection without a
matching recovery record FAILS --check).  The subprocess matrix —
``tools/chaos_run.py --matrix``, every fault kind against a real
LocalCluster pipeline-LM run — is ``slow``-marked.
"""
import json
import os
import subprocess
import sys

import pytest

from autodist_tpu import telemetry
from autodist_tpu.runtime.faults import (FAULT_KINDS, FaultInjector,
                                         FaultPlan, FaultSpec,
                                         install_ckpt_write_fail,
                                         load_fault_plan)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# --------------------------------------------------------------------------- #
# Plan vocabulary
# --------------------------------------------------------------------------- #
def test_fault_spec_validation():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec("disk_melt", at_step=1)
    with pytest.raises(ValueError, match="exactly one trigger"):
        FaultSpec("worker_crash")                       # no trigger
    with pytest.raises(ValueError, match="exactly one trigger"):
        FaultSpec("worker_crash", at_step=1, at_s=1.0)  # two triggers


def test_plan_json_roundtrip_and_env_shipping(tmp_path, monkeypatch):
    plan = FaultPlan(faults=[
        FaultSpec("worker_crash", target="worker-1", at_s=1.0,
                  exit_code=3),
        FaultSpec("ckpt_write_fail", target="chief", at_step=4, times=2),
    ], seed=99)
    back = FaultPlan.from_json(plan.to_json())
    assert back.seed == 99 and len(back.faults) == 2
    assert back.faults[0].kind == "worker_crash"
    assert back.faults[0].exit_code == 3
    assert back.for_target("chief")[0].times == 2
    # env shipping: inline JSON ...
    env = plan.ship({})
    monkeypatch.setenv("AUTODIST_TPU_FAULT_PLAN",
                       env["AUTODIST_TPU_FAULT_PLAN"])
    assert load_fault_plan().seed == 99
    # ... and @file indirection (the pipeline_train --chaos form)
    path = tmp_path / "plan.json"
    path.write_text(plan.to_json())
    assert load_fault_plan(f"@{path}").faults[1].at_step == 4
    monkeypatch.delenv("AUTODIST_TPU_FAULT_PLAN")
    assert load_fault_plan() is None       # chaos is strictly opt-in


def test_injector_triggers_once_on_step_and_walltime():
    telemetry.reset()
    t = {"now": 0.0}
    plan = FaultPlan(faults=[
        FaultSpec("slow_host", target="chief", at_step=3,
                  duration_s=0.0),
        FaultSpec("slow_host", target="chief", at_s=5.0, duration_s=0.0),
    ])
    inj = FaultInjector(plan, self_target="chief",
                        clock=lambda: t["now"])
    assert inj.maybe_fire(0) == []
    t["now"] = 1.0
    assert [s.at_step for s in inj.maybe_fire(3)] == [3]   # step trigger
    assert inj.maybe_fire(3) == []                         # fires ONCE
    t["now"] = 6.0
    assert [s.at_s for s in inj.maybe_fire(4)] == [5.0]    # wall trigger
    assert inj.maybe_fire(99) == []
    recs = [r for r in telemetry.get().step_records()
            if r.get("kind") == "fault"]
    # each slow_host injection paired with its own recovery record
    assert sum(r["phase"] == "injected" for r in recs) == 2
    assert sum(r["phase"] == "recovered" for r in recs) == 2


def test_injector_ignores_other_targets():
    plan = FaultPlan(faults=[FaultSpec("worker_crash", target="worker-2",
                                       at_step=0)])
    inj = FaultInjector(plan, self_target="chief")   # no workers map
    assert inj.maybe_fire(10) == []                  # not ours: no fire


def test_ckpt_write_fail_injection_counts_down(tmp_path):
    from autodist_tpu.checkpoint.saver import Saver

    saver = Saver(str(tmp_path))
    countdown = install_ckpt_write_fail(saver, times=2)
    for _ in range(2):
        with pytest.raises(OSError, match="injected ckpt_write_fail"):
            saver._mgr.save(0, args=None)
    assert countdown["left"] == 0


# --------------------------------------------------------------------------- #
# The report's fault schema gate
# --------------------------------------------------------------------------- #
def _check(tmp_path, records):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from telemetry_report import check_schema

    with open(os.path.join(tmp_path, "metrics.jsonl"), "w") as f:
        f.write("\n".join(json.dumps(r) for r in records) + "\n")
    return check_schema(str(tmp_path))


def test_report_gates_unrecovered_injection(tmp_path):
    inj = {"kind": "fault", "fault": "worker_crash", "target": "worker-1",
           "phase": "injected"}
    rec = {"kind": "fault", "fault": "worker_crash", "target": "worker-1",
           "phase": "recovered", "action": "restart"}
    problems = _check(tmp_path, [inj])
    assert any("no matching recovery" in p for p in problems)
    assert _check(tmp_path, [inj, rec]) == []
    # a recovery for a DIFFERENT target does not excuse the injection
    other = dict(rec, target="worker-2")
    assert any("no matching recovery" in p
               for p in _check(tmp_path, [inj, other]))
    # every terminal phase closes the loop
    for phase in ("degraded", "escalated", "teardown"):
        assert _check(tmp_path, [inj, dict(rec, phase=phase)]) == []


def test_report_gates_fault_record_shape(tmp_path):
    bad_kind = {"kind": "fault", "fault": "gremlins", "target": "x",
                "phase": "injected"}
    bad_phase = {"kind": "fault", "fault": "slow_host", "target": "x",
                 "phase": "vibing"}
    missing = {"kind": "fault", "fault": "slow_host"}
    problems = _check(tmp_path, [bad_kind, bad_phase, missing])
    assert any("unknown fault kind" in p for p in problems)
    assert any("unknown fault phase" in p for p in problems)
    assert any("fault record missing" in p for p in problems)


def test_report_renders_faults_section(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from telemetry_report import render

    records = [
        {"kind": "fault", "fault": "preempt_signal", "target": "chief",
         "phase": "injected", "step": 7},
        {"kind": "fault", "fault": "preempt_signal", "target": "chief",
         "phase": "recovered", "action": "shrink_resume", "step": 7},
    ]
    with open(os.path.join(tmp_path, "metrics.jsonl"), "w") as f:
        f.write("\n".join(json.dumps(r) for r in records) + "\n")
    out = render(str(tmp_path))
    assert "## faults" in out
    assert "preempt_signal" in out and "shrink_resume" in out


# --------------------------------------------------------------------------- #
# The subprocess chaos matrix (slow tier)
# --------------------------------------------------------------------------- #
@pytest.mark.slow
def test_chaos_matrix_every_fault_recovers(tmp_path):
    """tools/chaos_run.py --matrix: golden + every fault kind against a
    LocalCluster pipeline-LM run; each scenario must end in a
    supervised recovery or a clean coded teardown (never a hang), with
    schema-valid fault records and the loss trajectory matching the
    golden."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    for k in ("AUTODIST_TPU_WORKER", "AUTODIST_TPU_FAULT_PLAN",
              "XLA_FLAGS"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_run.py"),
         "--matrix", "--steps", "12", "--telemetry-dir", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=3000)
    assert proc.returncode == 0, (
        f"chaos matrix failed\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}")
    with open(tmp_path / "matrix.json") as f:
        results = json.load(f)
    assert set(results) == {"none", *FAULT_KINDS}
    assert all(r["ok"] for r in results.values()), results


@pytest.mark.slow
def test_serving_chaos_matrix_every_replica_fault_recovers(tmp_path):
    """tools/chaos_run.py --matrix --plane serving: golden + every
    replica fault kind against a 2-replica fleet; every request must
    complete exactly once, token-for-token equal to the single-replica
    fault-free golden, with zero leaked KV blocks and a schema-clean
    dispatch/fault trail."""
    from autodist_tpu.runtime.faults import SERVING_FAULT_KINDS

    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    for k in ("AUTODIST_TPU_WORKER", "AUTODIST_TPU_FAULT_PLAN",
              "XLA_FLAGS"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_run.py"),
         "--matrix", "--plane", "serving",
         "--telemetry-dir", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=3000)
    assert proc.returncode == 0, (
        f"serving chaos matrix failed\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}")
    with open(tmp_path / "matrix.json") as f:
        results = json.load(f)
    assert set(results) == {"none", *SERVING_FAULT_KINDS}
    assert all(r["ok"] for r in results.values()), results
    # token-for-token: the golden's streams appear verbatim in every
    # fault scenario's record (the matrix driver already joined them;
    # re-assert here so a driver regression cannot hide it)
    golden = results["none"]["tokens"]
    for kind in SERVING_FAULT_KINDS:
        assert results[kind]["tokens"] == golden, kind


@pytest.mark.slow
def test_serving_chaos_matrix_against_real_replica_processes(tmp_path):
    """tools/chaos_run.py --matrix --plane serving --processes: the
    replica fault kinds against a ProcessFleet of REAL replica
    processes, the fault plan shipped for worker self-injection (a
    crash is a dead process, a hang a SIGSTOP) — every request must
    still complete exactly once, token-for-token equal to the
    in-process fault-free golden, with zero leaked KV blocks."""
    from autodist_tpu.runtime.faults import SERVING_FAULT_KINDS

    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    for k in ("AUTODIST_TPU_WORKER", "AUTODIST_TPU_FAULT_PLAN",
              "XLA_FLAGS", "AUTODIST_TPU_COORD_SERVICE"):
        env.pop(k, None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_run.py"),
         "--matrix", "--plane", "serving", "--processes",
         "--telemetry-dir", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=3000)
    assert proc.returncode == 0, (
        f"cross-process serving chaos matrix failed\n"
        f"stdout:\n{proc.stdout[-4000:]}\nstderr:\n{proc.stderr[-4000:]}")
    with open(tmp_path / "matrix.json") as f:
        results = json.load(f)
    assert set(results) == {"none", *SERVING_FAULT_KINDS}
    assert all(r["ok"] for r in results.values()), results
    golden = results["none"]["tokens"]
    for kind in SERVING_FAULT_KINDS:
        assert results[kind]["tokens"] == golden, kind
    # the self-injected faults really happened in the worker processes:
    # each fault scenario's telemetry carries the worker-side injection
    # record merged from its replica-*-i0 directory
    for kind in SERVING_FAULT_KINDS:
        with open(tmp_path / kind / "metrics.jsonl") as f:
            recs = [json.loads(line) for line in f if line.strip()]
        assert any(r.get("kind") == "fault" and r.get("fault") == kind
                   and r.get("phase") == "injected" for r in recs), kind
