"""Checkpoint tests (≙ reference ``tests/checkpoint/``: partitioned-PS
checkpoints restore into vanilla graphs and vice versa)."""
import jax
import numpy as np
import optax
import pytest

from autodist_tpu import AllReduce, AutoDist, PartitionedPS, PS
from autodist_tpu.checkpoint.saver import Saver

from tests.unit.test_end_to_end import make_batch, make_trainable


def train_some(builder, steps=2, seed=0):
    runner = AutoDist({}, builder).build(make_trainable(seed=seed))
    for s in range(steps):
        runner.step(make_batch(s))
    return runner


def test_full_save_restore_exact_resume(tmp_path):
    runner = train_some(PS())
    saver = Saver(str(tmp_path))
    saver.save(runner)

    # fresh runner, restore, must continue *bit-identically*
    runner2 = AutoDist({}, PS()).build(make_trainable())
    saver.restore(runner2)
    b = make_batch(7)
    m1 = runner.step(dict(b))
    m2 = runner2.step(dict(b))
    assert float(m1["loss"]) == float(m2["loss"])
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(c)),
        runner.get_params(), runner2.get_params())


def test_portable_restores_across_strategies(tmp_path):
    """FSDP-written portable checkpoint restores under pure DP — the
    'checkpoints look unpartitioned' contract (reference saver.py:50-58)."""
    runner = train_some(PartitionedPS(), steps=3)
    params_before = runner.get_params()
    saver = Saver(str(tmp_path))
    saver.save(runner, portable=True)

    runner2 = AutoDist({}, AllReduce()).build(make_trainable(seed=9))
    saver.restore_portable(runner2)
    jax.tree.map(lambda a, c: np.testing.assert_allclose(
        np.asarray(a), np.asarray(c), rtol=1e-6),
        params_before, runner2.get_params())
    assert runner2.step_count == 3
    # training continues fine under the new strategy
    m = runner2.step(make_batch(11))
    assert np.isfinite(float(m["loss"]))


def test_portable_loads_as_host_arrays(tmp_path):
    """≙ restoring an AutoDist checkpoint into vanilla single-node TF."""
    runner = train_some(PartitionedPS())
    saver = Saver(str(tmp_path))
    saver.save(runner, portable=True)
    payload = saver.restore_params()
    # original, unpadded shapes under logical names
    assert np.asarray(payload["params"]["dense"]["w"]).shape == (6, 3)
    np.testing.assert_allclose(
        np.asarray(payload["params"]["dense"]["w"]),
        runner.get_params()["dense"]["w"], rtol=1e-6)


def test_latest_step_and_missing(tmp_path):
    saver = Saver(str(tmp_path))
    assert saver.latest_step() is None
    with pytest.raises(FileNotFoundError):
        saver.restore_params()


@pytest.mark.slow
def test_preemption_hook_checkpoints_on_sigterm(tmp_path):
    """A SIGTERM (TPU preemption) must flush a checkpoint before the
    process obeys the signal; run in a subprocess to observe the death."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    script = tmp_path / "preempt.py"
    script.write_text(f"""
import os, signal
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=8'
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
import sys
sys.path.insert(0, {repo!r})
from autodist_tpu import AutoDist, PS
from autodist_tpu.checkpoint.saver import Saver
from tests.unit.test_end_to_end import make_batch, make_trainable

runner = AutoDist({{}}, PS()).build(make_trainable())
runner.step(make_batch(0))
runner.step(make_batch(1))
saver = Saver({str(tmp_path / 'ckpt')!r})
saver.install_preemption_hook(runner)
os.kill(os.getpid(), signal.SIGTERM)   # simulate preemption
raise SystemExit("signal did not terminate the process")
""")
    proc = subprocess.run([sys.executable, str(script)], cwd=repo,
                          capture_output=True, text=True, timeout=240)
    assert proc.returncode != 0  # died by/after the signal, not SystemExit 0
    assert "signal did not terminate" not in proc.stdout + proc.stderr

    # The checkpoint written by the handler restores at step 2.
    saver = Saver(str(tmp_path / "ckpt"))
    assert saver.latest_step() == 2
    runner2 = AutoDist({}, PS()).build(make_trainable())
    saver.restore(runner2)
    assert runner2.step_count == 2


def test_async_save_snapshot_is_donation_safe(tmp_path):
    """Async save must capture the state *at save time*: training
    continues immediately after save() (donating/reusing the state
    buffers), yet the restored checkpoint equals the pre-continuation
    snapshot."""
    runner = train_some(AllReduce(), steps=2)
    snapshot = jax.device_get(runner.get_params())
    step_at_save = runner.step_count

    saver = Saver(str(tmp_path), async_save=True)
    saver.save(runner)                       # returns before disk commit
    for s in range(3):                       # donated buffers get reused
        runner.step(make_batch(10 + s))

    runner2 = AutoDist({}, AllReduce()).build(make_trainable())
    # explicit step naming the (possibly still in-flight) async save must
    # join the commit, not race it
    saver.restore(runner2, step=step_at_save)
    assert saver.latest_step() == step_at_save
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), runner2.get_params(), snapshot)
    # and the restored runner resumes from the saved step, not the later one
    assert runner2.step_count == step_at_save
    saver.close()


def test_portable_restore_elastic_mesh_shrink(tmp_path):
    """Elasticity: a portable checkpoint from an 8-device run restores
    into a 4-device runner (different mesh size AND strategy) and
    continues training — the restart path after losing capacity."""
    runner8 = AutoDist({"topology": {"num_devices": 8}},
                       PartitionedPS()).build(make_trainable())
    for s in range(2):
        runner8.step(make_batch(s))
    expect = runner8.get_params()
    saver = Saver(str(tmp_path))
    saver.save(runner8, portable=True)

    runner4 = AutoDist({"topology": {"num_devices": 4}},
                       AllReduce()).build(make_trainable(seed=9))
    saver.restore_portable(runner4)
    assert runner4.step_count == 2
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-6),
        runner4.get_params(), expect)
    # and it trains on the smaller mesh (batch must divide 4 now)
    b = make_batch(5)
    m = runner4.step(b)
    assert np.isfinite(float(np.asarray(m["loss"])))
    saver.close()


# --------------------------------------------------------------------------- #
# Chaos-hardened saves: bounded retries, coded degrade, async failures
# surfacing with their step number (pinned by injected ckpt_write_fail).
# --------------------------------------------------------------------------- #
def _fast_retry():
    from autodist_tpu.runtime.retry import RetryPolicy

    return RetryPolicy(max_attempts=2, base_delay_s=0.01,
                       cap_delay_s=0.01, seed=0)


def test_save_retries_through_injected_write_failure(tmp_path):
    """One injected write failure, a 2-attempt policy: the save lands
    and restores bit-exactly — the fault is invisible to the caller."""
    from autodist_tpu.runtime.faults import install_ckpt_write_fail

    runner = train_some(PS())
    saver = Saver(str(tmp_path), retry=_fast_retry())
    countdown = install_ckpt_write_fail(saver, times=1)
    step = saver.save(runner)
    assert step is not None and countdown["left"] == 0
    runner2 = AutoDist({}, PS()).build(make_trainable())
    saver.restore(runner2)
    jax.tree.map(lambda a, c: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(c)),
        runner.get_params(), runner2.get_params())


def test_save_degrades_on_persistent_write_failure(tmp_path):
    """Retries exhausted + degrade_on_failure: save() returns None, the
    counter and the kind="fault" degrade record fire, and the LAST GOOD
    checkpoint still restores — training stays alive."""
    from autodist_tpu import telemetry
    from autodist_tpu.runtime.faults import install_ckpt_write_fail

    telemetry.reset()
    runner = train_some(PS())
    saver = Saver(str(tmp_path), retry=_fast_retry(),
                  degrade_on_failure=True)
    good_step = saver.save(runner)          # the last good checkpoint
    runner.step(make_batch(5))
    install_ckpt_write_fail(saver, times=3)  # outlasts the 2 attempts
    assert saver.save(runner) is None        # coded degrade, no raise
    assert telemetry.get().registry.counter(
        "ckpt/save_failures").value == 1
    recs = [r for r in telemetry.get().step_records()
            if r.get("kind") == "fault"]
    assert any(r["fault"] == "ckpt_write_fail"
               and r["phase"] == "degraded"
               and r["last_good_step"] == good_step for r in recs)
    assert saver.latest_step() == good_step


def test_save_failure_without_degrade_is_typed(tmp_path):
    from autodist_tpu.checkpoint.saver import CheckpointSaveError
    from autodist_tpu.runtime.faults import install_ckpt_write_fail

    runner = train_some(PS())
    saver = Saver(str(tmp_path), retry=_fast_retry())
    install_ckpt_write_fail(saver, times=3)
    with pytest.raises(CheckpointSaveError) as ei:
        saver.save(runner)
    assert ei.value.step == runner.step_count


def test_async_save_failure_surfaces_with_step_at_next_join(tmp_path):
    """The satellite pin: a failed ASYNC commit surfaces as a typed
    error carrying the step that staged it — at the next save()/wait()/
    close(), never from an arbitrary later orbax call — and increments
    ckpt/async_save_failures."""
    from autodist_tpu import telemetry
    from autodist_tpu.checkpoint.saver import CheckpointSaveError
    from autodist_tpu.runtime.faults import install_ckpt_write_fail

    telemetry.reset()
    runner = train_some(PS())
    saver = Saver(str(tmp_path), async_save=True)
    staged = saver.save(runner)              # returns with commit in flight
    install_ckpt_write_fail(saver, times=1, where="commit")
    with pytest.raises(CheckpointSaveError) as ei:
        saver.wait()
    assert ei.value.step == staged
    assert f"step {staged}" in str(ei.value)
    assert telemetry.get().registry.counter(
        "ckpt/async_save_failures").value == 1
    # the failure was consumed: the next join is clean
    saver.wait()
    saver.close()


def test_async_save_failure_degrades_when_opted_in(tmp_path):
    from autodist_tpu import telemetry
    from autodist_tpu.runtime.faults import install_ckpt_write_fail

    telemetry.reset()
    runner = train_some(PS())
    saver = Saver(str(tmp_path), async_save=True, degrade_on_failure=True)
    staged = saver.save(runner)
    install_ckpt_write_fail(saver, times=1, where="commit")
    runner.step(make_batch(9))
    assert saver.save(runner) is not None    # next save joins + degrades
    recs = [r for r in telemetry.get().step_records()
            if r.get("kind") == "fault"]
    assert any(r["fault"] == "ckpt_write_fail"
               and r["phase"] == "degraded" and r["step"] == staged
               for r in recs)
    saver.close()
