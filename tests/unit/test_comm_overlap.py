"""Latency-hiding collectives for the tensor-parallel path.

The collective-matmul decomposition (``comm_overlap``): the row-parallel
output all-reduce splits into a reduce-scatter/all-gather pair
(``"rsag"``) or a chunked ``ppermute`` ring whose per-hop transfer
overlaps per-chunk compute (``"matmul"``).  Correctness is pinned the
way the dp×pp×tp composition was (``test_pipeline_tp.py``): goldens
against the blocking ``psum`` path and the sequential single-device
reference for tp ∈ {1, 2}, composed with ZeRO-1, bf16_ef, and virtual
stages — the decomposition may reorder float summation but must change
nothing else.  The HLO-structural half of the claim (zero monolithic
model-axis all-reduce, the ring's collective-permutes) lives in
``test_hlo_probe.py``.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu import AutoDist
from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
from autodist_tpu.models.transformer import TransformerConfig
from autodist_tpu.parallel.tensor import (collective_matmul_row,
                                          column_parallel,
                                          normalize_comm_overlap,
                                          psum_decomposed, row_parallel)

CFG = TransformerConfig(vocab_size=32, hidden_size=16, num_layers=2,
                        num_heads=2, mlp_dim=32, max_len=8,
                        dtype=jnp.float32, dropout_rate=0.0,
                        attention_dropout_rate=0.0)
SPEC_3D = {"topology": {"platform": "cpu", "num_devices": 8},
           "mesh": {"data": 2, "pipe": 2, "model": 2}}


def make_lm(opt=None, cfg=CFG, seed=0):
    return make_pipeline_lm_trainable(cfg, opt or optax.sgd(0.05),
                                      jax.random.PRNGKey(seed))


def lm_batches(n, seed=0):
    r = np.random.RandomState(seed)
    return [{"x": r.randint(0, CFG.vocab_size, (8, 8)).astype(np.int32),
             "y": r.randint(0, CFG.vocab_size, (8, 8)).astype(np.int32)}
            for _ in range(n)]


def train(runner, batches):
    losses = [float(np.asarray(runner.step(b, rng=jax.random.PRNGKey(0))
                               ["loss"])) for b in batches]
    return losses, runner.get_params()


def assert_trees_close(a, b, rtol=1e-5, atol=1e-6):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol), a, b)


# --------------------------------------------------------------------------- #
# Primitive-level goldens (pure shard_map, no pipeline)
# --------------------------------------------------------------------------- #
def _model_mesh(tp):
    return Mesh(np.array(jax.devices()[:tp]), ("model",))


@pytest.mark.parametrize("mode", ["rsag", "matmul"])
@pytest.mark.parametrize("tp,width", [(2, 10), (4, 10), (4, 12)])
def test_row_parallel_decomposed_matches_psum(mode, tp, width):
    """Forward AND both gradients of the decomposed row-parallel matmul
    match the blocking psum path — including output widths that don't
    divide the tp degree (the ring's zero-pad path)."""
    mesh = _model_mesh(tp)
    r = np.random.RandomState(0)
    x = r.randn(6, 8).astype(np.float32)
    k = r.randn(8, width).astype(np.float32)

    def run(fn, out_specs=P()):
        return jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=(P(None, "model"), P("model", None)),
            out_specs=out_specs, check_vma=False))

    def value(xs, ks, overlap):
        return row_parallel(xs, ks, model_axis="model",
                            comm_overlap=overlap)

    y_ref = run(lambda a, b: value(a, b, None))(x, k)
    y_dec = run(lambda a, b: value(a, b, mode))(x, k)
    np.testing.assert_allclose(np.asarray(y_dec), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-6)

    def grads(overlap):
        def loss(a, b):
            return jnp.sum(value(a, b, overlap) ** 2)
        return run(lambda a, b: jax.grad(loss, argnums=(0, 1))(a, b),
                   out_specs=(P(None, "model"), P("model", None)))(x, k)

    gx_ref, gk_ref = grads(None)
    gx, gk = grads(mode)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gk_ref),
                               rtol=1e-5, atol=1e-5)


def test_collective_matmul_row_axes2_and_column_backward():
    """The axes=2 contraction (attention out-proj shape) rides the ring,
    and column_parallel's decomposed backward cotangent reduction is
    exact."""
    mesh = _model_mesh(2)
    r = np.random.RandomState(1)
    x = r.randn(3, 4, 5).astype(np.float32)     # [B, heads, head_dim]
    k = r.randn(4, 5, 7).astype(np.float32)     # [heads, head_dim, H]

    def rowf(xs, ks):
        return collective_matmul_row(xs, ks, "model", 2)

    y = jax.jit(jax.shard_map(
        rowf, mesh=mesh, in_specs=(P(None, "model"), P("model",)),
        out_specs=P(), check_vma=False))(x, k)
    np.testing.assert_allclose(np.asarray(y), np.tensordot(x, k, axes=2),
                               rtol=1e-5, atol=1e-6)

    xc = r.randn(6, 8).astype(np.float32)
    kc = r.randn(8, 10).astype(np.float32)

    def col_grads(overlap):
        def loss(a, b):
            return jnp.sum(column_parallel(a, b, model_axis="model",
                                           comm_overlap=overlap) ** 2)
        return jax.jit(jax.shard_map(
            lambda a, b: jax.grad(loss, argnums=(0, 1))(a, b), mesh=mesh,
            in_specs=(P(), P(None, "model")),
            out_specs=(P(), P(None, "model")), check_vma=False))(xc, kc)

    gx_ref, gk_ref = col_grads(None)
    gx, gk = col_grads("rsag")
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gk_ref),
                               rtol=1e-5, atol=1e-5)


def test_psum_decomposed_matches_psum_and_stays_split():
    """psum_decomposed == psum numerically for a non-divisible payload,
    and its compiled HLO carries the reduce-scatter/all-gather pair with
    ZERO all-reduce — the optimization_barrier holds the re-fusion off
    (a reintroduced fused all-reduce fails here, in tier-1, on CPU)."""
    from tools.hlo_probe import collective_counts

    mesh = _model_mesh(4)
    x = np.arange(10, dtype=np.float32)

    def f(v):
        return psum_decomposed(v, "model")

    jitted = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(),
                                   out_specs=P(), check_vma=False))
    np.testing.assert_allclose(np.asarray(jitted(x)), x * 4, rtol=1e-6)
    counts = collective_counts(jitted.lower(x).compile().as_text())
    assert counts["all-reduce"] == 0, counts
    assert counts["reduce-scatter"] == 1 and counts["all-gather"] == 1, counts


def test_normalize_comm_overlap():
    assert normalize_comm_overlap(None) is None
    assert normalize_comm_overlap(False) is None
    assert normalize_comm_overlap("") is None
    assert normalize_comm_overlap(True) == "matmul"
    assert normalize_comm_overlap("rsag") == "rsag"
    with pytest.raises(ValueError, match="comm_overlap"):
        normalize_comm_overlap("bogus")


# --------------------------------------------------------------------------- #
# End-to-end goldens: overlapped pipeline == blocking pipeline == sequential
# --------------------------------------------------------------------------- #
def test_tp2_overlap_matches_blocking_and_sequential():
    """The headline golden: dp=2 × pp=2 × tp=2 training with BOTH
    decompositions reproduces the blocking-psum run and the sequential
    single-device reference — losses and parameters."""
    from tests.unit.test_pipeline_tp import sequential_train

    blk_l, blk_p = train(
        AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                 tensor_parallel=2).build(make_lm()), lm_batches(3))
    ref_p, ref_l = sequential_train(make_lm(), lm_batches(3))
    for mode in ("rsag", "matmul"):
        losses, params = train(
            AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                     tensor_parallel=2, comm_overlap=mode).build(make_lm()),
            lm_batches(3))
        np.testing.assert_allclose(losses, blk_l, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(losses, ref_l, rtol=1e-5, atol=1e-6)
        assert_trees_close(params, blk_p)
        assert_trees_close(params, ref_p)


@pytest.mark.slow
def test_tp1_overlap_is_a_noop():
    """tp=1 with the knob set: the builder records it, the lowering runs
    zero collectives either way, parity with the sequential reference is
    exact — completing the tp ∈ {1, 2} golden matrix."""
    from tests.unit.test_pipeline_tp import sequential_train

    spec = {"topology": {"platform": "cpu", "num_devices": 8},
            "mesh": {"data": 4, "pipe": 2}}
    runner = AutoDist(spec, "Pipeline", num_microbatches=2,
                      comm_overlap="matmul").build(make_lm())
    losses, params = train(runner, lm_batches(2))
    ref_p, ref_l = sequential_train(make_lm(), lm_batches(2))
    np.testing.assert_allclose(losses, ref_l, rtol=1e-5, atol=1e-6)
    assert_trees_close(params, ref_p)


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["rsag", "matmul"])
def test_tp2_overlap_composes_with_zero1(mode):
    r0 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, zero1=True).build(make_lm())
    r1 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, zero1=True,
                  comm_overlap=mode).build(make_lm())
    l0, p0 = train(r0, lm_batches(2))
    l1, p1 = train(r1, lm_batches(2))
    np.testing.assert_allclose(l1, l0, rtol=1e-5, atol=1e-6)
    assert_trees_close(p1, p0)


@pytest.mark.slow
def test_tp2_overlap_composes_with_bf16_ef():
    r0 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, compressor="bf16_ef").build(make_lm())
    r1 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, compressor="bf16_ef",
                  comm_overlap="matmul").build(make_lm())
    l0, p0 = train(r0, lm_batches(2))
    l1, p1 = train(r1, lm_batches(2))
    # bf16 wire quantization amplifies the summation-order difference;
    # the runs must stay close, not bitwise-equal.
    np.testing.assert_allclose(l1, l0, rtol=1e-4, atol=1e-4)
    assert_trees_close(p1, p0, rtol=5e-3, atol=5e-4)


@pytest.mark.slow
def test_tp2_overlap_composes_with_virtual_stages():
    """Megatron interleaving (V=2, 4 logical stages) under the chunked
    collective matmul — the ring-in-a-ring composition."""
    cfg4 = TransformerConfig(vocab_size=32, hidden_size=16, num_layers=4,
                             num_heads=2, mlp_dim=32, max_len=8,
                             dtype=jnp.float32, dropout_rate=0.0,
                             attention_dropout_rate=0.0)
    r0 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=4,
                  virtual_stages=2, tensor_parallel=2).build(
                      make_lm(cfg=cfg4, seed=1))
    r1 = AutoDist(SPEC_3D, "Pipeline", num_microbatches=4,
                  virtual_stages=2, tensor_parallel=2,
                  comm_overlap="matmul").build(make_lm(cfg=cfg4, seed=1))
    l0, p0 = train(r0, lm_batches(2))
    l1, p1 = train(r1, lm_batches(2))
    np.testing.assert_allclose(l1, l0, rtol=1e-5, atol=1e-6)
    assert_trees_close(p1, p0)


# --------------------------------------------------------------------------- #
# Strategy IR + lowering contracts
# --------------------------------------------------------------------------- #
def test_comm_overlap_ir_round_trip_and_validation():
    """The comm_overlap field survives serialization per variable and in
    the graph knob (chief→worker handoff); True canonicalizes to
    'matmul'; a non-overlap-aware stage_fn is rejected loudly."""
    from autodist_tpu.strategy.ir import Strategy

    ad = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2, comm_overlap=True)
    strategy = ad.build_or_load_strategy(make_lm())
    assert strategy.graph_config.parallel["comm_overlap"] == "matmul"
    clone = Strategy.from_json(strategy.to_json())
    by_name = {n.var_name: n for n in clone.node_configs}
    # tp-sharded vars carry the mode; model-replicated ones don't.
    assert by_name["stages/mlp/wo/kernel"].partitioner.comm_overlap == \
        "matmul"
    assert by_name["stages/attention/qkv/kernel"].partitioner.comm_overlap \
        == "matmul"
    assert by_name["stages/ln_mlp/scale"].partitioner.comm_overlap is None

    # a stage_fn without the comm_overlap keyword cannot honor the knob
    from autodist_tpu import PipelineTrainable
    stacked = {"wi": {"kernel": jnp.zeros((2, 8, 16))},
               "wo": {"kernel": jnp.zeros((2, 16, 8))}}
    mlp = PipelineTrainable(
        lambda p, x, model_axis=None: x, stacked,
        lambda o, b: (jnp.mean(o), {}), optax.sgd(0.1), num_stages=2)
    with pytest.raises(ValueError, match="comm_overlap"):
        AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                 tensor_parallel=2, comm_overlap="rsag").build(mlp)


def test_hand_edited_per_variable_overlap_drives_lowering():
    """A strategy whose graph knob is unset but whose tp-sharded node
    configs carry comm_overlap still lowers decomposed (the per-layer
    selectability the IR field exists for); disagreeing modes are
    rejected."""
    ad = AutoDist(SPEC_3D, "Pipeline", num_microbatches=2,
                  tensor_parallel=2)
    strategy = ad.build_or_load_strategy(make_lm())
    strategy.graph_config.parallel["comm_overlap"] = None
    tp_nodes = [n for n in strategy.node_configs
                if n.partitioner is not None and n.partitioner.spec
                and "model" in n.partitioner.spec[1:]]
    assert tp_nodes
    for n in tp_nodes:
        n.partitioner.comm_overlap = "rsag"
    runner = AutoDist(SPEC_3D).build(make_lm(), strategy)
    losses, _ = train(runner, lm_batches(1))
    assert np.isfinite(losses).all()

    tp_nodes[0].partitioner.comm_overlap = "matmul"
    with pytest.raises(ValueError, match="disagree"):
        AutoDist(SPEC_3D).build(make_lm(), strategy)


# --------------------------------------------------------------------------- #
# Overlap-aware cost model
# --------------------------------------------------------------------------- #
def _hinted_lm():
    t = make_lm()
    t.tokens_per_step = 4096
    t.act_bytes_per_token = 64.0
    return t


@pytest.mark.parametrize("profile", [
    None,
    {"ici_gbps": 1.0},                    # starved link: comm-bound
    {"ici_gbps": 400.0},                  # fat link
    {"hop_alpha_s": 1e-4},                # latency-dominated
    {"hop_alpha_s": 1e-7, "ici_gbps": 10.0},
    {"mxu_efficiency": 0.05},             # slow compute hides more comm
])
def test_cost_model_ranks_overlap_at_or_below_blocking(profile):
    """For EVERY calibrated link profile the overlapped variant prices
    ≤ the blocking one (the lowering can always fall back to the fused
    all-reduce, so the model caps at the blocking envelope), with the
    same wire bytes reported and a feasible-memory story unchanged."""
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator.cost_model import CostModel
    from autodist_tpu.strategy.parallel_builders import Pipeline

    rs = ResourceSpec(SPEC_3D)
    cm = CostModel(rs, link_profile=profile)
    t = _hinted_lm()
    blk = cm.strategy_cost(
        t, Pipeline(num_microbatches=2, tensor_parallel=2).build(t, rs))
    for mode in ("rsag", "matmul"):
        ov = cm.strategy_cost(
            t, Pipeline(num_microbatches=2, tensor_parallel=2,
                        comm_overlap=mode).build(t, rs))
        assert ov.comm_time_s <= blk.comm_time_s * (1 + 1e-12)
        assert ov.score <= blk.score * (1 + 1e-12)
        # same wire volume — the decomposition moves bytes differently,
        # it does not remove them
        assert ov.comm_bytes == pytest.approx(blk.comm_bytes)
        assert ov.mem_bytes_per_device == pytest.approx(
            blk.mem_bytes_per_device)


def test_cost_model_overlap_wins_when_compute_hides_hops():
    """On a link profile where chunk compute genuinely covers hop
    latency the overlapped plan is STRICTLY cheaper — the lever
    AutoStrategy's comm_overlap candidate exists to exploit."""
    from autodist_tpu.resource import ResourceSpec
    from autodist_tpu.simulator.cost_model import CostModel
    from autodist_tpu.strategy.parallel_builders import Pipeline

    rs = ResourceSpec(SPEC_3D)
    cm = CostModel(rs, link_profile={"hop_alpha_s": 1e-7,
                                     "ici_gbps": 10.0,
                                     "mxu_efficiency": 0.01})
    t = _hinted_lm()
    blk = cm.strategy_cost(
        t, Pipeline(num_microbatches=2, tensor_parallel=2).build(t, rs))
    ov = cm.strategy_cost(
        t, Pipeline(num_microbatches=2, tensor_parallel=2,
                    comm_overlap="matmul").build(t, rs))
    assert ov.comm_time_s < blk.comm_time_s


def test_calibration_link_section_reaches_cost_model(tmp_path):
    """A measured 'link' section in calibration.json lands in
    LINK_PROFILE and the CostModel picks it up (per-instance overrides
    still win)."""
    import json

    from autodist_tpu.simulator import cost_model as cm

    path = tmp_path / "measured.json"
    path.write_text(json.dumps(
        {"meta": {"backend": "v5e"},
         "compressor_factor": {},
         "link": {"ici_gbps": 123.0, "hop_alpha_s": 2e-6}}))
    saved = dict(cm.LINK_PROFILE)
    try:
        cm.load_calibration(str(path))
        assert cm.LINK_PROFILE["ici_gbps"] == 123.0
        from autodist_tpu.resource import ResourceSpec
        model = cm.CostModel(ResourceSpec(SPEC_3D))
        assert model.link_profile["ici_gbps"] == 123.0
        override = cm.CostModel(ResourceSpec(SPEC_3D),
                                link_profile={"ici_gbps": 7.0})
        assert override.link_profile["ici_gbps"] == 7.0
        assert override.link_profile["hop_alpha_s"] == 2e-6
    finally:
        cm.LINK_PROFILE.clear()
        cm.LINK_PROFILE.update(saved)


def test_latency_hiding_flags_knob(monkeypatch):
    """The runner knob: off by default; refused on non-TPU targets (XLA
    aborts on flags its build doesn't define); applied into XLA_FLAGS
    for TPU targets; a '--'-prefixed value replaces the default list
    (the escape hatch for jaxlib flag drift)."""
    from autodist_tpu.kernel import lowering as kl

    env = {}
    monkeypatch.delenv("AUTODIST_TPU_ASYNC_COLLECTIVES", raising=False)
    assert kl.apply_latency_hiding_flags(env, platform="tpu") is False

    monkeypatch.setenv("AUTODIST_TPU_ASYNC_COLLECTIVES", "1")
    assert kl.apply_latency_hiding_flags(env, platform="cpu") is False
    assert "XLA_FLAGS" not in env

    assert kl.apply_latency_hiding_flags(env, platform="tpu") is True
    for flag in kl.LATENCY_HIDING_XLA_FLAGS:
        assert flag in env["XLA_FLAGS"]
    # idempotent
    before = env["XLA_FLAGS"]
    assert kl.apply_latency_hiding_flags(env, platform="tpu") is True
    assert env["XLA_FLAGS"] == before

    monkeypatch.setenv("AUTODIST_TPU_ASYNC_COLLECTIVES",
                       "--xla_custom_flag=true")
    custom = {}
    assert kl.apply_latency_hiding_flags(custom, platform="tpu") is True
    assert custom["XLA_FLAGS"] == "--xla_custom_flag=true"

    monkeypatch.setenv("AUTODIST_TPU_ASYNC_COLLECTIVES", "0")
    assert kl.apply_latency_hiding_flags({}, platform="tpu") is False

    # platform=auto honors the JAX_PLATFORMS pin over libtpu detection
    monkeypatch.setenv("AUTODIST_TPU_ASYNC_COLLECTIVES", "1")
    assert kl.apply_latency_hiding_flags(
        {"JAX_PLATFORMS": "cpu"}, platform="auto") is False


def test_auto_strategy_candidates_include_comm_overlap():
    from autodist_tpu.simulator.auto_strategy import default_candidates
    from autodist_tpu.strategy.parallel_builders import Pipeline

    overlapped = [b for b in default_candidates()
                  if isinstance(b, Pipeline) and b.comm_overlap]
    assert overlapped and overlapped[0].comm_overlap == "matmul"
    assert overlapped[0].tensor_parallel == 2
