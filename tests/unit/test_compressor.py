"""Compressor tests (≙ reference compressor hierarchy coverage)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from autodist_tpu.kernel.compressor import Compressor


def run_allreduce(comp, x_per_device):
    """Drive compressor.allreduce inside a shard_map over 8 devices."""
    mesh = jax.make_mesh((8,), ("data",))
    state = comp.init_state(x_per_device[0])
    state_in = (jnp.stack([state] * 8) if state is not None
                else jnp.zeros((8, 1)))

    def f(x, s):
        st = s[0] if comp.stateful else None
        out, new_st = comp.allreduce(x[0], st, "data")
        new_s = new_st[None] if comp.stateful else s
        return out[None], new_s

    g = jax.shard_map(f, mesh=mesh, in_specs=(P("data"), P("data")),
                      out_specs=(P("data"), P("data")), check_vma=False)
    out, new_state = g(jnp.stack(x_per_device), state_in)
    return np.asarray(out), np.asarray(new_state)


@pytest.mark.parametrize("name", ["none", "fp16", "bf16"])
def test_stateless_mean(name):
    comp = Compressor.create(name)
    xs = [jnp.full((4, 4), float(i)) for i in range(8)]
    out, _ = run_allreduce(comp, xs)
    tol = {"none": 1e-6, "fp16": 1e-2, "bf16": 5e-2}[name]
    np.testing.assert_allclose(out[0], np.full((4, 4), 3.5), rtol=tol, atol=tol)
    # every device gets the same reduced value
    for i in range(8):
        np.testing.assert_array_equal(out[i], out[0])


@pytest.mark.parametrize("name", ["fp16_ef", "bf16_ef", "int8_ef"])
def test_error_feedback_accumulates(name):
    comp = Compressor.create(name)
    assert comp.stateful
    xs = [jnp.full((8,), 1.0 + 1e-4 * i) for i in range(8)]
    out, state = run_allreduce(comp, xs)
    np.testing.assert_allclose(out[0], np.mean([1.0 + 1e-4 * i for i in range(8)]),
                               rtol=5e-2)
    # residual = value - wire(value): bounded by quantization error
    assert np.all(np.isfinite(state))


@pytest.mark.slow
def test_ef_unbiased_over_steps():
    """Error feedback: average of compressed grads over many steps must
    approach the true mean (the point of the EF mixin)."""
    comp = Compressor.create("int8_ef")
    mesh = jax.make_mesh((8,), ("data",))
    true_vals = jnp.linspace(0.9999, 1.0001, 8)

    def f(x, s):
        out, ns = comp.allreduce(x[0], s[0], "data")
        return out[None], ns[None]

    g = jax.shard_map(f, mesh=mesh, in_specs=(P("data"), P("data")),
                      out_specs=(P("data"), P("data")), check_vma=False)
    state = jnp.zeros((8, 8))
    x = jnp.stack([jnp.full((8,), v) for v in true_vals])
    acc = 0.0
    steps = 50
    for _ in range(steps):
        out, state = g(x, state)
        acc = acc + np.asarray(out)[0]
    np.testing.assert_allclose(acc / steps,
                               float(jnp.mean(true_vals)), rtol=1e-5)


def test_unknown_compressor_raises():
    with pytest.raises(ValueError):
        Compressor.create("powersgd9000")


def test_powersgd_exact_for_low_rank():
    """A gradient whose matrix form is exactly rank-1 (identical across
    devices) must be reconstructed (nearly) exactly by rank-2 PowerSGD
    in one step: P spans col(M) for a generic start Q."""
    comp = Compressor.create("powersgd:2")
    total = 64  # reshapes to 8x8
    u = np.linspace(1.0, 2.0, 8).astype(np.float32)
    v = np.linspace(-1.0, 1.0, 8).astype(np.float32)
    flat = jnp.asarray(np.outer(u, v).reshape(-1))
    xs = [flat for _ in range(8)]
    out, state = run_allreduce(comp, xs)
    np.testing.assert_allclose(out[0], np.asarray(flat), rtol=1e-4,
                               atol=1e-5)
    assert state.shape[1] == len(comp.init_state_flat(total))
    assert np.all(np.isfinite(state))


def test_powersgd_ef_converges_over_steps():
    """Full-rank gradients are approximated; with error feedback the
    *running sum* of compressed outputs approaches the sum of true means
    (EF's guarantee), and the warm-started Q improves per-step quality."""
    comp = Compressor.create("powersgd")
    mesh = jax.make_mesh((8,), ("data",))
    r = np.random.RandomState(0)
    true = r.randn(8, 100).astype(np.float32)  # per-device constant grads
    state = jnp.stack([comp.init_state(jnp.zeros(100))] * 8)

    def f(x, s):
        out, new_st = comp.allreduce(x[0], s[0], "data")
        return out[None], new_st[None]

    g = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P("data")), check_vma=False))
    total_out = np.zeros(100, np.float32)
    mean_true = true.mean(axis=0)
    errs = {}
    for step in range(1, 41):
        out, state = g(jnp.asarray(true), state)
        total_out += np.asarray(out)[0]
        if step in (10, 40):
            errs[step] = np.abs(total_out / step - mean_true).max()
    # EF makes the running mean of compressed grads track the true mean:
    # the residual keeps re-injecting what rank-2 missed, so error falls.
    assert errs[40] < errs[10] * 0.6, errs
    np.testing.assert_allclose(total_out / 40, mean_true, atol=0.1)


def test_powersgd_trains_end_to_end():
    import optax

    from autodist_tpu import AllReduce, AutoDist, Trainable

    k = jax.random.PRNGKey(0)
    params = {"w": jax.random.normal(k, (32, 32)) * 0.1}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    t = Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.2))
    runner = AutoDist({}, AllReduce(compressor="powersgd:4")).build(t)
    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 32).astype(np.float32),
             "y": rng.randn(16, 32).astype(np.float32)}
    losses = [float(np.asarray(runner.step(batch)["loss"]))
              for _ in range(12)]
    assert losses[-1] < losses[0] * 0.7


def test_compressor_arg_parsing():
    assert Compressor.create("powersgd:8").rank == 8
    with pytest.raises(ValueError):
        Compressor.create("fp16:2")


@pytest.mark.slow
def test_int8_ring_matches_true_mean():
    """The hand-built int8 ring must agree with the true mean to
    quantization tolerance, for total sizes that do and don't divide
    the ring."""
    comp = Compressor.create("int8_ring")
    assert comp.stateful
    r = np.random.RandomState(1)
    for total in (64, 100, 7, 1):
        xs = [jnp.asarray(r.randn(total).astype(np.float32))
              for _ in range(8)]
        out, state = run_allreduce(comp, xs)
        true = np.mean([np.asarray(x) for x in xs], axis=0)
        np.testing.assert_allclose(out[0], true, atol=0.1, rtol=0.1)
        for i in range(8):  # every device reconstructs the same value
            np.testing.assert_array_equal(out[i], out[0])
        assert np.all(np.isfinite(state))


def test_int8_ring_ef_converges_over_steps():
    comp = Compressor.create("int8_ring")
    mesh = jax.make_mesh((8,), ("data",))
    r = np.random.RandomState(0)
    true = r.randn(8, 96).astype(np.float32)
    state = jnp.zeros((8, 96), jnp.float32)

    def f(x, s):
        out, new_st = comp.allreduce(x[0], s[0], "data")
        return out[None], new_st[None]

    g = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P("data")), check_vma=False))
    total_out = np.zeros(96, np.float32)
    for _ in range(20):
        out, state = g(jnp.asarray(true), state)
        total_out += np.asarray(out)[0]
    np.testing.assert_allclose(total_out / 20, true.mean(axis=0),
                               atol=0.03)


def test_int8_ring_trains_end_to_end():
    import optax

    from autodist_tpu import AllReduce, AutoDist, Trainable

    k = jax.random.PRNGKey(0)
    params = {"w": jax.random.normal(k, (32, 16)) * 0.1}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    t = Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.2))
    runner = AutoDist({}, AllReduce(compressor="int8_ring")).build(t)
    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 32).astype(np.float32),
             "y": rng.randn(16, 16).astype(np.float32)}
    losses = [float(np.asarray(runner.step(batch)["loss"]))
              for _ in range(12)]
    assert losses[-1] < losses[0] * 0.7
