"""Compressor tests (≙ reference compressor hierarchy coverage)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from autodist_tpu.kernel.compressor import Compressor


def run_allreduce(comp, x_per_device):
    """Drive compressor.allreduce inside a shard_map over 8 devices."""
    mesh = jax.make_mesh((8,), ("data",))
    state = comp.init_state(x_per_device[0])
    state_in = (jnp.stack([state] * 8) if state is not None
                else jnp.zeros((8, 1)))

    def f(x, s):
        st = s[0] if comp.stateful else None
        out, new_st = comp.allreduce(x[0], st, "data")
        new_s = new_st[None] if comp.stateful else s
        return out[None], new_s

    g = jax.shard_map(f, mesh=mesh, in_specs=(P("data"), P("data")),
                      out_specs=(P("data"), P("data")), check_vma=False)
    out, new_state = g(jnp.stack(x_per_device), state_in)
    return np.asarray(out), np.asarray(new_state)


@pytest.mark.parametrize("name", ["none", "fp16", "bf16"])
def test_stateless_mean(name):
    comp = Compressor.create(name)
    xs = [jnp.full((4, 4), float(i)) for i in range(8)]
    out, _ = run_allreduce(comp, xs)
    tol = {"none": 1e-6, "fp16": 1e-2, "bf16": 5e-2}[name]
    np.testing.assert_allclose(out[0], np.full((4, 4), 3.5), rtol=tol, atol=tol)
    # every device gets the same reduced value
    for i in range(8):
        np.testing.assert_array_equal(out[i], out[0])


@pytest.mark.parametrize("name", ["fp16_ef", "bf16_ef", "int8_ef"])
def test_error_feedback_accumulates(name):
    comp = Compressor.create(name)
    assert comp.stateful
    xs = [jnp.full((8,), 1.0 + 1e-4 * i) for i in range(8)]
    out, state = run_allreduce(comp, xs)
    np.testing.assert_allclose(out[0], np.mean([1.0 + 1e-4 * i for i in range(8)]),
                               rtol=5e-2)
    # residual = value - wire(value): bounded by quantization error
    assert np.all(np.isfinite(state))


def test_ef_unbiased_over_steps():
    """Error feedback: average of compressed grads over many steps must
    approach the true mean (the point of the EF mixin)."""
    comp = Compressor.create("int8_ef")
    mesh = jax.make_mesh((8,), ("data",))
    true_vals = jnp.linspace(0.9999, 1.0001, 8)

    def f(x, s):
        out, ns = comp.allreduce(x[0], s[0], "data")
        return out[None], ns[None]

    g = jax.shard_map(f, mesh=mesh, in_specs=(P("data"), P("data")),
                      out_specs=(P("data"), P("data")), check_vma=False)
    state = jnp.zeros((8, 8))
    x = jnp.stack([jnp.full((8,), v) for v in true_vals])
    acc = 0.0
    steps = 50
    for _ in range(steps):
        out, state = g(x, state)
        acc = acc + np.asarray(out)[0]
    np.testing.assert_allclose(acc / steps,
                               float(jnp.mean(true_vals)), rtol=1e-5)


def test_unknown_compressor_raises():
    with pytest.raises(ValueError):
        Compressor.create("powersgd9000")
