"""The conftest TPU-only-import collection guard (CI hygiene): an
unmarked tier-1 test module must not import TPU-only paths."""
import textwrap

from conftest import TPU_ONLY_IMPORT_PREFIXES, _tpu_only_imports


def test_detects_top_level_tpu_imports(tmp_path):
    mod = tmp_path / "test_x.py"
    mod.write_text(textwrap.dedent("""
        import jax.experimental.pallas.tpu as pltpu
        from autodist_tpu.ops.flash_attention import flash_attention

        def test_a():
            pass
    """))
    found = _tpu_only_imports(str(mod))
    assert "jax.experimental.pallas.tpu" in found
    assert "autodist_tpu.ops.flash_attention" in found


def test_function_local_imports_are_not_flagged(tmp_path):
    mod = tmp_path / "test_y.py"
    mod.write_text(textwrap.dedent("""
        import jax

        def test_b():
            from autodist_tpu.ops.flash_attention import flash_attention
            assert flash_attention
    """))
    # A buried import is a runtime gate the test owns; the guard only
    # polices top-level imports that break collection.
    assert _tpu_only_imports(str(mod)) == set()


def test_prefix_table_is_nonempty():
    assert "libtpu" in TPU_ONLY_IMPORT_PREFIXES
