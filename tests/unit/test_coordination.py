"""Native host-coordination service tests.

Mirrors the reference's coordination semantics: token-queue barriers
(``ps_synchronizer.py:335-385``), bounded-staleness SSP validated by
*timing* the way the reference's c9 case did — a slow worker sleeps and
the fast worker asserts which steps were/weren't blocked given the
staleness bound (``tests/integration/cases/c9.py:92-126``) — and the
chief→worker strategy handoff (``coordinator.py:66-90``) over KV instead
of SFTP.
"""
import threading
import time

import pytest

from autodist_tpu.runtime.coordination import (CoordClient, CoordServer,
                                               SSPController)


@pytest.fixture()
def server():
    with CoordServer() as s:
        yield s


def client(server):
    return CoordClient("127.0.0.1", server.port)


def test_kv_put_get(server):
    with client(server) as c:
        c.put("strategy/abc", b"proto-bytes")
        assert c.get("strategy/abc") == b"proto-bytes"
        assert c.get("missing", timeout_ms=50) is None


def test_kv_blocking_get_unblocks_on_put(server):
    """Worker blocks on the strategy key until the chief publishes it
    (the chief-builds/workers-load handoff)."""
    got = {}

    def worker():
        with client(server) as c:
            got["val"] = c.get("strategy/late", timeout_ms=5000)

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.15)
    with client(server) as c:
        c.put("strategy/late", b"s1")
    t.join(timeout=5)
    assert got["val"] == b"s1"


def test_counter(server):
    with client(server) as c:
        assert c.counter_add("steps", 1) == 1
        assert c.counter_add("steps", 5) == 6
        assert c.counter_add("other", 2) == 2


def test_queue_fifo_and_blocking(server):
    with client(server) as c:
        c.queue_put("tokens", b"a")
        c.queue_put("tokens", b"b")
        assert c.queue_get("tokens") == b"a"
        assert c.queue_get("tokens") == b"b"
        assert c.queue_get("tokens", timeout_ms=50) is None


def test_barrier_three_participants(server):
    n = 3
    release_times = []

    def participant(delay):
        with client(server) as c:
            time.sleep(delay)
            assert c.barrier("start", n, timeout_ms=10000)
            release_times.append(time.monotonic())

    threads = [threading.Thread(target=participant, args=(d,))
               for d in (0.0, 0.1, 0.3)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(release_times) == n
    # Nobody released before the last participant arrived (~0.3s).
    assert min(release_times) - t0 > 0.25
    # All released together.
    assert max(release_times) - min(release_times) < 0.2


def test_barrier_reusable(server):
    """Generation counter lets the same name be used every step."""
    n = 2
    done = []

    def participant():
        with client(server) as c:
            for _ in range(3):
                assert c.barrier("step", n, timeout_ms=10000)
            done.append(True)

    threads = [threading.Thread(target=participant) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(done) == n


def test_barrier_timeout(server):
    with client(server) as c:
        assert not c.barrier("lonely", 2, timeout_ms=100)


def test_ssp_timing_bounded_staleness(server):
    """c9-style: staleness=2 lets the fast worker run at most 3 steps
    ahead; it must block on step 3 until the slow worker finishes step 0."""
    staleness = 2
    fast_step_starts = {}
    slow_started = threading.Event()

    def fast():
        with client(server) as c:
            ssp = SSPController(c, "fast", staleness, num_workers=2)
            slow_started.wait(5)
            for step in range(5):
                assert ssp.start_step(step)
                fast_step_starts[step] = time.monotonic()
                ssp.finish_step(step)

    def slow():
        with client(server) as c:
            slow_started.set()
            ssp = SSPController(c, "slow", staleness, num_workers=2)
            for step in range(5):
                assert ssp.start_step(step)
                time.sleep(0.3)  # slow worker: 0.3s per step
                ssp.finish_step(step)

    t0 = time.monotonic()
    ts = [threading.Thread(target=fast), threading.Thread(target=slow)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)

    # Steps 0..2 ran immediately (within the staleness window).
    assert fast_step_starts[2] - t0 < 0.25
    # Step 3 had to wait for slow's step 0 (~0.3s); step 4 for slow's
    # step 1 (~0.6s).
    assert fast_step_starts[3] - t0 > 0.25
    assert fast_step_starts[4] - t0 > 0.55


def test_ssp_zero_staleness_is_lockstep(server):
    """staleness=0: the fast worker can never start step k+1 before every
    worker finished step k."""
    order = []

    def worker(name, delay):
        with client(server) as c:
            # num_workers barriers registration so neither races ahead
            ssp = SSPController(c, name, staleness=0, num_workers=2)
            for step in range(3):
                assert ssp.start_step(step)
                order.append((name, step))
                time.sleep(delay)
                ssp.finish_step(step)

    ts = [threading.Thread(target=worker, args=("fast", 0.0)),
          threading.Thread(target=worker, args=("slow", 0.1))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)

    # Lockstep: every step k for both workers precedes step k+1 anywhere.
    last_of = {}
    for i, (_, step) in enumerate(order):
        last_of[step] = i
    first_of = {}
    for i, (_, step) in reversed(list(enumerate(order))):
        first_of[step] = i
    for k in range(2):
        assert last_of[k] < first_of[k + 1]


def test_large_value_roundtrip(server):
    """Strategy protos can be MBs; exercise a 4 MB value."""
    blob = bytes(range(256)) * (4 * 1024 * 16)
    with client(server) as c:
        c.put("big", blob)
        assert c.get("big") == blob


@pytest.mark.slow
def test_cluster_strategy_handoff_over_service(tmp_path):
    """End-to-end chief→worker handoff: the chief's Cluster starts the
    native service, publishes the strategy to KV, and a worker *process*
    loads it through build_or_load_strategy (no shared filesystem)."""
    import os
    import sys

    from autodist_tpu import ResourceSpec
    from autodist_tpu.runtime.cluster import Cluster
    from autodist_tpu.strategy.ir import (AllReduceSynchronizer, GraphConfig,
                                          NodeConfig, Strategy)

    strategy = Strategy(
        node_configs=[NodeConfig(var_name="w",
                                 synchronizer=AllReduceSynchronizer())],
        graph_config=GraphConfig(replicas=1))
    out = tmp_path / "loaded.txt"
    script = tmp_path / "worker.py"
    # The worker only exercises the strategy handoff, not jax.distributed:
    # neutralize the multihost markers before importing the facade.
    script.write_text(
        "import os\n"
        "os.environ['AUTODIST_TPU_NUM_PROCESSES'] = '1'\n"
        "from autodist_tpu.autodist import AutoDist\n"
        "ad = AutoDist({})\n"
        "s = ad.build_or_load_strategy(trainable=None)\n"
        f"open({str(out)!r}, 'w').write(s.id + '|' + s.node_configs[0].var_name)\n"
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    cluster = Cluster(ResourceSpec({}), hosts=["localhost"])
    try:
        # launch_clients starts the service and publishes the strategy.
        cluster.launch_clients(
            strategy, argv=[sys.executable, str(script)],
            extra_env={"PYTHONPATH": repo_root, "JAX_PLATFORMS": "cpu",
                       # no shared strategy dir: KV is the only channel
                       "AUTODIST_TPU_WORKING_DIR": str(tmp_path / "scratch")})
        cluster.join(timeout=60)
    finally:
        cluster.terminate()
    got = out.read_text().split("|")
    assert got == [strategy.id, "w"]


def test_auth_rejects_wrong_token(server):
    """A connection with a bad (or missing) token must be refused before
    it can touch barriers/KV (the strategy-handoff surface)."""
    with pytest.raises(OSError, match="token rejected|could not connect"):
        CoordClient("127.0.0.1", server.port, connect_timeout_ms=2000,
                    token="wrong-" + server.token)
    # No token: the TCP connect succeeds but the first request is refused
    # and the connection dropped.
    c = CoordClient("127.0.0.1", server.port, connect_timeout_ms=2000,
                    token="")
    with pytest.raises(OSError):
        c.put("k", b"v")
    c.close()
    # The right token still works afterwards.
    with CoordClient("127.0.0.1", server.port, token=server.token) as c:
        c.put("k", b"v")
        assert c.get("k") == b"v"


def test_bind_host_restricts_interface():
    """bind_host=127.0.0.1 keeps the service off external interfaces."""
    with CoordServer(bind_host="127.0.0.1") as s:
        with CoordClient("127.0.0.1", s.port, token=s.token) as c:
            c.put("x", b"1")
            assert c.get("x") == b"1"


# --------------------------------------------------------------------------- #
# Reconnect-and-retry (chaos-hardened runtime): the happy path stays one
# native call; a bounced server is survived; exhaustion is typed.
# --------------------------------------------------------------------------- #
def test_happy_path_never_reconnects(server, monkeypatch):
    """Both-ways pin: with no fault, adopting the retry policy changes
    nothing — the client never dials a reconnect."""
    with client(server) as c:
        dialed = []
        monkeypatch.setattr(c, "_reconnect",
                            lambda: dialed.append(1) or (_ for _ in ()))
        c.put("k", b"v")
        assert c.get("k") == b"v"
        assert c.counter_add("n", 1) == 1
        assert dialed == []


def test_reconnect_on_server_bounce_mid_get():
    """The coord_drop fault: a client blocked in get survives the
    server stopping and restarting on the same port, and still
    receives the value published after the bounce."""
    from autodist_tpu.runtime.coordination import CoordServer
    from autodist_tpu.runtime.retry import RetryPolicy

    s = CoordServer()
    port, token = s.port, s.token
    c = CoordClient("127.0.0.1", port, token=token,
                    retry=RetryPolicy(max_attempts=10, base_delay_s=0.2,
                                      cap_delay_s=0.5, deadline_s=30.0,
                                      seed=0))
    got = {}
    t = threading.Thread(
        target=lambda: got.update(v=c.get("late", timeout_ms=20000)))
    t.start()
    try:
        time.sleep(0.3)          # the get is blocked server-side
        s.stop()                 # ... and its socket just died
        time.sleep(0.3)
        s = CoordServer(port=port, token=token)   # chief comes back
        with CoordClient("127.0.0.1", port, token=token) as pub:
            pub.put("late", b"value")
        t.join(timeout=25)
        assert not t.is_alive(), "client never recovered from the bounce"
        assert got.get("v") == b"value"
    finally:
        c.close()
        s.stop()


def test_exhausted_retries_raise_typed_unavailable():
    from autodist_tpu.runtime.coordination import (CoordServer,
                                                   CoordUnavailableError)
    from autodist_tpu.runtime.retry import RetryPolicy

    assert issubclass(CoordUnavailableError, OSError)  # legacy handlers
    s = CoordServer()
    c = CoordClient("127.0.0.1", s.port, token=s.token,
                    retry=RetryPolicy(max_attempts=2, base_delay_s=0.05,
                                      cap_delay_s=0.05, seed=0))
    s.stop()   # the service is gone for good
    with pytest.raises(CoordUnavailableError, match="unavailable"):
        c.put("k", b"v")
    c.close()


def test_retry_opt_out_keeps_raw_oserror():
    from autodist_tpu.runtime.coordination import (CoordServer,
                                                   CoordUnavailableError)

    s = CoordServer()
    c = CoordClient("127.0.0.1", s.port, token=s.token, retry=None)
    s.stop()
    with pytest.raises(OSError) as ei:
        c.put("k", b"v")
    assert not isinstance(ei.value, CoordUnavailableError)
    c.close()


def test_concurrent_spawns_reserve_distinct_ports():
    """The held-socket port election: ``reserve_coord_port`` keeps the
    elected ephemeral port BOUND until the server adopts the fd, so N
    concurrent spawns can never elect the same port — the old
    bind-then-release probe raced exactly in the gap between election
    and serve, and two clusters starting at once could collide."""
    from autodist_tpu.runtime.coordination import reserve_coord_port

    n = 12
    socks = [reserve_coord_port() for _ in range(n)]   # all held at once
    ports = [s.getsockname()[1] for s in socks]
    assert len(set(ports)) == n, f"duplicate reserved ports: {ports}"
    servers: list = [None] * n
    errors: list = [None] * n

    def adopt(i):
        try:
            servers[i] = CoordServer(listen_sock=socks[i])
        except Exception as e:    # noqa: BLE001 — surfaced below
            errors[i] = e

    threads = [threading.Thread(target=adopt, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    try:
        assert errors == [None] * n, errors
        # each server serves on exactly the port its reservation held,
        # and actually answers on it
        for i, s in enumerate(servers):
            assert s.port == ports[i]
            with CoordClient("127.0.0.1", s.port, token=s.token) as c:
                c.put("spawn/port", str(ports[i]).encode())
                assert c.get("spawn/port") == str(ports[i]).encode()
    finally:
        for s in servers:
            if s is not None:
                s.stop()
