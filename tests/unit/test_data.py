"""Input-pipeline tests: prefetch, feed contract, per-process sharding."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AllReduce, AutoDist, Trainable
from autodist_tpu.data import DataLoader, shard_batch


def make_runner():
    params = {"w": jnp.zeros((4,), jnp.float32)}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    return AutoDist({}, AllReduce()).build(
        Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1)))


def batches(n):
    r = np.random.RandomState(0)
    return [{"x": r.randn(16, 4).astype(np.float32),
             "y": r.randn(16).astype(np.float32)} for _ in range(n)]


def test_loader_feeds_runner():
    runner = make_runner()
    loader = DataLoader(batches(4), runner.mesh, buffer_size=2)
    losses = [float(np.asarray(runner.step(b)["loss"])) for b in loader]
    assert len(losses) == 4 and all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_loader_matches_direct_steps():
    """Prefetched placement must not change numerics."""
    bs = batches(3)
    r1 = make_runner()
    for b in bs:
        r1.step(b, rng=jax.random.PRNGKey(1))
    r2 = make_runner()
    for b in DataLoader(list(bs), r2.mesh):
        r2.step(b, rng=jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(r1.get_params()["w"]),
                                  np.asarray(r2.get_params()["w"]))


def test_loader_callable_source_and_limit():
    runner = make_runner()
    calls = []

    def src(i):
        calls.append(i)
        return batches(1)[0]

    out = list(DataLoader(src, runner.mesh, num_batches=3))
    assert len(out) == 3 and calls == [0, 1, 2]


def test_loader_scalar_leaves_duplicate():
    runner = make_runner()
    b = dict(batches(1)[0], scale=np.float32(2.0))
    placed = next(iter(DataLoader([b], runner.mesh)))
    from jax.sharding import PartitionSpec as P
    assert placed["scale"].sharding.spec == P()
    assert placed["x"].sharding.spec == P("data")


def test_loader_propagates_source_errors():
    runner = make_runner()

    def bad(i):
        if i == 1:
            raise RuntimeError("boom")
        return batches(1)[0]

    with pytest.raises(RuntimeError, match="boom"):
        list(DataLoader(bad, runner.mesh, num_batches=3))


def test_shard_batch_slices_per_process():
    b = {"x": np.arange(8).reshape(8, 1), "s": np.float32(1.0)}
    got = shard_batch(b, process_index=1, process_count=2)
    np.testing.assert_array_equal(got["x"][:, 0], [4, 5, 6, 7])
    assert got["s"] == np.float32(1.0)
    with pytest.raises(ValueError, match="not divisible"):
        shard_batch({"x": np.zeros((7, 1))}, process_index=0,
                    process_count=2)
