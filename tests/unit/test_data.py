"""Input-pipeline tests: prefetch, feed contract, per-process sharding."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AllReduce, AutoDist, Trainable
from autodist_tpu.data import DataLoader, shard_batch


def make_runner():
    params = {"w": jnp.zeros((4,), jnp.float32)}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    return AutoDist({}, AllReduce()).build(
        Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1)))


def batches(n):
    r = np.random.RandomState(0)
    return [{"x": r.randn(16, 4).astype(np.float32),
             "y": r.randn(16).astype(np.float32)} for _ in range(n)]


def test_loader_feeds_runner():
    runner = make_runner()
    loader = DataLoader(batches(4), runner.mesh, buffer_size=2)
    losses = [float(np.asarray(runner.step(b)["loss"])) for b in loader]
    assert len(losses) == 4 and all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_loader_matches_direct_steps():
    """Prefetched placement must not change numerics."""
    bs = batches(3)
    r1 = make_runner()
    for b in bs:
        r1.step(b, rng=jax.random.PRNGKey(1))
    r2 = make_runner()
    for b in DataLoader(list(bs), r2.mesh):
        r2.step(b, rng=jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(r1.get_params()["w"]),
                                  np.asarray(r2.get_params()["w"]))


def test_loader_callable_source_and_limit():
    runner = make_runner()
    calls = []

    def src(i):
        calls.append(i)
        return batches(1)[0]

    out = list(DataLoader(src, runner.mesh, num_batches=3))
    assert len(out) == 3 and calls == [0, 1, 2]


def test_loader_scalar_leaves_duplicate():
    runner = make_runner()
    b = dict(batches(1)[0], scale=np.float32(2.0))
    placed = next(iter(DataLoader([b], runner.mesh)))
    from jax.sharding import PartitionSpec as P
    assert placed["scale"].sharding.spec == P()
    assert placed["x"].sharding.spec == P("data")


def test_loader_propagates_source_errors():
    runner = make_runner()

    def bad(i):
        if i == 1:
            raise RuntimeError("boom")
        return batches(1)[0]

    with pytest.raises(RuntimeError, match="boom"):
        list(DataLoader(bad, runner.mesh, num_batches=3))


def test_shard_batch_slices_per_process():
    b = {"x": np.arange(8).reshape(8, 1), "s": np.float32(1.0)}
    got = shard_batch(b, process_index=1, process_count=2)
    np.testing.assert_array_equal(got["x"][:, 0], [4, 5, 6, 7])
    assert got["s"] == np.float32(1.0)
    with pytest.raises(ValueError, match="not divisible"):
        shard_batch({"x": np.zeros((7, 1))}, process_index=0,
                    process_count=2)


# ---------------- TokenFile / native data IO ---------------------------- #
@pytest.fixture()
def token_file(tmp_path):
    data = np.arange(1000, dtype=np.int32)
    p = tmp_path / "tokens.bin"
    data.tofile(p)
    return str(p), data


@pytest.mark.parametrize("native", [True, False], ids=["native", "memmap"])
def test_token_file_gather_matches_numpy(token_file, native):
    from autodist_tpu.data import TokenFile

    path, data = token_file
    tf_ = TokenFile(path, np.int32, native=native)
    assert len(tf_) == 1000
    offs = np.array([0, 7, 993], dtype=np.int64)
    got = tf_.gather(offs, 7)
    for row, off in zip(got, offs):
        np.testing.assert_array_equal(row, data[off:off + 7])
    tf_.prefetch(offs, 7)  # must not raise on either path


@pytest.mark.parametrize("native", [True, False], ids=["native", "memmap"])
def test_token_file_bounds(token_file, native):
    from autodist_tpu.data import TokenFile

    path, _ = token_file
    tf_ = TokenFile(path, np.int32, native=native)
    with pytest.raises(IndexError):
        tf_.gather(np.array([995], dtype=np.int64), 7)
    with pytest.raises(IndexError):
        tf_.gather(np.array([-1], dtype=np.int64), 7)


def test_token_file_rejects_misaligned(tmp_path):
    from autodist_tpu.data import TokenFile

    p = tmp_path / "odd.bin"
    p.write_bytes(b"\x01\x02\x03")  # 3 bytes: not a multiple of 4
    with pytest.raises(OSError):
        TokenFile(str(p), np.int32, native=True)


def test_lm_window_loader_shifted_labels(token_file):
    from autodist_tpu.data import lm_window_loader

    path, data = token_file
    source = lm_window_loader(path, batch_size=4, seq_len=16, seed=0)
    b = source(0)
    assert b["x"].shape == (4, 16) and b["y"].shape == (4, 16)
    # y is x shifted by one: both are windows of consecutive integers here
    np.testing.assert_array_equal(b["y"][:, :-1], b["x"][:, 1:])
    np.testing.assert_array_equal(b["y"][:, 0], b["x"][:, 0] + 1)
    # deterministic under seed
    b2 = lm_window_loader(path, batch_size=4, seq_len=16, seed=0)(0)
    np.testing.assert_array_equal(b["x"], b2["x"])


def test_lm_window_loader_through_device_loader(token_file):
    from autodist_tpu.data import lm_window_loader

    path, _ = token_file
    runner = make_runner()
    source = lm_window_loader(path, batch_size=8, seq_len=8, seed=1)
    seen = 0
    for batch in DataLoader(source, runner.mesh, num_batches=3):
        assert batch["x"].shape == (8, 8)
        seen += 1
    assert seen == 3


def test_lm_window_loader_resume_continues_stream(token_file):
    """source(step) is a pure function of (seed, step): a resumed job
    shifting the source by the restored step (fit's resume path) gets
    exactly the windows the uninterrupted run would have seen."""
    from autodist_tpu.data import lm_window_loader

    path, _ = token_file
    full = lm_window_loader(path, batch_size=4, seq_len=16, seed=7)
    uninterrupted = [full(i) for i in range(5)]

    resumed = lm_window_loader(path, batch_size=4, seq_len=16, seed=7)
    for i in range(3, 5):  # "restart" at step 3
        np.testing.assert_array_equal(resumed(i)["x"],
                                      uninterrupted[i]["x"])
    # distinct steps produce distinct windows (not a constant stream)
    assert not np.array_equal(uninterrupted[0]["x"], uninterrupted[1]["x"])
