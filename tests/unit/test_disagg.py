"""Disaggregated prefill/decode serving goldens (ISSUE 17).

The bar: a request decodes the exact same token stream whether it runs
through a colocated ``ContinuousBatcher`` or crosses the
prefill→decode pool boundary through the compiled KV handoff — with
zero leaked blocks in EITHER pool, the handoff program ADT110-clean
(no gather above the pool-shard budget, no host transfer), every
transfer a schema-gated ``kind="handoff"`` record naming its paired
replicas, and the pool-split election pinned in both traffic
directions (prefill-heavy elects prefill replicas, decode-heavy
decode).
"""
import json
import os
import sys

import jax
import jax.numpy as jnp
import optax
import pytest

from autodist_tpu import telemetry
from autodist_tpu.analysis import lint_disagg, lint_handoff
from autodist_tpu.models.pipeline_lm import make_pipeline_lm_trainable
from autodist_tpu.models.transformer import TransformerConfig
from autodist_tpu.resource import ResourceSpec
from autodist_tpu.serving import ContinuousBatcher, OverloadedError
from autodist_tpu.serving.disagg import (DisaggConfig, DisaggServer,
                                         elect_pool_split)
from autodist_tpu.serving.remote import tiny_engine_factory

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, "tools"))

# Short ragged prompts: block-tail adoption (a partial last block
# crosses the handoff), slot reuse (6 requests through 2-slot pools),
# and both decode engines participating.
MIX = [([1, 2, 3], 8), ([4, 5], 8), ([6], 8), ([7, 8, 9], 8),
       ([3, 1], 8), ([2, 9, 4], 8)]


def run_colocated(reqs):
    """The golden: the same engine recipe, prefill+decode colocated."""
    b = ContinuousBatcher(tiny_engine_factory())
    rids = [b.submit(p, max_new_tokens=m, rid=f"r{i}", seed=i)
            for i, (p, m) in enumerate(reqs)]
    done = b.run()
    return {rid: done[rid].tokens for rid in rids}


def test_disagg_parity_zero_leak_and_handoff_records(tmp_path):
    import telemetry_report as tr

    golden = run_colocated(MIX)
    telemetry.configure(out_dir=str(tmp_path))
    srv = DisaggServer(tiny_engine_factory, prefill_replicas=1,
                       decode_replicas=2)
    for i, (p, m) in enumerate(MIX):
        srv.submit(p, max_new_tokens=m, rid=f"r{i}", seed=i)
    done = srv.run()
    # token-for-token: the pool boundary is invisible to the client
    for rid, want in golden.items():
        assert done[rid].tokens == want, rid
    # the handoff program compiled clean under ADT110/ADT104
    assert srv.last_handoff_report is not None
    assert srv.last_handoff_report.ok, \
        srv.last_handoff_report.render("handoff lint")
    # zero residency in EVERY pool once drained
    for name, (free, used, total) in srv.block_accounting().items():
        assert used == 0 and free == total, (name, free, used, total)
    # both decode engines actually served (the least-loaded pick)
    assert {done[r].decode_replica for r in golden} \
        == {"decode-0", "decode-1"}
    telemetry.flush()
    # one schema-gated handoff record per request, replicas paired
    assert tr.check_schema(str(tmp_path)) == []
    with open(tmp_path / "metrics.jsonl") as f:
        recs = [json.loads(line) for line in f if line.strip()]
    handoffs = [r for r in recs if r.get("kind") == "handoff"]
    assert len(handoffs) == len(MIX)
    for r in handoffs:
        assert r["prefill_replica"] == "prefill-0"
        assert r["decode_replica"] in ("decode-0", "decode-1")
        assert r["route"] == "ici"
        assert 0 < r["per_device_gather_elems"] <= r["budget_elems"]
    rendered = tr.render(str(tmp_path))
    assert "## disaggregated serving" in rendered
    assert "prefill-0 → decode-0" in rendered


def test_submit_mirrors_batcher_validation():
    srv = DisaggServer(tiny_engine_factory, prefill_replicas=1,
                       decode_replicas=1, max_queue=1)
    with pytest.raises(ValueError, match="empty"):
        srv.submit([], max_new_tokens=4)
    with pytest.raises(ValueError, match="exceeds"):
        srv.submit(list(range(1, 30)), max_new_tokens=4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        srv.submit([1], max_new_tokens=0)
    srv.submit([1, 2], max_new_tokens=4)
    with pytest.raises(OverloadedError):
        srv.submit([3, 4], max_new_tokens=4)
    srv.run()


def test_pool_shape_comes_from_exactly_one_source():
    cfg = DisaggConfig(prefill_replicas=1, decode_replicas=1)
    with pytest.raises(ValueError, match="config"):
        DisaggServer(tiny_engine_factory, prefill_replicas=1,
                     decode_replicas=1, config=cfg)
    # an explicit empty pool is rejected, not silently defaulted to 1
    with pytest.raises(ValueError, match="replica"):
        DisaggServer(tiny_engine_factory, prefill_replicas=0,
                     decode_replicas=1)
    # no shape at all falls back to the smallest disaggregated fleet
    srv = DisaggServer(tiny_engine_factory)
    assert srv.config.prefill_replicas == 1
    assert srv.config.decode_replicas == 1
    srv = DisaggServer(tiny_engine_factory, config=cfg)
    assert srv.describe()["prefill_replicas"] == 1


# --------------------------------------------------------------------- #
# the election: pinned in both traffic directions
# --------------------------------------------------------------------- #
def _trainable(max_len=512):
    cfg = TransformerConfig(vocab_size=33, hidden_size=16, num_layers=2,
                            num_heads=2, mlp_dim=32, max_len=max_len,
                            dtype=jnp.float32, dropout_rate=0.0,
                            attention_dropout_rate=0.0)
    return make_pipeline_lm_trainable(cfg, optax.sgd(0.1),
                                      jax.random.PRNGKey(0))


def test_election_pinned_both_traffic_directions():
    """rank_serving(objective="disagg"): a prompt-dominated mix elects
    a prefill-leaning split, a decode-dominated mix a decode-leaning
    one — the bottleneck-stage objective moves replicas toward the
    stage the traffic loads."""
    tr = _trainable()
    spec = ResourceSpec({"topology": {"num_devices": 8,
                                      "num_slices": 1}})
    heavy_prompt, _ = elect_pool_split(
        tr, spec, batch_slots=2, max_len=512,
        mean_request_len=500, mean_prompt_len=480)
    heavy_decode, _ = elect_pool_split(
        tr, spec, batch_slots=2, max_len=512,
        mean_request_len=500, mean_prompt_len=20)
    assert heavy_prompt.prefill_replicas > heavy_decode.prefill_replicas
    assert heavy_decode.decode_replicas > heavy_prompt.decode_replicas
    # the elected split always fits the device budget it was given
    for cand in (heavy_prompt, heavy_decode):
        assert (cand.prefill_replicas + cand.decode_replicas) \
            * cand.tensor_parallel <= 8
        assert lint_disagg(cand, spec).ok


def test_infeasible_split_is_rejected_not_built():
    spec = ResourceSpec({"topology": {"num_devices": 2,
                                      "num_slices": 1}})
    report = lint_disagg(DisaggConfig(prefill_replicas=2,
                                      decode_replicas=2), spec)
    assert not report.ok
    assert any(d.code == "ADT089" for d in report.errors)
    with pytest.raises(ValueError, match="ADT089"):
        DisaggServer(tiny_engine_factory,
                     config=DisaggConfig(prefill_replicas=2,
                                         decode_replicas=2),
                     resource_spec=spec)


def test_cross_slice_tp_split_is_rejected():
    spec = ResourceSpec({"topology": {"num_devices": 8,
                                      "num_slices": 4}})
    report = lint_disagg(DisaggConfig(prefill_replicas=1,
                                      decode_replicas=1,
                                      tensor_parallel=4), spec)
    assert not report.ok
    assert any("ICI" in d.message or "slice" in d.message
               for d in report.errors)


def test_handoff_plan_budget_gate():
    """lint_handoff: a plan whose per-device gather exceeds the pool
    shard budget is an ADT072 error (the full-pool staging the
    compiled route exists to prevent); a prefix-block plan is clean."""
    plan = {"per_device_gather_elems": 160, "budget_elems": 1600,
            "blocks": 1, "prefill_replica": "prefill-0",
            "decode_replica": "decode-0"}
    assert lint_handoff(plan).ok
    bloated = dict(plan, per_device_gather_elems=3200)
    report = lint_handoff(bloated)
    assert not report.ok
    assert any(d.code == "ADT072" for d in report.errors)
    # an explicit budget overrides the plan's own
    assert not lint_handoff(plan, budget_elems=100).ok
