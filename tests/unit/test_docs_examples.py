"""Docs stay honest: the tutorial code actually runs.

The reference's tutorials bit-rotted against its own API more than once;
these tests execute the documented snippets (the custom-builder example
from ``docs/usage/tutorials/customize-strategy.md`` and the quickstart
flow) against the live API so a signature change breaks CI, not a user.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist, Trainable
from autodist_tpu.strategy.base import StrategyBuilder
from autodist_tpu.strategy.ir import (AllReduceSynchronizer, NodeConfig,
                                      PartitionerConfig, PSSynchronizer,
                                      Strategy)


class BigVarsSharded(StrategyBuilder):
    """Verbatim from docs/usage/tutorials/customize-strategy.md."""

    def __init__(self, threshold_bytes=1 << 20):
        self.threshold = threshold_bytes

    def build(self, trainable, resource_spec):
        n = self.num_replicas(resource_spec)
        nodes = []
        for info in trainable.var_infos():
            if info.byte_size > self.threshold and info.shape:
                node = NodeConfig(
                    var_name=info.name,
                    synchronizer=PSSynchronizer(),
                    partitioner=PartitionerConfig(
                        partition_str=",".join(
                            [str(n)] + ["1"] * (len(info.shape) - 1))))
            else:
                node = NodeConfig(var_name=info.name,
                                  synchronizer=AllReduceSynchronizer())
            nodes.append(node)
        return Strategy(node_configs=nodes,
                        graph_config=self._graph_config(resource_spec))


def _trainable():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    params = {
        # > 1 MB: 512x600 fp32 = 1.2 MB -> sharded branch
        "big": jax.random.normal(k1, (512, 600), jnp.float32) * 0.02,
        "small": jax.random.normal(k2, (8,), jnp.float32),
    }

    def loss_fn(p, batch):
        pred = batch["x"] @ p["big"]
        return jnp.mean((pred - batch["y"]) ** 2) + jnp.sum(p["small"] ** 2)

    return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1))


def test_custom_builder_from_docs_trains():
    trainable = _trainable()
    ad = AutoDist({"topology": {"num_devices": 8}}, BigVarsSharded())
    strategy = ad.strategy_builder.build(trainable, ad.resource_spec)
    node = strategy.node_config_for("big")
    assert node.synchronizer.kind == "ps"
    assert node.partitioner.partition_str == "8,1"
    assert strategy.node_config_for("small").synchronizer.kind == "allreduce"

    runner = ad.build(trainable)
    batch = {"x": np.ones((16, 512), np.float32),
             "y": np.zeros((16, 600), np.float32)}
    m0 = runner.step(batch)
    m1 = runner.step(batch)
    assert float(m1["loss"]) < float(m0["loss"])


def test_quickstart_flow_runs():
    trainable = _trainable()
    runner = AutoDist({"topology": {"num_devices": 8}}).build(trainable)
    batch = {"x": np.ones((8, 512), np.float32),
             "y": np.zeros((8, 600), np.float32)}
    metrics = runner.step(batch)
    assert "loss" in metrics
    params = runner.get_params()
    assert params["big"].shape == (512, 600)
