"""Docs stay honest: the tutorial code actually runs.

The reference's tutorials bit-rotted against its own API more than once;
these tests execute the documented snippets against the live API so a
signature change breaks CI, not a user.  The custom-builder class is
*extracted from the markdown itself* (``docs/usage/tutorials/
customize-strategy.md``), so editing the doc re-tests the doc.
"""
import pathlib
import re

import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist, Trainable

DOCS = pathlib.Path(__file__).resolve().parents[2] / "docs"


import pytest

pytestmark = pytest.mark.slow

def _exec_doc_builder():
    """Exec the tutorial's code blocks — imports included — in order,
    up to and including the one defining ``BigVarsSharded``, so a rename
    anywhere in the documented preamble breaks this test too."""
    text = (DOCS / "usage/tutorials/customize-strategy.md").read_text()
    ns = {}
    for block in re.findall(r"```python\n(.*?)```", text, re.DOTALL):
        exec(block, ns)
        if "BigVarsSharded" in ns:
            return ns["BigVarsSharded"]
    raise AssertionError("no python block defines BigVarsSharded")


def _trainable():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    params = {
        # > 1 MB: 512x600 fp32 = 1.2 MB -> sharded branch
        "big": jax.random.normal(k1, (512, 600), jnp.float32) * 0.02,
        "small": jax.random.normal(k2, (8,), jnp.float32),
    }

    def loss_fn(p, batch):
        pred = batch["x"] @ p["big"]
        return jnp.mean((pred - batch["y"]) ** 2) + jnp.sum(p["small"] ** 2)

    return Trainable.from_loss_fn(loss_fn, params, optax.sgd(0.1))


def test_custom_builder_from_docs_trains():
    BigVarsSharded = _exec_doc_builder()
    trainable = _trainable()
    ad = AutoDist({"topology": {"num_devices": 8}}, BigVarsSharded())
    strategy = ad.strategy_builder.build(trainable, ad.resource_spec)
    node = strategy.node_config_for("big")
    assert node.synchronizer.kind == "ps"
    assert node.partitioner.partition_str == "8,1"
    assert strategy.node_config_for("small").synchronizer.kind == "allreduce"

    runner = ad.build(trainable)
    batch = {"x": np.ones((16, 512), np.float32),
             "y": np.zeros((16, 600), np.float32)}
    m0 = runner.step(batch)
    m1 = runner.step(batch)
    assert float(m1["loss"]) < float(m0["loss"])


def test_quickstart_flow_runs():
    trainable = _trainable()
    runner = AutoDist({"topology": {"num_devices": 8}}).build(trainable)
    batch = {"x": np.ones((8, 512), np.float32),
             "y": np.zeros((8, 600), np.float32)}
    metrics = runner.step(batch)
    assert "loss" in metrics
    params = runner.get_params()
    assert params["big"].shape == (512, 600)


def test_documented_public_api_imports():
    """Every entry point the migration guide and tutorials name must be
    importable from where the docs say it lives."""
    from autodist_tpu import (AllReduce, AutoDist, AutoStrategy,  # noqa: F401
                              DistributedRunner, FSDPSharded,
                              GradAccumulation, Parallax, PartitionedAR,
                              PartitionedPS, PS, PSLoadBalancing,
                              RandomAxisPartitionAR, ResourceSpec, Sharded,
                              Strategy, TensorParallel, Trainable,
                              UnevenPartitionedPS, VarInfo, ZeRO, fit)
    from autodist_tpu.checkpoint import (Saver, export_model,  # noqa: F401
                                         load_exported)
    from autodist_tpu.data import (DataLoader, TokenFile,  # noqa: F401
                                   lm_window_loader, shard_batch)
    from autodist_tpu.ops import (flash_attention,  # noqa: F401
                                  flash_attention_with_lse,
                                  make_attention_fn)
    from autodist_tpu.parallel.ring_attention import (  # noqa: F401
        make_ring_attention_fn, make_ring_flash_attention_fn,
        ring_flash_attention, ring_self_attention)
    from autodist_tpu.parallel.sequence import (  # noqa: F401
        global_positions, lower_sequence_parallel)
    from autodist_tpu.runtime import (Cluster, Coordinator,  # noqa: F401
                                      make_global_batch)
